"""ctypes bindings to the native host-data-path library (native/stereodata.cpp).

The shared library is built on demand with the system compiler the first time
it is needed and cached next to the sources; absence of a toolchain degrades
gracefully to the numpy implementations (``available()`` returns False). The
decoder's output is bit-identical to :func:`frame_utils.read_pfm` (tested in
tests/test_native.py).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libstereodata.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    src = os.path.join(_NATIVE_DIR, "stereodata.cpp")
    if not os.path.isfile(src):
        return False
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                       capture_output=True, timeout=120)
        return os.path.isfile(_LIB_PATH)
    except (OSError, subprocess.SubprocessError) as e:
        logger.info("native data-path build failed (%s); using numpy path", e)
        return False


def _bind(lib: ctypes.CDLL) -> None:
    """Declare every symbol's signature; AttributeError = stale library."""
    lib.pfm_probe.restype = ctypes.c_int
    lib.pfm_probe.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64)]
    lib.pfm_decode.restype = ctypes.c_int
    lib.pfm_decode.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int32, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_float)]
    lib.collate_u8_to_f32.restype = None
    lib.collate_u8_to_f32.argtypes = [
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)), ctypes.c_int32,
        ctypes.c_int64, ctypes.POINTER(ctypes.c_float)]
    lib.png16_probe.restype = ctypes.c_int
    lib.png16_probe.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32)]
    lib.png16_decode.restype = ctypes.c_int
    lib.png16_decode.argtypes = [
        ctypes.c_char_p, ctypes.c_int32, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_uint16)]


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        src = os.path.join(_NATIVE_DIR, "stereodata.cpp")
        stale = (os.path.isfile(_LIB_PATH) and os.path.isfile(src)
                 and os.path.getmtime(src) > os.path.getmtime(_LIB_PATH))
        # Rebuild stale/missing libraries BEFORE the first dlopen: reloading
        # the same path after a rebuild would return the cached stale handle
        # (dlopen caches by path within a process).
        if (not os.path.isfile(_LIB_PATH) or stale) and not _build():
            if not os.path.isfile(_LIB_PATH):
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
            _bind(lib)
        except (OSError, AttributeError) as e:
            # AttributeError = a .so missing expected symbols (built from a
            # different source revision with equal mtimes) — degrade to the
            # numpy/cv2 paths rather than crash every data-layer caller.
            logger.info("native data-path load failed (%s); "
                        "using numpy path", e)
            return None
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def read_pfm(path: str) -> Optional[np.ndarray]:
    """Native PFM decode; None when the library is unavailable (caller falls
    back to the numpy codec) — raises on malformed files like the numpy path."""
    lib = _load()
    if lib is None:
        return None
    w = ctypes.c_int32()
    h = ctypes.c_int32()
    c = ctypes.c_int32()
    le = ctypes.c_int32()
    off = ctypes.c_int64()
    rc = lib.pfm_probe(path.encode(), ctypes.byref(w), ctypes.byref(h),
                       ctypes.byref(c), ctypes.byref(le), ctypes.byref(off))
    if rc != 0:
        raise ValueError(f"{path}: not a valid PFM file (native rc={rc})")
    out = np.empty((h.value, w.value, c.value) if c.value == 3
                   else (h.value, w.value), np.float32)
    rc = lib.pfm_decode(path.encode(), off.value, w.value, h.value, c.value,
                        le.value,
                        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
    if rc != 0:
        raise ValueError(f"{path}: truncated/unreadable PFM (native rc={rc})")
    return out


def read_png16(path: str) -> Optional[np.ndarray]:
    """Native 16-bit greyscale PNG decode (the KITTI disparity codec,
    reference frame_utils.py:124-127) -> (H, W) uint16.

    Returns None when the library is unavailable, the file is not a
    supported 16-bit grey non-interlaced PNG, OR the decode itself fails
    (truncated IDAT, CRC-corrupt or nonstandard zlib stream) — callers fall
    back to cv2, which tolerates more minor nonconformance than this
    strict decoder; if the file is truly corrupt cv2 raises there.
    """
    lib = _load()
    if lib is None:
        return None
    w = ctypes.c_int32()
    h = ctypes.c_int32()
    if lib.png16_probe(path.encode(), ctypes.byref(w), ctypes.byref(h)) != 0:
        return None  # unsupported flavor: defer to the cv2 path
    out = np.empty((h.value, w.value), np.uint16)
    rc = lib.png16_decode(path.encode(), w.value, h.value,
                          out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)))
    if rc != 0:
        logger.warning("%s: native 16-bit PNG decode failed (rc=%d); "
                       "falling back to cv2", path, rc)
        return None
    return out


def collate_u8(images) -> Optional[np.ndarray]:
    """Stack same-shaped uint8 arrays into one float32 batch in a single
    native pass; None when unavailable."""
    lib = _load()
    if lib is None:
        return None
    images = [np.ascontiguousarray(im, dtype=np.uint8) for im in images]
    shape = images[0].shape
    if any(im.shape != shape for im in images):
        raise ValueError("collate_u8 requires same-shaped samples")
    n = len(images)
    elems = int(np.prod(shape))
    out = np.empty((n,) + shape, np.float32)
    ptrs = (ctypes.POINTER(ctypes.c_uint8) * n)(
        *[im.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)) for im in images])
    lib.collate_u8_to_f32(ptrs, n, elems,
                          out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
    return out
