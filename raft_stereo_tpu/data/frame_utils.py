"""Image / disparity / flow codecs (capability of core/utils/frame_utils.py).

All readers return numpy arrays (images uint8 HWC RGB; disparities float32 HW)
— no PIL objects cross module boundaries. Dataset-specific disparity decoders
are exposed through a small registry (`DISPARITY_READERS`) so dataset classes
reference them by name.

Format semantics (with the reference behavior each reproduces):

* PFM: Pf/PF header, w h, negative scale = little-endian, rows bottom-up
  (frame_utils.py:34-69 read, :71-81 write).
* Middlebury .flo: magic 202021.25 float, then w, h int32, then h*w*2 float32
  (frame_utils.py:13-32).
* KITTI disparity PNG: 16-bit, value/256.0, 0 = invalid (frame_utils.py:124-127).
* KITTI flow PNG: 16-bit BGR, (value-2^15)/64, third channel validity
  (frame_utils.py:117-122, write :170-174).
* Sintel stereo disparity: 8-bit RGB packed d = R*4 + G/64 + B/16384, paired
  occlusion mask where 0 = valid (frame_utils.py:130-136).
* FallingThings: uint16 depth PNG + `_camera_settings.json` fx; disparity =
  fx * 6.0 * 100 / depth (frame_utils.py:139-146).
* TartanAir: .npy depth; disparity = 80 / depth (frame_utils.py:149-153).
* Middlebury GT: disp0GT.pfm + mask0nocc.png==255 nocc mask; disp0.pfm with
  valid = disp < 1e3 (frame_utils.py:156-168).
"""

from __future__ import annotations

import json
import os
import re
from typing import Callable, Dict, Tuple

import numpy as np

FLO_MAGIC = 202021.25


# --------------------------------------------------------------------------- images

def read_image(path: str) -> np.ndarray:
    """Read an image file as uint8 (H, W, C) RGB (or (H, W) for grayscale)."""
    from PIL import Image

    with Image.open(path) as im:
        return np.asarray(im)


# --------------------------------------------------------------------------- PFM

def read_pfm(path: str) -> np.ndarray:
    """Read a PFM file -> float32 (H, W) or (H, W, 3), top-down row order.

    Uses the native mmap decoder (data/native.py, bit-identical output) when
    the shared library is available; this numpy path is the fallback and the
    reference implementation.
    """
    from raft_stereo_tpu.data import native

    if native.available():
        out = native.read_pfm(path)
        if out is not None:
            return out
    return _read_pfm_numpy(path)


def _read_pfm_numpy(path: str) -> np.ndarray:
    with open(path, "rb") as f:
        header = f.readline().rstrip()
        if header == b"PF":
            channels = 3
        elif header == b"Pf":
            channels = 1
        else:
            raise ValueError(f"{path}: not a PFM file (header {header!r})")

        dims = f.readline()
        m = re.match(rb"^(\d+)\s+(\d+)\s*$", dims)
        if not m:
            raise ValueError(f"{path}: malformed PFM dims {dims!r}")
        width, height = int(m.group(1)), int(m.group(2))

        scale = float(f.readline().rstrip())
        endian = "<" if scale < 0 else ">"

        data = np.fromfile(f, endian + "f4", count=height * width * channels)
    if data.size != height * width * channels:
        raise ValueError(f"{path}: truncated PFM payload")
    shape = (height, width, 3) if channels == 3 else (height, width)
    # PFM stores rows bottom-to-top.
    return np.flipud(data.reshape(shape)).astype(np.float32)


def write_pfm(path: str, array: np.ndarray) -> None:
    """Write a single-channel float32 PFM (little-endian, bottom-up rows)."""
    if array.ndim != 2:
        raise ValueError("write_pfm supports single-channel (H, W) arrays")
    h, w = array.shape
    with open(path, "wb") as f:
        f.write(b"Pf\n")
        f.write(f"{w} {h}\n".encode())
        f.write(b"-1\n")
        f.write(np.flipud(array).astype("<f4").tobytes())


# --------------------------------------------------------------------------- .flo

def read_flo(path: str) -> np.ndarray:
    """Read Middlebury .flo optical flow -> float32 (H, W, 2)."""
    with open(path, "rb") as f:
        magic = np.fromfile(f, np.float32, count=1)
        if magic.size != 1 or magic[0] != np.float32(FLO_MAGIC):
            raise ValueError(f"{path}: bad .flo magic {magic!r}")
        w = int(np.fromfile(f, np.int32, count=1)[0])
        h = int(np.fromfile(f, np.int32, count=1)[0])
        data = np.fromfile(f, np.float32, count=2 * w * h)
    return data.reshape(h, w, 2)


def write_flo(path: str, flow: np.ndarray) -> None:
    flow = np.asarray(flow, np.float32)
    h, w, c = flow.shape
    if c != 2:
        raise ValueError("flow must be (H, W, 2)")
    with open(path, "wb") as f:
        np.float32(FLO_MAGIC).tofile(f)
        np.int32(w).tofile(f)
        np.int32(h).tofile(f)
        flow.tofile(f)


# --------------------------------------------------------------------------- KITTI PNGs

def _read_png_16bit(path: str) -> np.ndarray:
    # native single-pass decoder first (zlib + unfilter in C++,
    # native/stereodata.cpp); returns None for unsupported PNG flavors
    from raft_stereo_tpu.data import native

    img = native.read_png16(path)
    if img is not None:
        return img
    import cv2

    img = cv2.imread(path, cv2.IMREAD_ANYDEPTH | cv2.IMREAD_UNCHANGED)
    if img is None:
        raise FileNotFoundError(path)
    return img


def read_disp_kitti(path: str) -> Tuple[np.ndarray, np.ndarray]:
    disp = _read_png_16bit(path).astype(np.float32) / 256.0
    return disp, disp > 0.0


def read_disp_eth3d(path: str) -> Tuple[np.ndarray, np.ndarray]:
    """ETH3D GT: the reference reads disp0GT.pfm through plain ``read_gen``
    (stereo_datasets.py:188-189 passes no reader), so validity is the generic
    dense threshold ``disp < 512`` — the on-disk nocc mask is never consulted
    (unlike Middlebury). Oracle-pinned in tests/test_eval_oracle.py."""
    disp = read_pfm(path)
    if disp.ndim == 3:
        disp = disp[..., 0]
    return disp, disp < 512.0


def read_flow_kitti(path: str) -> Tuple[np.ndarray, np.ndarray]:
    import cv2

    raw = cv2.imread(path, cv2.IMREAD_ANYDEPTH | cv2.IMREAD_COLOR)
    if raw is None:
        raise FileNotFoundError(path)
    raw = raw[:, :, ::-1].astype(np.float32)  # BGR -> RGB channel order
    flow = (raw[:, :, :2] - 2.0 ** 15) / 64.0
    valid = raw[:, :, 2]
    return flow, valid


def write_flow_kitti(path: str, flow: np.ndarray) -> None:
    import cv2

    enc = 64.0 * np.asarray(flow, np.float64) + 2 ** 15
    valid = np.ones(enc.shape[:2] + (1,))
    out = np.concatenate([enc, valid], axis=-1).astype(np.uint16)
    cv2.imwrite(path, out[..., ::-1])


# ----------------------------------------------------------------- dataset decoders

def read_disp_sintel(path: str) -> Tuple[np.ndarray, np.ndarray]:
    rgb = read_image(path).astype(np.float32)
    disp = rgb[..., 0] * 4.0 + rgb[..., 1] / 64.0 + rgb[..., 2] / 16384.0
    occ_path = path.replace("disparities", "occlusions")
    occlusion = read_image(occ_path)
    valid = (occlusion == 0) & (disp > 0)
    return disp, valid


def read_disp_falling_things(path: str) -> Tuple[np.ndarray, np.ndarray]:
    depth = read_image(path).astype(np.float32)
    settings = os.path.join(os.path.dirname(path), "_camera_settings.json")
    with open(settings) as f:
        intrinsics = json.load(f)
    fx = intrinsics["camera_settings"][0]["intrinsic_settings"]["fx"]
    with np.errstate(divide="ignore"):
        disp = (fx * 6.0 * 100.0) / depth
    return disp, disp > 0


def read_disp_tartanair(path: str) -> Tuple[np.ndarray, np.ndarray]:
    depth = np.load(path)
    with np.errstate(divide="ignore"):
        disp = 80.0 / depth.astype(np.float32)
    return disp, disp > 0


def read_disp_middlebury(path: str) -> Tuple[np.ndarray, np.ndarray]:
    name = os.path.basename(path)
    disp = read_pfm(path)
    if disp.ndim != 2:
        raise ValueError(f"{path}: expected single-channel disparity")
    if name == "disp0GT.pfm":
        nocc_path = path.replace("disp0GT.pfm", "mask0nocc.png")
        valid = read_image(nocc_path) == 255
        return disp, valid
    return disp, disp < 1e3


def read_disp_pfm(path: str) -> Tuple[np.ndarray, np.ndarray]:
    """Generic PFM disparity (SceneFlow): finite values are valid."""
    disp = read_pfm(path)
    if disp.ndim == 3:
        disp = disp[..., 0]
    return disp, np.isfinite(disp)


DISPARITY_READERS: Dict[str, Callable[[str], Tuple[np.ndarray, np.ndarray]]] = {
    "pfm": read_disp_pfm,
    "kitti": read_disp_kitti,
    "sintel": read_disp_sintel,
    "falling_things": read_disp_falling_things,
    "tartanair": read_disp_tartanair,
    "middlebury": read_disp_middlebury,
}


def read_gen(path: str) -> np.ndarray:
    """Extension-dispatched reader (frame_utils.py:177-191): images, .flo, .pfm, .npy."""
    ext = os.path.splitext(path)[-1].lower()
    if ext in (".png", ".jpeg", ".jpg", ".ppm"):
        return read_image(path)
    if ext in (".bin", ".raw", ".npy"):
        return np.load(path)
    if ext == ".flo":
        return read_flo(path)
    if ext == ".pfm":
        data = read_pfm(path)
        return data if data.ndim == 2 else data[:, :, :-1]
    raise ValueError(f"unsupported extension {ext!r} for {path}")
