"""Host-side data layer: codecs, augmentation, datasets, loader.

Everything here runs on CPU in numpy; arrays cross to device once per step as
a single batched transfer (vs. the reference's per-tensor ``.cuda()`` copies,
train_stereo.py:163).
"""

from raft_stereo_tpu.data import frame_utils
from raft_stereo_tpu.data.datasets import (
    ETH3D,
    KITTI,
    FallingThings,
    Middlebury,
    SceneFlow,
    SintelStereo,
    StereoDataset,
    TartanAir,
    fetch_dataloader,
)

__all__ = [
    "frame_utils",
    "StereoDataset",
    "SceneFlow",
    "ETH3D",
    "SintelStereo",
    "FallingThings",
    "TartanAir",
    "KITTI",
    "Middlebury",
    "fetch_dataloader",
]
