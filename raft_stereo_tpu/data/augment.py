"""Photometric + spatial augmentation (capability of core/utils/augmentor.py).

Same augmentation surface as the reference's ``FlowAugmentor`` /
``SparseFlowAugmentor`` but re-designed for a deterministic host pipeline:

* every random draw comes from an explicit ``np.random.Generator`` threaded
  through the call (the reference mixes ``random``, ``np.random`` and torch
  RNG global state, augmentor.py:53-54,86,102);
* photometric jitter (brightness/contrast/saturation/hue/gamma) is implemented
  directly in numpy/cv2 instead of torchvision ``ColorJitter``
  (augmentor.py:78,200) — factor ranges match torchvision's conventions;
* output crops are always exactly ``crop_size``: static shapes are what keep
  XLA from recompiling per step.

Behavioral spec preserved from the reference:
  dense (FlowAugmentor, augmentor.py:60-182): asymmetric color prob 0.2;
  eraser prob 0.5 painting 1-2 mean-color rectangles (50-100 px) into img2;
  scale = 2**U(min_scale, max_scale) with 0.8-prob per-axis stretch
  2**U(-0.2, 0.2), clamped so the scaled image covers crop+8; h-flip ('hf'),
  stereo-swap flip ('h'), v-flip ('v', prob 0.1); optional yjitter crop with
  the right image offset y±2 (imperfect rectification).
  sparse (SparseFlowAugmentor, augmentor.py:184-317): always-symmetric color,
  spatial prob 0.8, no stretch, scatter-based sparse flow-map resize, and a
  margin-biased crop (y +20 / x ±50, clipped).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

import cv2

cv2.setNumThreads(0)
cv2.ocl.setUseOpenCL(False)


# ------------------------------------------------------------------ photometric

def _blend(a: np.ndarray, b, factor: float) -> np.ndarray:
    """``clip(f*a + (1-f)*b)`` with minimal temporaries; ``b`` may be a
    scalar or a broadcastable array."""
    out = np.multiply(a, np.float32(factor), dtype=np.float32)
    if isinstance(b, np.ndarray):
        out += (1.0 - factor) * b
    elif b:
        out += np.float32((1.0 - factor) * b)
    return np.clip(out, 0.0, 255.0, out=out)


def _grayscale(img: np.ndarray) -> np.ndarray:
    # ITU-R 601-2 luma, matching PIL's L conversion used by ColorJitter.
    return img @ np.array([0.299, 0.587, 0.114], np.float32)


def adjust_brightness(img: np.ndarray, factor: float) -> np.ndarray:
    return _blend(img, 0.0, factor)


def adjust_contrast(img: np.ndarray, factor: float) -> np.ndarray:
    mean = float(_grayscale(img).mean())
    return _blend(img, mean, factor)


def adjust_saturation(img: np.ndarray, factor: float) -> np.ndarray:
    gray = _grayscale(img)[..., None]
    return _blend(img, gray, factor)


def adjust_hue(img: np.ndarray, shift: float) -> np.ndarray:
    """Shift hue by ``shift`` (fraction of a full turn, in [-0.5, 0.5])."""
    hsv = cv2.cvtColor(img.astype(np.float32) / 255.0, cv2.COLOR_RGB2HSV)
    hsv[..., 0] = (hsv[..., 0] + shift * 360.0) % 360.0
    return cv2.cvtColor(hsv, cv2.COLOR_HSV2RGB) * 255.0


def adjust_gamma(img: np.ndarray, gamma: float, gain: float = 1.0) -> np.ndarray:
    if gamma == 1.0:
        out = np.multiply(img, np.float32(gain), dtype=np.float32)
        return np.clip(out, 0.0, 255.0, out=out)
    out = np.multiply(img, np.float32(1.0 / 255.0), dtype=np.float32)
    np.power(out, np.float32(gamma), out=out)
    out *= np.float32(255.0 * gain)
    return np.clip(out, 0.0, 255.0, out=out)


class PhotometricAugment:
    """ColorJitter-equivalent: random factors, random op order, then gamma.

    ``brightness``/``contrast`` give factor ranges [max(0,1-x), 1+x];
    ``saturation`` is an explicit (lo, hi) range; ``hue`` a turn fraction
    drawn from [-hue, hue]; ``gamma`` is (gamma_min, gamma_max, gain_min,
    gain_max) as in the reference's AdjustGamma (augmentor.py:47-55).
    """

    def __init__(self, brightness: float = 0.4, contrast: float = 0.4,
                 saturation: Tuple[float, float] = (0.6, 1.4),
                 hue: float = 0.5 / 3.14,
                 gamma: Sequence[float] = (1, 1, 1, 1)):
        self.brightness = (max(0.0, 1.0 - brightness), 1.0 + brightness)
        self.contrast = (max(0.0, 1.0 - contrast), 1.0 + contrast)
        self.saturation = tuple(saturation)
        self.hue = hue
        self.gamma = tuple(gamma)

    def __call__(self, img: np.ndarray, rng: np.random.Generator) -> np.ndarray:
        out = img.astype(np.float32)
        ops = [
            lambda x: adjust_brightness(x, rng.uniform(*self.brightness)),
            lambda x: adjust_contrast(x, rng.uniform(*self.contrast)),
            lambda x: adjust_saturation(x, rng.uniform(*self.saturation)),
            lambda x: adjust_hue(x, rng.uniform(-self.hue, self.hue)),
        ]
        for i in rng.permutation(4):
            out = ops[i](out)
        g_min, g_max, gain_min, gain_max = self.gamma
        # the RNG draws must happen unconditionally to keep the deterministic
        # stream identical whether or not the gamma op is an identity
        gamma = rng.uniform(g_min, g_max)
        gain = rng.uniform(gain_min, gain_max)
        if not (gamma == 1.0 and gain == 1.0):
            out = adjust_gamma(out, gamma, gain)
        return out.astype(np.uint8)


# ------------------------------------------------------------------ shared pieces

def _eraser(img2: np.ndarray, rng: np.random.Generator,
            bounds: Tuple[int, int] = (50, 100), prob: float = 0.5) -> np.ndarray:
    """Occlusion simulation: paint mean-color rectangles into the right image."""
    ht, wd = img2.shape[:2]
    if rng.random() < prob:
        img2 = img2.copy()
        mean_color = img2.reshape(-1, 3).mean(axis=0)
        for _ in range(rng.integers(1, 3)):
            x0 = rng.integers(0, wd)
            y0 = rng.integers(0, ht)
            dx = rng.integers(bounds[0], bounds[1])
            dy = rng.integers(bounds[0], bounds[1])
            img2[y0:y0 + dy, x0:x0 + dx, :] = mean_color
    return img2


def _resize(img: np.ndarray, fx: float, fy: float,
            interp=cv2.INTER_LINEAR) -> np.ndarray:
    return cv2.resize(img, None, fx=fx, fy=fy, interpolation=interp)


def _flips(img1, img2, flow, rng, do_flip, h_flip_prob, v_flip_prob,
           valid=None):
    """The reference's three flip modes (augmentor.py:137-151):

    'hf' mirrors both images and negates x-flow; 'h' is the stereo-consistent
    flip (mirror AND swap left/right, flow unchanged); 'v' flips vertically
    with prob ``v_flip_prob`` and negates y-flow.

    ``valid`` (sparse GT) is flipped together with ``flow`` — a fix over the
    reference, which leaves the sparse validity mask unflipped (reference
    augmentor.py spatial_transform) and so silently supervises mirrored
    positions against the wrong mask. Dense callers pass ``valid=None``
    (their validity is recomputed from the flipped flow afterwards).
    """
    if do_flip:
        if rng.random() < h_flip_prob and do_flip == "hf":
            img1 = img1[:, ::-1]
            img2 = img2[:, ::-1]
            flow = flow[:, ::-1] * [-1.0, 1.0]
            if valid is not None:
                valid = valid[:, ::-1]
        if rng.random() < h_flip_prob and do_flip == "h":
            img1, img2 = img2[:, ::-1], img1[:, ::-1]
        if rng.random() < v_flip_prob and do_flip == "v":
            img1 = img1[::-1, :]
            img2 = img2[::-1, :]
            flow = flow[::-1, :] * [1.0, -1.0]
            if valid is not None:
                valid = valid[::-1, :]
    if valid is None:
        return img1, img2, flow
    return img1, img2, flow, valid


class FlowAugmentor:
    """Dense-GT augmentor (SceneFlow/Sintel/FallingThings/TartanAir)."""

    def __init__(self, crop_size: Tuple[int, int], min_scale: float = -0.2,
                 max_scale: float = 0.5, do_flip: Optional[str] = None,
                 yjitter: bool = False,
                 saturation_range: Tuple[float, float] = (0.6, 1.4),
                 gamma: Sequence[float] = (1, 1, 1, 1)):
        self.crop_size = tuple(crop_size)
        self.min_scale = min_scale
        self.max_scale = max_scale
        self.spatial_aug_prob = 1.0
        self.stretch_prob = 0.8
        self.max_stretch = 0.2
        self.yjitter = yjitter
        self.do_flip = do_flip
        self.h_flip_prob = 0.5
        self.v_flip_prob = 0.1
        self.photo = PhotometricAugment(0.4, 0.4, saturation_range,
                                        0.5 / 3.14, gamma)
        self.asymmetric_color_aug_prob = 0.2

    def color_transform(self, img1, img2, rng):
        if rng.random() < self.asymmetric_color_aug_prob:
            return self.photo(img1, rng), self.photo(img2, rng)
        stack = self.photo(np.concatenate([img1, img2], axis=0), rng)
        out1, out2 = np.split(stack, 2, axis=0)
        return out1, out2

    def spatial_transform(self, img1, img2, flow, rng):
        ch, cw = self.crop_size
        ht, wd = img1.shape[:2]
        # never scale below what the crop (plus an 8-px guard) needs
        min_scale = max((ch + 8) / float(ht), (cw + 8) / float(wd))

        scale = 2.0 ** rng.uniform(self.min_scale, self.max_scale)
        scale_x = scale_y = scale
        if rng.random() < self.stretch_prob:
            scale_x *= 2 ** rng.uniform(-self.max_stretch, self.max_stretch)
            scale_y *= 2 ** rng.uniform(-self.max_stretch, self.max_stretch)
        scale_x = max(scale_x, min_scale)
        scale_y = max(scale_y, min_scale)

        if rng.random() < self.spatial_aug_prob:
            img1 = _resize(img1, scale_x, scale_y)
            img2 = _resize(img2, scale_x, scale_y)
            flow = _resize(flow, scale_x, scale_y)
            flow = flow * [scale_x, scale_y]

        img1, img2, flow = _flips(img1, img2, flow, rng, self.do_flip,
                                  self.h_flip_prob, self.v_flip_prob)

        if self.yjitter:
            y0 = rng.integers(2, img1.shape[0] - ch - 2)
            x0 = rng.integers(2, img1.shape[1] - cw - 2)
            y1 = y0 + rng.integers(-2, 3)  # imperfect-rectification jitter
            img1 = img1[y0:y0 + ch, x0:x0 + cw]
            img2 = img2[y1:y1 + ch, x0:x0 + cw]
            flow = flow[y0:y0 + ch, x0:x0 + cw]
        else:
            y0 = rng.integers(0, img1.shape[0] - ch)
            x0 = rng.integers(0, img1.shape[1] - cw)
            img1 = img1[y0:y0 + ch, x0:x0 + cw]
            img2 = img2[y0:y0 + ch, x0:x0 + cw]
            flow = flow[y0:y0 + ch, x0:x0 + cw]
        return img1, img2, flow

    def __call__(self, img1, img2, flow, rng: np.random.Generator):
        img1, img2 = self.color_transform(img1, img2, rng)
        img2 = _eraser(img2, rng)
        img1, img2, flow = self.spatial_transform(img1, img2, flow, rng)
        return (np.ascontiguousarray(img1), np.ascontiguousarray(img2),
                np.ascontiguousarray(flow))


class SparseFlowAugmentor:
    """Sparse-GT augmentor (KITTI/ETH3D/Middlebury): scatter-resized flow maps."""

    def __init__(self, crop_size: Tuple[int, int], min_scale: float = -0.2,
                 max_scale: float = 0.5, do_flip: Optional[str] = None,
                 yjitter: bool = False,
                 saturation_range: Tuple[float, float] = (0.7, 1.3),
                 gamma: Sequence[float] = (1, 1, 1, 1)):
        del yjitter  # accepted for interface parity; sparse crops never jitter
        self.crop_size = tuple(crop_size)
        self.min_scale = min_scale
        self.max_scale = max_scale
        self.spatial_aug_prob = 0.8
        self.do_flip = do_flip
        self.h_flip_prob = 0.5
        self.v_flip_prob = 0.1
        self.photo = PhotometricAugment(0.3, 0.3, saturation_range,
                                        0.3 / 3.14, gamma)

    def color_transform(self, img1, img2, rng):
        stack = self.photo(np.concatenate([img1, img2], axis=0), rng)
        out1, out2 = np.split(stack, 2, axis=0)
        return out1, out2

    @staticmethod
    def resize_sparse_flow_map(flow, valid, fx: float, fy: float):
        """Resize a sparse flow field by scattering valid samples (augmentor.py:223-255)."""
        ht, wd = flow.shape[:2]
        xx, yy = np.meshgrid(np.arange(wd), np.arange(ht))
        coords = np.stack([xx, yy], axis=-1).reshape(-1, 2).astype(np.float32)
        flow_flat = flow.reshape(-1, 2).astype(np.float32)
        keep = valid.reshape(-1) >= 1

        coords1 = coords[keep] * [fx, fy]
        flow1 = flow_flat[keep] * [fx, fy]

        ht1, wd1 = int(round(ht * fy)), int(round(wd * fx))
        xi = np.round(coords1[:, 0]).astype(np.int32)
        yi = np.round(coords1[:, 1]).astype(np.int32)
        inb = (xi > 0) & (xi < wd1) & (yi > 0) & (yi < ht1)

        flow_img = np.zeros([ht1, wd1, 2], dtype=np.float32)
        valid_img = np.zeros([ht1, wd1], dtype=np.int32)
        flow_img[yi[inb], xi[inb]] = flow1[inb]
        valid_img[yi[inb], xi[inb]] = 1
        return flow_img, valid_img

    def spatial_transform(self, img1, img2, flow, valid, rng):
        ch, cw = self.crop_size
        ht, wd = img1.shape[:2]
        min_scale = max((ch + 1) / float(ht), (cw + 1) / float(wd))
        scale = max(2.0 ** rng.uniform(self.min_scale, self.max_scale),
                    min_scale)

        if rng.random() < self.spatial_aug_prob or \
                img1.shape[0] <= ch or img1.shape[1] <= cw:
            img1 = _resize(img1, scale, scale)
            img2 = _resize(img2, scale, scale)
            flow, valid = self.resize_sparse_flow_map(flow, valid, scale, scale)

        img1, img2, flow, valid = _flips(img1, img2, flow, rng, self.do_flip,
                                         self.h_flip_prob, self.v_flip_prob,
                                         valid=valid)

        # margin-biased crop: favors the lower / interior image regions where
        # sparse GT (LiDAR) actually lives (augmentor.py:291-298)
        margin_y, margin_x = 20, 50
        y0 = rng.integers(0, img1.shape[0] - ch + margin_y)
        x0 = rng.integers(-margin_x, img1.shape[1] - cw + margin_x)
        y0 = int(np.clip(y0, 0, img1.shape[0] - ch))
        x0 = int(np.clip(x0, 0, img1.shape[1] - cw))

        img1 = img1[y0:y0 + ch, x0:x0 + cw]
        img2 = img2[y0:y0 + ch, x0:x0 + cw]
        flow = flow[y0:y0 + ch, x0:x0 + cw]
        valid = valid[y0:y0 + ch, x0:x0 + cw]
        return img1, img2, flow, valid

    def __call__(self, img1, img2, flow, valid, rng: np.random.Generator):
        img1, img2 = self.color_transform(img1, img2, rng)
        img2 = _eraser(img2, rng)
        img1, img2, flow, valid = self.spatial_transform(img1, img2, flow,
                                                         valid, rng)
        return (np.ascontiguousarray(img1), np.ascontiguousarray(img2),
                np.ascontiguousarray(flow), np.ascontiguousarray(valid))
