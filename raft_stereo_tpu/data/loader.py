"""Threaded, deterministic prefetching batch loader.

Replaces the reference's ``torch.utils.data.DataLoader`` with worker
*processes* (stereo_datasets.py:317-318) by a thread pool: the decode path
(PIL/cv2/numpy) releases the GIL for its hot loops, samples are fixed-size
after augmentation (static shapes), and batches are assembled into one
contiguous numpy array per field so the host->device transfer is a single DMA.

Determinism: sample ``i`` of epoch ``e`` is always decoded with
``Philox(key=(seed, e, perm[i]))`` — the stream does not depend on worker
count or scheduling, unlike worker-id-seeded torch loaders
(stereo_datasets.py:55-61).

I/O resilience (training/resilience.py is the checkpoint half; this is the
data half): a decode failure is retried ``decode_retries`` times with
exponential backoff (transient NFS/GCS hiccups), then the sample is
QUARANTINED — deterministically substituted by the next decodable dataset
index, decoded with the **original slot's** Philox key. Substitution
consumes no other slot's randomness and depends only on (epoch, index,
which samples are broken), so a resumed run quarantines identically and
the Philox exact-resume contract survives bad files. Quarantines are
logged and reported through ``quarantine_hook`` (the trainer forwards them
as ``anomaly`` events with ``kind="loader_quarantine"``).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, Iterator, Optional

import numpy as np

from raft_stereo_tpu.obs.trace import NULL_TRACER

logger = logging.getLogger(__name__)

BATCH_FIELDS = ("image1", "image2", "flow", "valid")

# Producer-side gauge cadence: one `loader` telemetry event per this many
# batches (obs/telemetry.py loader_gauge) — frequent enough to see a draining
# prefetch queue, cheap enough to never show up in a profile.
GAUGE_EVERY = 16


def _collate(samples) -> Dict[str, np.ndarray]:
    """Stack per-sample arrays into one contiguous batch per field.

    uint8 image fields are collated straight to float32 — in one native pass
    (native/stereodata.cpp) when the library is built, else stack+astype.
    """
    from raft_stereo_tpu.data import native

    out: Dict[str, np.ndarray] = {}
    for k in BATCH_FIELDS:
        arrs = [s[k] for s in samples]
        if arrs[0].dtype == np.uint8:
            batched = native.collate_u8(arrs) if native.available() else None
            out[k] = (np.stack(arrs).astype(np.float32)
                      if batched is None else batched)
        else:
            out[k] = np.stack(arrs)
    return out


class Loader:
    """Iterable over batches of stacked numpy arrays.

    Each ``__iter__`` starts a fresh epoch: a seeded permutation of the
    dataset, ``num_workers`` decode threads, and a bounded prefetch queue.
    """

    def __init__(self, dataset, batch_size: int, seed: int = 0,
                 num_workers: int = 4, shuffle: bool = True,
                 drop_last: bool = True, prefetch: int = 4,
                 decode_retries: int = 2, retry_backoff_s: float = 0.05):
        self.dataset = dataset
        self.batch_size = batch_size
        self.seed = seed
        self.num_workers = max(1, num_workers)
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.prefetch = prefetch
        self.epoch = 0
        # I/O resilience (module doc): bounded retry-with-backoff on decode
        # failures, then deterministic skip-and-quarantine.
        self.decode_retries = max(0, decode_retries)
        self.retry_backoff_s = retry_backoff_s
        self.quarantine_hook: Optional[Callable[[Dict], None]] = None
        self.quarantined: list = []  # records of substituted samples
        # Optional telemetry hook (set by the trainer): called from the
        # producer thread with queue-depth/wait gauges every GAUGE_EVERY
        # batches. Must never raise into the pipeline — calls are guarded.
        self.gauge_hook: Optional[Callable[[Dict], None]] = None
        # Optional span tracer (obs/trace.py; set by the trainer alongside
        # gauge_hook): the producer thread records loader/produce spans
        # with decode/put legs, and quarantines record their scan window.
        self.tracer = None
        # Consumed by the NEXT __iter__ only (then reset): resume support.
        # Because sample (epoch, index) fully determines decode + augment
        # (Philox keying below), skipping the first k batches of the
        # restored epoch reproduces the exact stream a run that never
        # stopped would have seen — no decode work is spent on the skip.
        self.start_batch = 0
        if len(self) == 0:
            raise ValueError(
                f"dataset of {len(dataset)} samples yields no batches at "
                f"batch_size={batch_size} (drop_last={drop_last})")

    def __len__(self) -> int:
        n = len(self.dataset) // self.batch_size
        if not self.drop_last and len(self.dataset) % self.batch_size:
            n += 1
        return n

    def _rng(self, epoch: int, index: int) -> np.random.Generator:
        return np.random.Generator(
            np.random.Philox(key=[(self.seed << 32) + epoch, index]))

    def _sample(self, epoch: int, index: int) -> Dict[str, np.ndarray]:
        return self.dataset.sample(index, self._rng(epoch, index))

    # Bounded substitution scan: how many forward dataset indices to try
    # before declaring the dataset unusable and propagating the original
    # decode error (a whole broken dataset must fail fast, not spin).
    _QUARANTINE_SCAN = 64

    def _sample_resilient(self, epoch: int, index: int
                          ) -> Dict[str, np.ndarray]:
        """Decode with retry + backoff; quarantine and substitute on a
        persistent failure (see module doc). Runs on pool threads."""
        delay = self.retry_backoff_s
        error: Optional[Exception] = None
        for attempt in range(self.decode_retries + 1):
            try:
                return self._sample(epoch, index)
            except Exception as e:
                error = e
                if attempt < self.decode_retries:
                    time.sleep(delay)
                    delay *= 2
        # persistent failure: substitute the next decodable index, decoded
        # with the ORIGINAL slot's rng — every other sample in the stream
        # stays bitwise identical, so resume reproduces the same stream
        n = len(self.dataset)
        tq0 = time.perf_counter()
        for k in range(1, min(n, self._QUARANTINE_SCAN)):
            sub = (index + k) % n
            try:
                sample = self.dataset.sample(sub, self._rng(epoch, index))
            except Exception:
                continue
            record = {"epoch": epoch, "index": int(index),
                      "substitute": int(sub),
                      "error": f"{type(error).__name__}: {error}",
                      "retries": self.decode_retries}
            self.quarantined.append(record)
            logger.warning(
                "quarantined sample %d (epoch %d) after %d retries: %s — "
                "substituted index %d", index, epoch, self.decode_retries,
                record["error"], sub)
            if self.quarantine_hook is not None:
                try:
                    self.quarantine_hook(dict(record))
                except Exception:
                    self.quarantine_hook = None  # never break the pipeline
            (self.tracer or NULL_TRACER).record(
                "loader/quarantine", tq0, time.perf_counter(),
                epoch=epoch, index=int(index), substitute=int(sub))
            return sample
        raise error

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        epoch = self.epoch
        self.epoch += 1

        order = np.arange(len(self.dataset))
        if self.shuffle:
            np.random.Generator(
                np.random.Philox(
                    key=[(self.seed << 32) + epoch, 1 << 48])).shuffle(order)

        n_batches = len(self)
        skip, self.start_batch = self.start_batch, 0
        if skip:
            # the permutation depends only on (seed, epoch), so dropping its
            # first k*B entries resumes mid-epoch exactly
            order = order[skip * self.batch_size:]
            n_batches = max(n_batches - skip, 0)
        out: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()

        def produce():
            decode_wait = put_wait = 0.0
            tracer = self.tracer or NULL_TRACER
            with ThreadPoolExecutor(self.num_workers) as pool:
                # pipeline sample futures one batch ahead of consumption
                futures = [pool.submit(self._sample_resilient, epoch, int(i))
                           for i in order[:min(len(order),
                                               2 * self.batch_size)]]
                submitted = len(futures)
                for b in range(n_batches):
                    batch_futs = futures[:self.batch_size]
                    futures = futures[self.batch_size:]
                    while submitted < len(order) and \
                            len(futures) < 2 * self.batch_size:
                        futures.append(pool.submit(
                            self._sample_resilient, epoch,
                            int(order[submitted])))
                        submitted += 1
                    try:
                        tb0 = time.perf_counter()
                        batch = _collate([f.result() for f in batch_futs])
                        td = time.perf_counter()
                        decode_wait += td - tb0
                    except Exception as e:  # propagate to consumer
                        out.put(e)
                        return
                    if stop.is_set():
                        return
                    out.put(batch)
                    tp = time.perf_counter()
                    put_wait += tp - td
                    # retroactive spans from the stamps just taken: decode
                    # (future-wait + collate) and put (blocked on a full
                    # prefetch queue) tile the produce root
                    root = tracer.record("loader/produce", tb0, tp,
                                         batch=b, epoch=epoch)
                    tracer.record("loader/decode", tb0, td, parent=root)
                    tracer.record("loader/put_wait", td, tp, parent=root)
                    if self.gauge_hook is not None and b % GAUGE_EVERY == 0:
                        try:
                            # queue_depth: batches banked ahead of the
                            # consumer (0 = training is data-starved);
                            # put_wait_s: producer blocked on a full queue
                            # (high = decode comfortably ahead)
                            self.gauge_hook({
                                "queue_depth": out.qsize(),
                                "prefetch": self.prefetch,
                                "decode_wait_s": round(decode_wait, 6),
                                "put_wait_s": round(put_wait, 6),
                                "batches_produced": b + 1,
                                "epoch": epoch,
                            })
                        except Exception:
                            self.gauge_hook = None  # never break the pipeline
                out.put(None)

        thread = threading.Thread(target=produce, daemon=True)
        thread.start()
        try:
            while True:
                try:
                    # bounded wait so a silently-dead producer (killed
                    # executor, interpreter teardown) can't wedge training
                    # on a forever-blocking get
                    item = out.get(timeout=5.0)
                except queue.Empty:
                    if thread.is_alive():
                        continue
                    try:  # item landed between the timeout and the check
                        item = out.get_nowait()
                    except queue.Empty:
                        raise RuntimeError(
                            "loader producer thread died without delivering "
                            "a batch or an exception") from None
                if item is None:
                    break
                if isinstance(item, Exception):
                    raise item
                yield item
        finally:
            stop.set()
            # drain so the producer can observe `stop` and exit
            while thread.is_alive():
                try:
                    out.get_nowait()
                except queue.Empty:
                    thread.join(timeout=0.1)


def infinite_batches(loader: Loader) -> Iterator[Dict[str, np.ndarray]]:
    """Loop epochs forever (the reference's `while should_keep_training`,
    train_stereo.py:159)."""
    while True:
        yield from loader
