"""Stereo datasets (capability of core/stereo_datasets.py).

Design differences from the reference, for the TPU host pipeline:

* Samples are numpy NHWC dicts (``image1``, ``image2``, ``flow``, ``valid``)
  — no torch tensors; the batch crosses to device once per step.
* ``__getitem__`` is replaced by a pure ``sample(index, rng)`` taking an
  explicit ``np.random.Generator`` — determinism comes from seeding, not from
  worker-global state (stereo_datasets.py:55-61 reseeds inside workers).
* Oversampling keeps the reference's semantics (``dataset * k`` replicates the
  index list, stereo_datasets.py:111-117; ``a + b`` concatenates) but is
  implemented with index arithmetic, not list copies.
* The KITTI constructor accepts the ``split`` keyword actually passed by
  ``fetch_dataloader`` (the reference's `KITTI(aug_params, split=...)`
  stereo_datasets.py:304 is a TypeError against its own ctor :247).

Directory layouts are the reference's, so existing dataset downloads work
unchanged (globs mirror stereo_datasets.py:136-280).
"""

from __future__ import annotations

import copy
import logging
import os
import os.path as osp
from glob import glob
from pathlib import Path
from typing import Dict, List, Optional, Sequence

import numpy as np

from raft_stereo_tpu.data import frame_utils
from raft_stereo_tpu.data.augment import FlowAugmentor, SparseFlowAugmentor

logger = logging.getLogger(__name__)

MAX_FLOW_VALID = 512.0  # dense-GT validity threshold (stereo_datasets.py:100)


def _make_augmentor(aug_params: Optional[dict], sparse: bool):
    if aug_params is None or "crop_size" not in aug_params:
        return None
    params = dict(aug_params)
    params.pop("img_pad", None)
    cls = SparseFlowAugmentor if sparse else FlowAugmentor
    return cls(**params)


class StereoDataset:
    """Base dataset: path lists + decode + augment -> numpy NHWC sample dict."""

    def __init__(self, aug_params: Optional[dict] = None, sparse: bool = False,
                 reader=None):
        self.sparse = sparse
        self.img_pad = (aug_params or {}).get("img_pad")
        self.augmentor = _make_augmentor(aug_params, sparse)
        self.disparity_reader = reader or frame_utils.read_disp_pfm
        self.image_list: List[List[str]] = []
        self.disparity_list: List[str] = []
        self.extra_info: List = []

    # -- composition ------------------------------------------------------
    def __mul__(self, k: int) -> "StereoDataset":
        out = copy.copy(self)
        out.image_list = k * self.image_list
        out.disparity_list = k * self.disparity_list
        out.extra_info = k * self.extra_info
        delegates = getattr(self, "_delegates", None)
        if delegates is not None:
            out._delegates = k * delegates
        return out

    __rmul__ = __mul__

    def __add__(self, other: "StereoDataset") -> "StereoDataset":
        out = StereoDataset.__new__(StereoDataset)
        StereoDataset.__init__(out)
        out.image_list = self.image_list + other.image_list
        out.disparity_list = self.disparity_list + other.disparity_list
        out.extra_info = self.extra_info + other.extra_info
        # per-item decode/augment settings must travel with each item
        out._delegates = (getattr(self, "_delegates", None)
                          or [self] * len(self.image_list)) + \
                         (getattr(other, "_delegates", None)
                          or [other] * len(other.image_list))
        return out

    def __len__(self) -> int:
        return len(self.image_list)

    # -- decode -----------------------------------------------------------
    def read_raw(self, index: int):
        """Decode one (img1, img2, flow, valid) tuple, un-augmented."""
        owner = getattr(self, "_delegates", None)
        if owner is not None:
            # concatenated dataset: delegate decode to the item's source
            src = owner[index]
        else:
            src = self
        disp = src.disparity_reader(self.disparity_list[index])
        if isinstance(disp, tuple):
            disp, valid = disp
        else:
            valid = disp < MAX_FLOW_VALID

        img1 = frame_utils.read_image(self.image_list[index][0])
        img2 = frame_utils.read_image(self.image_list[index][1])

        img1 = np.asarray(img1).astype(np.uint8)
        img2 = np.asarray(img2).astype(np.uint8)
        if img1.ndim == 2:  # grayscale -> 3-channel
            img1 = np.tile(img1[..., None], (1, 1, 3))
            img2 = np.tile(img2[..., None], (1, 1, 3))
        else:
            img1 = img1[..., :3]
            img2 = img2[..., :3]

        disp = np.asarray(disp, np.float32)
        # disparity -> horizontal flow; left image content moves left
        flow = np.stack([-disp, np.zeros_like(disp)], axis=-1)
        return img1, img2, flow, np.asarray(valid)

    def sample(self, index: int, rng: Optional[np.random.Generator] = None
               ) -> Dict[str, np.ndarray]:
        """One training sample as float32 NHWC arrays (flow keeps x only)."""
        index = index % len(self.image_list)
        img1, img2, flow, valid = self.read_raw(index)

        owner = getattr(self, "_delegates", None)
        src = owner[index] if owner is not None else self
        if src.augmentor is not None:
            if rng is None:
                # Deterministic by construction: deriving from the index keeps
                # ad-hoc sample() calls reproducible instead of silently
                # breaking the data layer's determinism contract.
                rng = np.random.default_rng(np.random.Philox(key=index))
            if src.sparse:
                img1, img2, flow, valid = src.augmentor(img1, img2, flow,
                                                        valid, rng)
            else:
                img1, img2, flow = src.augmentor(img1, img2, flow, rng)

        if not src.sparse:
            valid = (np.abs(flow[..., 0]) < MAX_FLOW_VALID) & \
                    (np.abs(flow[..., 1]) < MAX_FLOW_VALID)

        if src.img_pad is not None:
            pad_h, pad_w = src.img_pad
            pad = [(pad_h, pad_h), (pad_w, pad_w), (0, 0)]
            img1 = np.pad(img1, pad)
            img2 = np.pad(img2, pad)

        return {
            # images stay uint8 here; the loader's collate fuses the
            # stack + float32 cast (natively when libstereodata is built)
            "image1": np.ascontiguousarray(img1, dtype=np.uint8),
            "image2": np.ascontiguousarray(img2, dtype=np.uint8),
            "flow": flow[..., :1].astype(np.float32),
            "valid": valid.astype(np.float32),
            "paths": tuple(self.image_list[index]) + (self.disparity_list[index],),
        }


# ------------------------------------------------------------------ datasets

class SceneFlow(StereoDataset):
    """FlyingThings3D + Monkaa + Driving (stereo_datasets.py:123-184)."""

    def __init__(self, aug_params=None, root="datasets",
                 dstype="frames_cleanpass", things_test=False):
        super().__init__(aug_params)
        self.root = root
        self.dstype = dstype
        if things_test:
            self._add_things("TEST")
        else:
            self._add_things("TRAIN")
            self._add_monkaa()
            self._add_driving()

    def _append(self, left_images: Sequence[str], disp_from):
        for im in left_images:
            self.image_list.append([im, im.replace("left", "right")])
            self.disparity_list.append(disp_from(im))

    def _add_things(self, split="TRAIN"):
        n0 = len(self.disparity_list)
        root = osp.join(self.root, "FlyingThings3D")
        left = sorted(glob(osp.join(root, self.dstype, split, "*/*/left/*.png")))
        # the reference's fixed 400-frame val split, seed 1000
        # (stereo_datasets.py:145-149)
        val_idxs = set(
            np.random.RandomState(1000).permutation(len(left))[:400])
        keep = [im for i, im in enumerate(left)
                if split == "TRAIN" or i in val_idxs]
        self._append(keep, lambda im: im.replace(self.dstype, "disparity")
                     .replace(".png", ".pfm"))
        logger.info("Added %d from FlyingThings %s",
                    len(self.disparity_list) - n0, self.dstype)

    def _add_monkaa(self):
        n0 = len(self.disparity_list)
        root = osp.join(self.root, "Monkaa")
        left = sorted(glob(osp.join(root, self.dstype, "*/left/*.png")))
        self._append(left, lambda im: im.replace(self.dstype, "disparity")
                     .replace(".png", ".pfm"))
        logger.info("Added %d from Monkaa", len(self.disparity_list) - n0)

    def _add_driving(self):
        n0 = len(self.disparity_list)
        root = osp.join(self.root, "Driving")
        left = sorted(glob(osp.join(root, self.dstype, "*/*/*/left/*.png")))
        self._append(left, lambda im: im.replace(self.dstype, "disparity")
                     .replace(".png", ".pfm"))
        logger.info("Added %d from Driving", len(self.disparity_list) - n0)


class ETH3D(StereoDataset):
    def __init__(self, aug_params=None, root="datasets/ETH3D", split="training"):
        # The reference ETH3D (stereo_datasets.py:187-189) reads disp0GT.pfm
        # through plain read_gen, so ``valid`` is ``disp < 512`` — the nocc
        # mask on disk is never read. (The Middlebury nocc reader here would
        # silently change the validator's mask semantics; oracle-pinned in
        # tests/test_eval_oracle.py.)
        super().__init__(aug_params, sparse=True,
                         reader=frame_utils.read_disp_eth3d)
        im0 = sorted(glob(osp.join(root, f"two_view_{split}/*/im0.png")))
        im1 = sorted(glob(osp.join(root, f"two_view_{split}/*/im1.png")))
        if split == "training":
            disp = sorted(glob(osp.join(root, "two_view_training_gt/*/disp0GT.pfm")))
        else:  # test split has no GT; reference points at a placeholder
            disp = [osp.join(root, "two_view_training_gt/playground_1l/disp0GT.pfm")] * len(im0)
        for i0, i1, d in zip(im0, im1, disp):
            self.image_list.append([i0, i1])
            self.disparity_list.append(d)


class SintelStereo(StereoDataset):
    def __init__(self, aug_params=None, root="datasets/SintelStereo"):
        super().__init__(aug_params, sparse=True,
                         reader=frame_utils.read_disp_sintel)
        im0 = sorted(glob(osp.join(root, "training/*_left/*/frame_*.png")))
        im1 = sorted(glob(osp.join(root, "training/*_right/*/frame_*.png")))
        disp = sorted(glob(osp.join(root, "training/disparities/*/frame_*.png"))) * 2
        for i0, i1, d in zip(im0, im1, disp):
            if i0.split("/")[-2:] != d.split("/")[-2:]:
                raise ValueError(f"Sintel pairing mismatch: {i0} vs {d}")
            self.image_list.append([i0, i1])
            self.disparity_list.append(d)


class FallingThings(StereoDataset):
    def __init__(self, aug_params=None, root="datasets/FallingThings"):
        super().__init__(aug_params, reader=frame_utils.read_disp_falling_things)
        with open(osp.join(root, "filenames.txt")) as f:
            filenames = sorted(f.read().splitlines())
        for e in filenames:
            self.image_list.append([osp.join(root, e),
                                    osp.join(root, e.replace("left.jpg", "right.jpg"))])
            self.disparity_list.append(
                osp.join(root, e.replace("left.jpg", "left.depth.png")))


class TartanAir(StereoDataset):
    def __init__(self, aug_params=None, root="datasets", keywords=()):
        super().__init__(aug_params, reader=frame_utils.read_disp_tartanair)
        with open(osp.join(root, "tartanair_filenames.txt")) as f:
            filenames = sorted(
                s for s in f.read().splitlines()
                if "seasonsforest_winter/Easy" not in s)
        for kw in keywords:
            filenames = [s for s in filenames if kw in s.lower()]
        for e in filenames:
            self.image_list.append([osp.join(root, e),
                                    osp.join(root, e.replace("_left", "_right"))])
            self.disparity_list.append(
                osp.join(root, e.replace("image_left", "depth_left")
                         .replace("left.png", "left_depth.npy")))


class KITTI(StereoDataset):
    def __init__(self, aug_params=None, root="datasets/KITTI",
                 image_set="training", split=None):
        super().__init__(aug_params, sparse=True,
                         reader=frame_utils.read_disp_kitti)
        if split is not None:  # accept fetch_dataloader's spelling
            image_set = "training" if "kitti" in str(split) else str(split)
        im0 = sorted(glob(osp.join(root, image_set, "image_2/*_10.png")))
        im1 = sorted(glob(osp.join(root, image_set, "image_3/*_10.png")))
        if image_set == "training":
            disp = sorted(glob(osp.join(root, "training", "disp_occ_0/*_10.png")))
        else:
            disp = [osp.join(root, "training/disp_occ_0/000085_10.png")] * len(im0)
        for i0, i1, d in zip(im0, im1, disp):
            self.image_list.append([i0, i1])
            self.disparity_list.append(d)


class Middlebury(StereoDataset):
    def __init__(self, aug_params=None, root="datasets/Middlebury", split="F"):
        super().__init__(aug_params, sparse=True,
                         reader=frame_utils.read_disp_middlebury)
        if split not in ("F", "H", "Q", "2014"):
            raise ValueError(f"bad Middlebury split {split!r}")
        if split == "2014":
            for scene in sorted((Path(root) / "2014").glob("*")):
                for s in ("E", "L", ""):
                    self.image_list.append([str(scene / "im0.png"),
                                            str(scene / f"im1{s}.png")])
                    self.disparity_list.append(str(scene / "disp0.pfm"))
        else:
            official = Path(root, "MiddEval3/official_train.txt") \
                .read_text().splitlines()
            names = [osp.basename(p)
                     for p in glob(osp.join(root, "MiddEval3/trainingF/*"))]
            names = sorted(n for n in names if n in official)
            for name in names:
                base = osp.join(root, "MiddEval3", f"training{split}", name)
                self.image_list.append([osp.join(base, "im0.png"),
                                        osp.join(base, "im1.png")])
                self.disparity_list.append(osp.join(base, "disp0GT.pfm"))


# ------------------------------------------------------------------ loader entry

def build_train_dataset(train_datasets: Sequence[str], aug_params: dict,
                        root: str = "datasets") -> StereoDataset:
    """Mix datasets with the reference's oversampling ratios
    (stereo_datasets.py:294-315)."""
    combined = None
    for name in train_datasets:
        if name.startswith("middlebury_"):
            ds = Middlebury(aug_params, root=osp.join(root, "Middlebury"),
                            split=name.replace("middlebury_", ""))
        elif name == "sceneflow":
            clean = SceneFlow(aug_params, root=root, dstype="frames_cleanpass")
            final = SceneFlow(aug_params, root=root, dstype="frames_finalpass")
            ds = (clean * 4) + (final * 4)
        elif "kitti" in name:
            ds = KITTI(aug_params, root=osp.join(root, "KITTI"), split=name)
        elif name == "sintel_stereo":
            ds = SintelStereo(aug_params, root=osp.join(root, "SintelStereo")) * 140
        elif name == "falling_things":
            ds = FallingThings(aug_params,
                               root=osp.join(root, "FallingThings")) * 5
        elif name.startswith("tartan_air"):
            ds = TartanAir(aug_params, root=root,
                           keywords=name.split("_")[2:])
        else:
            raise ValueError(f"unknown training dataset {name!r}")
        logger.info("Adding %d samples from %s", len(ds), name)
        combined = ds if combined is None else combined + ds
    if combined is None or len(combined) == 0:
        raise ValueError(f"no training data found for {list(train_datasets)}")
    logger.info("Training with %d image pairs", len(combined))
    return combined


def fetch_dataloader(cfg, root: Optional[str] = None):
    """Build the training loader from a TrainConfig (train_stereo.py surface)."""
    from raft_stereo_tpu.data.loader import Loader

    aug_params = {
        "crop_size": tuple(cfg.image_size),
        "min_scale": cfg.spatial_scale[0],
        "max_scale": cfg.spatial_scale[1],
        "do_flip": cfg.do_flip,
        "yjitter": not cfg.noyjitter,
    }
    if cfg.saturation_range is not None:
        aug_params["saturation_range"] = tuple(cfg.saturation_range)
    if cfg.img_gamma is not None:
        aug_params["gamma"] = tuple(cfg.img_gamma)

    dataset = build_train_dataset(cfg.train_datasets, aug_params,
                                  root=root or cfg.data_root)
    return Loader(dataset, batch_size=cfg.batch_size, seed=cfg.seed,
                  num_workers=cfg.num_workers, drop_last=True, shuffle=True)
