"""Shared CLI flag surface -> dataclass configs.

The reference triplicates its argparse declarations across train/eval/demo
(train_stereo.py:214-249, evaluate_stereo.py:192-209, demo.py:55-75). Here the
flag names — the de-facto public API — are declared once and parsed into
:class:`RAFTStereoConfig` / :class:`TrainConfig`.
"""

from __future__ import annotations

import argparse
from typing import Optional, Sequence

from raft_stereo_tpu.config import RAFTStereoConfig, TrainConfig


def add_model_args(parser: argparse.ArgumentParser) -> None:
    """Architecture choices (identical flag group in all three reference CLIs)."""
    g = parser.add_argument_group("architecture")
    g.add_argument("--hidden_dims", nargs="+", type=int, default=[128, 128, 128],
                   help="hidden state and context dimensions")
    g.add_argument("--corr_implementation",
                   choices=["reg", "alt", "reg_cuda", "alt_cuda",
                            "reg_pallas", "alt_pallas", "ring"], default="reg",
                   help="correlation volume implementation "
                        "(*_cuda aliases map to the *_pallas TPU kernels; "
                        "ring = width-sharded sequence parallelism)")
    g.add_argument("--shared_backbone", action="store_true",
                   help="use a single backbone for context and feature nets")
    g.add_argument("--corr_levels", type=int, default=4)
    g.add_argument("--corr_radius", type=int, default=4)
    g.add_argument("--n_downsample", type=int, default=2,
                   help="resolution of the disparity field (1/2^K)")
    g.add_argument("--context_norm",
                   choices=["group", "batch", "instance", "none"],
                   default="batch")
    g.add_argument("--slow_fast_gru", action="store_true",
                   help="iterate the low-res GRUs more frequently")
    g.add_argument("--n_gru_layers", type=int, default=3)
    g.add_argument("--mixed_precision", action="store_true",
                   help="bf16 compute dtype (no loss scaling needed on TPU)")
    g.add_argument("--no_remat", action="store_true",
                   help="disable refinement-loop rematerialization "
                        "(faster, much more HBM)")
    g.add_argument("--corr_storage_dtype",
                   choices=["float32", "bfloat16"], default=None,
                   help="correlation-volume storage precision; default "
                        "matches the reference (fp32 for reg/alt, compute "
                        "dtype for the *_pallas kernels)")
    g.add_argument("--fused_lookup", choices=["auto", "on", "off"],
                   default="auto",
                   help="fused pyramid-lookup+convc1 Pallas kernel (auto: "
                        "off — measured slower than XLA's unfused path on "
                        "every surface, PERF.md r4 A/B; 'on' opts in where "
                        "shapes fit)")
    g.add_argument("--refinement_save_policy",
                   choices=["auto", "on", "off", "corr"], default="auto",
                   help="selective refinement-backward saves vs full remat "
                        "(auto: by the measured-size estimate — ON at "
                        "b4-like residency, OFF at b8 where HBM pressure "
                        "inverts the trade; 'corr' saves only the corr "
                        "lookup output, ~180 MB at b8; PERF.md)")
    g.add_argument("--batched_scan_wgrad", choices=["auto", "on", "off"],
                   default="auto",
                   help="custom-VJP refinement scan with batched weight "
                        "gradients (ops/scan_grad.py): one reverse scan "
                        "computes data grads, each gate conv's weight grad "
                        "is a single post-scan contraction (auto: off "
                        "pending hardware measurement; bench.py A/Bs both)")
    g.add_argument("--residual_dtype", choices=["float32", "bfloat16"],
                   default=None,
                   help="storage dtype for refinement-backward residual "
                        "stacks (bf16 halves the dominant stack residency; "
                        "accumulation stays fp32)")
    g.add_argument("--no_remat_loss_tail", action="store_true",
                   help="save the post-scan upsample/loss intermediates "
                        "across the loss backward instead of recomputing "
                        "them (1.4-1.9 GB extra residency at SceneFlow b8; "
                        "slightly faster where it fits)")


def model_config(args: argparse.Namespace) -> RAFTStereoConfig:
    return RAFTStereoConfig(
        hidden_dims=tuple(args.hidden_dims),
        corr_implementation=args.corr_implementation,
        shared_backbone=args.shared_backbone,
        corr_levels=args.corr_levels,
        corr_radius=args.corr_radius,
        n_downsample=args.n_downsample,
        context_norm=args.context_norm,
        slow_fast_gru=args.slow_fast_gru,
        n_gru_layers=args.n_gru_layers,
        mixed_precision=args.mixed_precision,
        remat_refinement=not getattr(args, "no_remat", False),
        corr_storage_dtype=getattr(args, "corr_storage_dtype", None),
        fused_lookup={"auto": None, "on": True, "off": False}[
            getattr(args, "fused_lookup", "auto")],
        remat_loss_tail=not getattr(args, "no_remat_loss_tail", False),
        refinement_save_policy={"auto": None, "on": True, "off": False,
                                "corr": "corr"}[
            getattr(args, "refinement_save_policy", "auto")],
        batched_scan_wgrad={"auto": None, "on": True, "off": False}[
            getattr(args, "batched_scan_wgrad", "auto")],
        residual_dtype=getattr(args, "residual_dtype", None),
    )


def add_train_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--name", default="raft-stereo",
                        help="name your experiment")
    parser.add_argument("--restore_ckpt", default=None,
                        help="orbax state dir, reference .pth, or 'auto' — "
                             "resume from the newest manifest-valid "
                             "checkpoint in ckpt_dir (corrupt/truncated/"
                             "foreign ones are skipped with a "
                             "ckpt_integrity event)")
    parser.add_argument("--batch_size", type=int, default=6)
    parser.add_argument("--train_datasets", nargs="+", default=["sceneflow"])
    parser.add_argument("--lr", type=float, default=0.0002)
    parser.add_argument("--num_steps", type=int, default=100000)
    parser.add_argument("--image_size", type=int, nargs="+",
                        default=[320, 720])
    parser.add_argument("--train_iters", type=int, default=16)
    parser.add_argument("--valid_iters", type=int, default=32)
    parser.add_argument("--wdecay", type=float, default=1e-5)
    g = parser.add_argument_group("data augmentation")
    g.add_argument("--img_gamma", type=float, nargs="+", default=None)
    g.add_argument("--saturation_range", type=float, nargs="+", default=None)
    g.add_argument("--do_flip", choices=["h", "v"], default=None)
    g.add_argument("--spatial_scale", type=float, nargs="+", default=[0, 0])
    g.add_argument("--noyjitter", action="store_true")
    o = parser.add_argument_group("ours")
    o.add_argument("--data_root", default="datasets")
    o.add_argument("--ckpt_dir", default="checkpoints")
    o.add_argument("--validation_frequency", type=int, default=10000)
    o.add_argument("--num_workers", type=int, default=4)
    o.add_argument("--seed", type=int, default=1234)
    o.add_argument("--data_parallel", type=int, default=0,
                   help="data-parallel shards (<=0: all devices)")
    o.add_argument("--seq_parallel", type=int, default=1,
                   help="width (sequence) parallel shards")
    o.add_argument("--grad_accum_steps", type=int, default=1,
                   help="average grads over k micro-batches per update")
    o.add_argument("--run_dir", default="runs",
                   help="run-artifact root: console/TB logs and the "
                        "events.jsonl telemetry land under <run_dir>/<name>")
    o.add_argument("--stall_deadline_s", type=float, default=300.0,
                   help="stall-watchdog deadline: warn + emit a `stall` "
                        "event when no step completes within this many "
                        "seconds (0 disables)")
    f = parser.add_argument_group(
        "fault tolerance", "atomic checkpoints, preemption handling and "
        "the device-side anomaly guard (training/resilience.py; drill: "
        "scripts/fault_drill.py)")
    f.add_argument("--checkpoint_frequency", type=int, default=None,
                   help="checkpoint every N steps (default: ride "
                        "validation_frequency); a SIGKILL loses at most "
                        "this many steps, SIGTERM/SIGINT lose none")
    f.add_argument("--ckpt_keep_last", type=int, default=3,
                   help="retention: keep the newest K step checkpoints "
                        "(0 = keep everything)")
    f.add_argument("--ckpt_keep_every", type=int, default=0,
                   help="retention: additionally spare checkpoints whose "
                        "step is a multiple of N (0 = none)")
    f.add_argument("--no_anomaly_guard", action="store_true",
                   help="disable the lax.cond skip of optimizer updates "
                        "on non-finite grad-norm/loss")
    f.add_argument("--anomaly_max_skips", type=int, default=10,
                   help="halt (for rollback to the last valid checkpoint) "
                        "after M consecutive skipped updates (0 = never)")


def train_config(args: argparse.Namespace) -> TrainConfig:
    return TrainConfig(
        name=args.name,
        restore_ckpt=args.restore_ckpt,
        batch_size=args.batch_size,
        train_datasets=tuple(args.train_datasets),
        lr=args.lr,
        num_steps=args.num_steps,
        image_size=tuple(args.image_size),
        train_iters=args.train_iters,
        valid_iters=args.valid_iters,
        wdecay=args.wdecay,
        img_gamma=tuple(args.img_gamma) if args.img_gamma else None,
        saturation_range=(tuple(args.saturation_range)
                          if args.saturation_range else None),
        do_flip=args.do_flip,
        spatial_scale=tuple(args.spatial_scale),
        noyjitter=args.noyjitter,
        data_root=args.data_root,
        seed=args.seed,
        ckpt_dir=args.ckpt_dir,
        validation_frequency=args.validation_frequency,
        num_workers=args.num_workers,
        data_parallel=args.data_parallel,
        seq_parallel=args.seq_parallel,
        grad_accum_steps=args.grad_accum_steps,
        run_dir=args.run_dir,
        stall_deadline_s=args.stall_deadline_s or None,
        checkpoint_frequency=args.checkpoint_frequency,
        ckpt_keep_last=args.ckpt_keep_last,
        ckpt_keep_every=args.ckpt_keep_every,
        anomaly_guard=not args.no_anomaly_guard,
        anomaly_max_skips=args.anomaly_max_skips,
    )


def load_variables(restore_ckpt: Optional[str], cfg: RAFTStereoConfig,
                   image_shape=(1, 64, 96, 3)):
    """Init a model and (optionally) load weights from .pth or orbax state."""
    import jax

    from raft_stereo_tpu.models import init_model

    model, variables = init_model(jax.random.PRNGKey(0), cfg, image_shape)
    if restore_ckpt is None:
        return model, variables
    if restore_ckpt.endswith((".pth", ".pth.gz")):
        from raft_stereo_tpu.utils.checkpoint_convert import (
            load_reference_checkpoint, validate_against_variables)
        converted = load_reference_checkpoint(restore_ckpt)
        return model, validate_against_variables(converted, variables)
    from raft_stereo_tpu.training.checkpoint import restore_train_state
    from raft_stereo_tpu.training.optim import fetch_optimizer
    from raft_stereo_tpu.training.state import TrainState

    state = TrainState.create(variables, fetch_optimizer(TrainConfig()))
    restored = restore_train_state(restore_ckpt, jax.device_get(state))
    return model, {"params": restored.params,
                   "batch_stats": restored.batch_stats}


def build_train_parser() -> argparse.ArgumentParser:
    """The training flag surface (reference train_stereo.py:214-249)."""
    parser = argparse.ArgumentParser(description="RAFT-Stereo TPU training")
    add_train_args(parser)
    add_model_args(parser)
    return parser


def build_eval_parser() -> argparse.ArgumentParser:
    """The evaluation flag surface (reference evaluate_stereo.py:192-209)."""
    parser = argparse.ArgumentParser(description="RAFT-Stereo TPU evaluation")
    parser.add_argument("--restore_ckpt", default=None,
                        help="reference .pth or orbax state dir")
    parser.add_argument("--run_dir", default=None,
                        help="write events.jsonl telemetry (per-frame timing "
                             "+ results) under this run directory")
    parser.add_argument("--dataset", required=True,
                        choices=["eth3d", "kitti", "things", "middlebury_F",
                                 "middlebury_H", "middlebury_Q"])
    parser.add_argument("--valid_iters", type=int, default=32,
                        help="number of refinement iterations")
    parser.add_argument("--data_root", default="datasets")
    parser.add_argument("--bucket", type=int, default=0,
                        help="pad eval images up to multiples of this size "
                             "to bound recompiles (0 = exact /32 padding)")
    g = parser.add_argument_group(
        "streaming", "pipelined evaluation (eval/stream.py): overlap frame "
        "decode, device dispatch and result fetch instead of paying them "
        "serially per frame")
    g.add_argument("--stream", choices=["auto", "on", "off"], default="auto",
                   help="auto streams whenever the predictor supports async "
                        "dispatch; off reproduces the serial loop (and, on "
                        "kitti, the device-only FPS measurement)")
    g.add_argument("--stream_window", type=int, default=3,
                   help="max in-flight device dispatches (1 = no overlap)")
    g.add_argument("--stream_microbatch", type=int, default=1,
                   help="stack up to this many consecutive same-shape "
                        "frames through one dispatch")
    g.add_argument("--decode_workers", type=int, default=2,
                   help="background frame-decode threads")
    add_model_args(parser)
    return parser


def build_demo_parser() -> argparse.ArgumentParser:
    """The demo flag surface (reference demo.py:55-75)."""
    parser = argparse.ArgumentParser(description="RAFT-Stereo TPU demo")
    parser.add_argument("--restore_ckpt", required=True,
                        help="reference .pth or orbax state dir")
    parser.add_argument("-l", "--left_imgs", required=True,
                        help="glob for left images")
    parser.add_argument("-r", "--right_imgs", required=True,
                        help="glob for right images")
    parser.add_argument("--output_directory", default="demo_output")
    parser.add_argument("--save_numpy", action="store_true",
                        help="also save raw .npy disparities")
    parser.add_argument("--valid_iters", type=int, default=32)
    add_model_args(parser)
    return parser


def _train_main():
    """Console entry point (`raft-stereo-train`); same surface as
    train_stereo.py."""
    import logging

    args = build_train_parser().parse_args()
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(filename)s:%(lineno)d %(message)s")
    from raft_stereo_tpu.training.trainer import train
    print(f"final checkpoint: {train(model_config(args), train_config(args))}")


def _eval_main():
    """Console entry point (`raft-stereo-eval`); same surface as
    evaluate_stereo.py."""
    import logging

    from raft_stereo_tpu.eval.validate import VALIDATORS, validate_middlebury
    from raft_stereo_tpu.inference import StereoPredictor

    args = build_eval_parser().parse_args()
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(filename)s:%(lineno)d %(message)s")
    # the reference enables mixed precision automatically for the kernel
    # implementations (evaluate_stereo.py:229-231); mirror that for the
    # pallas variants (and their *_cuda aliases)
    if args.corr_implementation.endswith(("_cuda", "_pallas")) \
            and not args.mixed_precision:
        logging.getLogger(__name__).info(
            "enabling mixed precision for %s", args.corr_implementation)
        args.mixed_precision = True
    cfg = model_config(args)
    _, variables = load_variables(args.restore_ckpt, cfg)
    predictor = StereoPredictor(cfg, variables, valid_iters=args.valid_iters,
                                bucket=args.bucket)
    from raft_stereo_tpu.eval.stream import StreamConfig
    stream = StreamConfig(
        enabled={"auto": None, "on": True, "off": False}[args.stream],
        window=args.stream_window, microbatch=args.stream_microbatch,
        decode_workers=args.decode_workers)
    tel = None
    if args.run_dir:
        from raft_stereo_tpu.obs import Telemetry
        tel = Telemetry(args.run_dir, stall_deadline_s=None)
        tel.run_start(config={"dataset": args.dataset,
                              "valid_iters": args.valid_iters,
                              "stream": args.stream,
                              "stream_window": args.stream_window,
                              "stream_microbatch": args.stream_microbatch})
    try:
        if args.dataset.startswith("middlebury_"):
            results = validate_middlebury(predictor, args.data_root,
                                          args.valid_iters,
                                          split=args.dataset.split("_")[1],
                                          telemetry=tel, stream=stream)
        else:
            results = VALIDATORS[args.dataset](predictor, args.data_root,
                                               args.valid_iters,
                                               telemetry=tel, stream=stream)
    except BaseException as e:
        if tel is not None:
            tel.error(e)
            tel.emit("run_end", steps=0, ok=False)
            tel.close()
        raise
    if tel is not None:
        tel.emit("run_end", steps=tel.steps, ok=True)
        tel.close()
    print(results)


def main(argv: Optional[Sequence[str]] = None) -> int:
    """Subcommand dispatch for ``python -m raft_stereo_tpu.cli``:

    * ``telemetry <run_dir>`` — summarize a run's events.jsonl + profiler
      trace (obs/summarize.py),
    * ``compare <baseline> <candidate>`` — regression-gate two runs' event
      logs (obs/compare.py; exit 1 on regression),
    * ``lint [--graph|--ast]`` — graftlint: jaxpr/HLO contract rules +
      tracer-safety AST lint (raft_stereo_tpu/analysis/; exit 1 on
      unsuppressed error-severity findings),
    * ``train`` / ``eval`` — the console entry points, for environments
      without the installed scripts.
    """
    import sys

    argv = list(sys.argv[1:] if argv is None else argv)
    commands = ("telemetry", "compare", "lint", "train", "eval")
    if not argv or argv[0] not in commands:
        print(f"usage: python -m raft_stereo_tpu.cli {{{'|'.join(commands)}}} "
              "...", file=sys.stderr)
        return 2
    cmd, rest = argv[0], argv[1:]
    if cmd == "telemetry":
        from raft_stereo_tpu.obs.summarize import main as telemetry_main
        return telemetry_main(rest)
    if cmd == "compare":
        from raft_stereo_tpu.obs.compare import main as compare_main
        return compare_main(rest)
    if cmd == "lint":
        from raft_stereo_tpu.analysis.runner import main as lint_main
        return lint_main(rest)
    # _train_main/_eval_main parse sys.argv via argparse; present the
    # remainder as the whole command line
    sys.argv = [f"{sys.argv[0]} {cmd}"] + rest
    (_train_main if cmd == "train" else _eval_main)()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
