"""Shared CLI flag surface -> dataclass configs.

The reference triplicates its argparse declarations across train/eval/demo
(train_stereo.py:214-249, evaluate_stereo.py:192-209, demo.py:55-75). Here the
flag names — the de-facto public API — are declared once and parsed into
:class:`RAFTStereoConfig` / :class:`TrainConfig`.
"""

from __future__ import annotations

import argparse
from typing import Optional, Sequence

from raft_stereo_tpu.config import RAFTStereoConfig, TrainConfig


def add_model_args(parser: argparse.ArgumentParser) -> None:
    """Architecture choices (identical flag group in all three reference CLIs)."""
    g = parser.add_argument_group("architecture")
    g.add_argument("--hidden_dims", nargs="+", type=int, default=[128, 128, 128],
                   help="hidden state and context dimensions")
    g.add_argument("--corr_implementation",
                   choices=["reg", "alt", "reg_cuda", "alt_cuda",
                            "reg_pallas", "alt_pallas", "ring", "fused",
                            "fused_cuda", "memoryless"], default="reg",
                   help="correlation volume implementation "
                        "(reg_cuda aliases reg_pallas; alt_cuda/fused_cuda/"
                        "memoryless alias fused, the memoryless W2-blocked "
                        "kernel that never builds the B*H*W^2 volume; "
                        "ring = width-sharded sequence parallelism)")
    g.add_argument("--shared_backbone", action="store_true",
                   help="use a single backbone for context and feature nets")
    g.add_argument("--corr_levels", type=int, default=4)
    g.add_argument("--corr_radius", type=int, default=4)
    g.add_argument("--n_downsample", type=int, default=2,
                   help="resolution of the disparity field (1/2^K)")
    g.add_argument("--context_norm",
                   choices=["group", "batch", "instance", "none"],
                   default="batch")
    g.add_argument("--slow_fast_gru", action="store_true",
                   help="iterate the low-res GRUs more frequently")
    g.add_argument("--n_gru_layers", type=int, default=3)
    g.add_argument("--mixed_precision", action="store_true",
                   help="bf16 compute dtype (no loss scaling needed on TPU)")
    g.add_argument("--no_remat", action="store_true",
                   help="disable refinement-loop rematerialization "
                        "(faster, much more HBM)")
    g.add_argument("--corr_storage_dtype",
                   choices=["float32", "bfloat16"], default=None,
                   help="correlation-volume storage precision; default "
                        "matches the reference (fp32 for reg/alt, compute "
                        "dtype for the *_pallas and fused kernels)")
    g.add_argument("--fused_block_w", type=int, default=256,
                   help="W2 tile width (lanes) for the memoryless 'fused' "
                        "correlation kernel; bounds its VMEM sub-slab "
                        "independent of image width (halved further under "
                        "pressure)")
    g.add_argument("--fused_lookup", choices=["auto", "on", "off"],
                   default="auto",
                   help="fused pyramid-lookup+convc1 Pallas kernel (auto: "
                        "off — measured slower than XLA's unfused path on "
                        "every surface, PERF.md r4 A/B; 'on' opts in where "
                        "shapes fit)")
    g.add_argument("--refinement_save_policy",
                   choices=["auto", "on", "off", "corr"], default="auto",
                   help="selective refinement-backward saves vs full remat "
                        "(auto: by the measured-size estimate — ON at "
                        "b4-like residency, OFF at b8 where HBM pressure "
                        "inverts the trade; 'corr' saves only the corr "
                        "lookup output, ~180 MB at b8; PERF.md)")
    g.add_argument("--batched_scan_wgrad", choices=["auto", "on", "off"],
                   default="auto",
                   help="custom-VJP refinement scan with batched weight "
                        "gradients (ops/scan_grad.py): one reverse scan "
                        "computes data grads, each gate conv's weight grad "
                        "is a single post-scan contraction (auto: off "
                        "pending hardware measurement; bench.py A/Bs both)")
    g.add_argument("--residual_dtype", choices=["float32", "bfloat16"],
                   default=None,
                   help="storage dtype for refinement-backward residual "
                        "stacks (bf16 halves the dominant stack residency; "
                        "accumulation stays fp32)")
    g.add_argument("--no_remat_loss_tail", action="store_true",
                   help="save the post-scan upsample/loss intermediates "
                        "across the loss backward instead of recomputing "
                        "them (1.4-1.9 GB extra residency at SceneFlow b8; "
                        "slightly faster where it fits)")


def model_config(args: argparse.Namespace) -> RAFTStereoConfig:
    return RAFTStereoConfig(
        hidden_dims=tuple(args.hidden_dims),
        corr_implementation=args.corr_implementation,
        shared_backbone=args.shared_backbone,
        corr_levels=args.corr_levels,
        corr_radius=args.corr_radius,
        n_downsample=args.n_downsample,
        context_norm=args.context_norm,
        slow_fast_gru=args.slow_fast_gru,
        n_gru_layers=args.n_gru_layers,
        mixed_precision=args.mixed_precision,
        remat_refinement=not getattr(args, "no_remat", False),
        corr_storage_dtype=getattr(args, "corr_storage_dtype", None),
        fused_block_w=getattr(args, "fused_block_w", 256),
        fused_lookup={"auto": None, "on": True, "off": False}[
            getattr(args, "fused_lookup", "auto")],
        remat_loss_tail=not getattr(args, "no_remat_loss_tail", False),
        refinement_save_policy={"auto": None, "on": True, "off": False,
                                "corr": "corr"}[
            getattr(args, "refinement_save_policy", "auto")],
        batched_scan_wgrad={"auto": None, "on": True, "off": False}[
            getattr(args, "batched_scan_wgrad", "auto")],
        residual_dtype=getattr(args, "residual_dtype", None),
    )


def add_train_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--name", default="raft-stereo",
                        help="name your experiment")
    parser.add_argument("--restore_ckpt", default=None,
                        help="orbax state dir, reference .pth, or 'auto' — "
                             "resume from the newest manifest-valid "
                             "checkpoint in ckpt_dir (corrupt/truncated/"
                             "foreign ones are skipped with a "
                             "ckpt_integrity event)")
    parser.add_argument("--batch_size", type=int, default=6)
    parser.add_argument("--train_datasets", nargs="+", default=["sceneflow"])
    parser.add_argument("--lr", type=float, default=0.0002)
    parser.add_argument("--num_steps", type=int, default=100000)
    parser.add_argument("--image_size", type=int, nargs="+",
                        default=[320, 720])
    parser.add_argument("--train_iters", type=int, default=16)
    parser.add_argument("--valid_iters", type=int, default=32)
    parser.add_argument("--wdecay", type=float, default=1e-5)
    g = parser.add_argument_group("data augmentation")
    g.add_argument("--img_gamma", type=float, nargs="+", default=None)
    g.add_argument("--saturation_range", type=float, nargs="+", default=None)
    g.add_argument("--do_flip", choices=["h", "v"], default=None)
    g.add_argument("--spatial_scale", type=float, nargs="+", default=[0, 0])
    g.add_argument("--noyjitter", action="store_true")
    o = parser.add_argument_group("ours")
    o.add_argument("--data_root", default="datasets")
    o.add_argument("--ckpt_dir", default="checkpoints")
    o.add_argument("--validation_frequency", type=int, default=10000)
    o.add_argument("--num_workers", type=int, default=4)
    o.add_argument("--seed", type=int, default=1234)
    o.add_argument("--data_parallel", type=int, default=0,
                   help="data-parallel shards (<=0: all devices)")
    o.add_argument("--seq_parallel", type=int, default=1,
                   help="width (sequence) parallel shards")
    o.add_argument("--grad_accum_steps", type=int, default=1,
                   help="average grads over k micro-batches per update")
    o.add_argument("--run_dir", default="runs",
                   help="run-artifact root: console/TB logs and the "
                        "events.jsonl telemetry land under <run_dir>/<name>")
    o.add_argument("--stall_deadline_s", type=float, default=300.0,
                   help="stall-watchdog deadline: warn + emit a `stall` "
                        "event when no step completes within this many "
                        "seconds (0 disables)")
    o.add_argument("--no_trace", action="store_true",
                   help="disable span tracing (obs/trace.py): no schema-v7 "
                        "`span` records, no cli timeline/doctor phase "
                        "breakdown for this run")
    f = parser.add_argument_group(
        "fault tolerance", "atomic checkpoints, preemption handling and "
        "the device-side anomaly guard (training/resilience.py; drill: "
        "scripts/fault_drill.py)")
    f.add_argument("--checkpoint_frequency", type=int, default=None,
                   help="checkpoint every N steps (default: ride "
                        "validation_frequency); a SIGKILL loses at most "
                        "this many steps, SIGTERM/SIGINT lose none")
    f.add_argument("--ckpt_keep_last", type=int, default=3,
                   help="retention: keep the newest K step checkpoints "
                        "(0 = keep everything)")
    f.add_argument("--ckpt_keep_every", type=int, default=0,
                   help="retention: additionally spare checkpoints whose "
                        "step is a multiple of N (0 = none)")
    f.add_argument("--no_anomaly_guard", action="store_true",
                   help="disable the lax.cond skip of optimizer updates "
                        "on non-finite grad-norm/loss")
    f.add_argument("--anomaly_max_skips", type=int, default=10,
                   help="halt (for rollback to the last valid checkpoint) "
                        "after M consecutive skipped updates (0 = never)")
    n = parser.add_argument_group(
        "numerics observatory", "in-graph per-leaf gradient-norm "
        "statistics and NaN provenance (obs/numerics.py; replay: "
        "`cli numerics <run_dir>`; drill: scripts/numerics_drill.py)")
    n.add_argument("--no_numerics", action="store_true",
                   help="disable the per-leaf gradient-norm aux entirely; "
                        "the train-step program and event stream are "
                        "bitwise-identical to pre-v9 training")
    n.add_argument("--numerics_every", type=int, default=50,
                   help="emit one grad `numerics` event every N steps (a "
                        "non-finite norm vector always emits regardless, "
                        "so cadence never hides NaN provenance)")
    fl = parser.add_argument_group(
        "fleet observatory", "schema-v10 host identity, clock anchor and "
        "heartbeat liveness on the event stream (obs/fleet.py; rollup: "
        "`cli fleet <dir>`; drill: scripts/fleet_drill.py)")
    fl.add_argument("--no_fleet", action="store_true",
                    help="disable fleet stamping entirely: no host_id/pid "
                         "extras, no clock_anchor, no heartbeat records — "
                         "the stream is byte-shaped like a single-process "
                         "run")
    fl.add_argument("--host_id", default=None,
                    help="host identity stamped on every record (default: "
                         "RAFT_HOST_ID env, else <hostname>-<pid>)")
    fl.add_argument("--heartbeat_every", type=float, default=10.0,
                    help="trainer heartbeat cadence in seconds (0 "
                         "disables the beats; stamping stays on)")


def train_config(args: argparse.Namespace) -> TrainConfig:
    return TrainConfig(
        name=args.name,
        restore_ckpt=args.restore_ckpt,
        batch_size=args.batch_size,
        train_datasets=tuple(args.train_datasets),
        lr=args.lr,
        num_steps=args.num_steps,
        image_size=tuple(args.image_size),
        train_iters=args.train_iters,
        valid_iters=args.valid_iters,
        wdecay=args.wdecay,
        img_gamma=tuple(args.img_gamma) if args.img_gamma else None,
        saturation_range=(tuple(args.saturation_range)
                          if args.saturation_range else None),
        do_flip=args.do_flip,
        spatial_scale=tuple(args.spatial_scale),
        noyjitter=args.noyjitter,
        data_root=args.data_root,
        seed=args.seed,
        ckpt_dir=args.ckpt_dir,
        validation_frequency=args.validation_frequency,
        num_workers=args.num_workers,
        data_parallel=args.data_parallel,
        seq_parallel=args.seq_parallel,
        grad_accum_steps=args.grad_accum_steps,
        run_dir=args.run_dir,
        stall_deadline_s=args.stall_deadline_s or None,
        trace=not args.no_trace,
        checkpoint_frequency=args.checkpoint_frequency,
        ckpt_keep_last=args.ckpt_keep_last,
        ckpt_keep_every=args.ckpt_keep_every,
        anomaly_guard=not args.no_anomaly_guard,
        anomaly_max_skips=args.anomaly_max_skips,
        numerics=not args.no_numerics,
        numerics_every=args.numerics_every,
        fleet=not args.no_fleet,
        host_id=args.host_id,
        heartbeat_every_s=args.heartbeat_every,
    )


def load_variables(restore_ckpt: Optional[str], cfg: RAFTStereoConfig,
                   image_shape=(1, 64, 96, 3)):
    """Init a model and (optionally) load weights from .pth or orbax state."""
    import jax

    from raft_stereo_tpu.models import init_model

    model, variables = init_model(jax.random.PRNGKey(0), cfg, image_shape)
    if restore_ckpt is None:
        return model, variables
    if restore_ckpt.endswith((".pth", ".pth.gz")):
        from raft_stereo_tpu.utils.checkpoint_convert import (
            load_reference_checkpoint, validate_against_variables)
        converted = load_reference_checkpoint(restore_ckpt)
        return model, validate_against_variables(converted, variables)
    from raft_stereo_tpu.training.checkpoint import restore_train_state
    from raft_stereo_tpu.training.optim import fetch_optimizer
    from raft_stereo_tpu.training.state import TrainState

    state = TrainState.create(variables, fetch_optimizer(TrainConfig()))
    restored = restore_train_state(restore_ckpt, jax.device_get(state))
    return model, {"params": restored.params,
                   "batch_stats": restored.batch_stats}


def build_train_parser() -> argparse.ArgumentParser:
    """The training flag surface (reference train_stereo.py:214-249)."""
    parser = argparse.ArgumentParser(description="RAFT-Stereo TPU training")
    add_train_args(parser)
    add_model_args(parser)
    return parser


def build_eval_parser() -> argparse.ArgumentParser:
    """The evaluation flag surface (reference evaluate_stereo.py:192-209)."""
    parser = argparse.ArgumentParser(description="RAFT-Stereo TPU evaluation")
    parser.add_argument("--restore_ckpt", default=None,
                        help="reference .pth or orbax state dir")
    parser.add_argument("--run_dir", default=None,
                        help="write events.jsonl telemetry (per-frame timing "
                             "+ results) under this run directory")
    parser.add_argument("--dataset", required=True,
                        choices=["eth3d", "kitti", "things", "middlebury_F",
                                 "middlebury_H", "middlebury_Q"])
    parser.add_argument("--valid_iters", type=int, default=32,
                        help="number of refinement iterations")
    parser.add_argument("--data_root", default="datasets")
    parser.add_argument("--bucket", type=int, default=0,
                        help="pad eval images up to multiples of this size "
                             "to bound recompiles (0 = exact /32 padding)")
    g = parser.add_argument_group(
        "streaming", "pipelined evaluation (eval/stream.py): overlap frame "
        "decode, device dispatch and result fetch instead of paying them "
        "serially per frame")
    g.add_argument("--stream", choices=["auto", "on", "off"], default="auto",
                   help="auto streams whenever the predictor supports async "
                        "dispatch; off reproduces the serial loop (and, on "
                        "kitti, the device-only FPS measurement)")
    g.add_argument("--stream_window", type=int, default=3,
                   help="max in-flight device dispatches (1 = no overlap)")
    g.add_argument("--stream_microbatch", type=int, default=1,
                   help="stack up to this many consecutive same-shape "
                        "frames through one dispatch")
    g.add_argument("--decode_workers", type=int, default=2,
                   help="background frame-decode threads")
    c = parser.add_argument_group(
        "convergence", "iteration-resolved quality telemetry "
        "(obs/converge.py): per-frame |delta disparity| curves on the "
        "event bus, replayable offline by `cli converge <run_dir>`")
    c.add_argument("--no_converge", action="store_true",
                   help="disable the convergence aux entirely; the forward "
                        "graph and event stream are bitwise-identical to "
                        "pre-v8 eval")
    c.add_argument("--iter_epe", action="store_true",
                   help="additionally compute the in-graph per-iteration "
                        "EPE against GT (needs datasets with flow; implies "
                        "the convergence aux)")
    c.add_argument("--iter_policy", default=None, metavar="PATH",
                   help="iteration-policy JSON (`cli converge --emit-policy`)"
                        ": run the COMPILED early-exit forward with each "
                        "bucket's recorded (tau, budget, min_iters) instead "
                        "of the fixed valid_iters trip; per-frame "
                        "iters_taken rides the converge events")
    n = parser.add_argument_group(
        "numerics", "per-iteration activation-tap range statistics "
        "(obs/numerics.py): min/max/absmean, bf16 saturation/underflow "
        "counters and first-nonfinite NaN provenance as `numerics` "
        "events, replayable by `cli numerics <run_dir>`")
    n.add_argument("--no_numerics", action="store_true",
                   help="disable the numerics aux entirely; the forward "
                        "program and event stream are bitwise-identical "
                        "to pre-v9 eval")
    add_model_args(parser)
    return parser


def build_demo_parser() -> argparse.ArgumentParser:
    """The demo flag surface (reference demo.py:55-75)."""
    parser = argparse.ArgumentParser(description="RAFT-Stereo TPU demo")
    parser.add_argument("--restore_ckpt", required=True,
                        help="reference .pth or orbax state dir")
    parser.add_argument("-l", "--left_imgs", required=True,
                        help="glob for left images")
    parser.add_argument("-r", "--right_imgs", required=True,
                        help="glob for right images")
    parser.add_argument("--output_directory", default="demo_output")
    parser.add_argument("--save_numpy", action="store_true",
                        help="also save raw .npy disparities")
    parser.add_argument("--valid_iters", type=int, default=32)
    add_model_args(parser)
    return parser


def add_serve_args(parser: argparse.ArgumentParser) -> None:
    """Scheduler/queue knobs shared by ``serve`` and ``loadtest``."""
    g = parser.add_argument_group(
        "serving", "continuous-batching scheduler (raft_stereo_tpu/serve)")
    g.add_argument("--max_batch", type=int, default=4,
                   help="max requests stacked through one dispatch")
    g.add_argument("--queue_depth", type=int, default=64,
                   help="bounded request-queue depth (admission "
                        "backpressure past this)")
    g.add_argument("--window", type=int, default=2,
                   help="max device dispatches in flight")
    g.add_argument("--iters", type=int, default=32,
                   help="refinement iterations per request (the request "
                        "may override)")
    g.add_argument("--bucket", type=int, default=0,
                   help="pad request shapes up to multiples of this to "
                        "bound compiled buckets (0 = exact /32 padding)")
    g.add_argument("--linger_ms", type=float, default=0.0,
                   help="wait up to this long for same-bucket stragglers "
                        "while a batch is below max_batch")
    g.add_argument("--no_aot", action="store_true",
                   help="skip AOT lower().compile(); jit on first call")
    g.add_argument("--slo_every", type=int, default=16,
                   help="emit one `slo` rollup event every N retirements")
    g.add_argument("--no_converge", action="store_true",
                   help="serve the 3-output program without the per-request "
                        "convergence aux: no converge events, no per-bucket "
                        "slo quality gauges (the schema-v7 pin)")
    g.add_argument("--numerics", action="store_true",
                   help="serve the numerics flavor (obs/numerics.py): "
                        "per-dispatch activation-tap `numerics` events + "
                        "per-bucket output-range drift gauges on the "
                        "Prometheus /metrics endpoint; OFF by default — "
                        "the served program stays byte-identical without it")
    g.add_argument("--iter_policy", default=None, metavar="PATH",
                   help="iteration-policy JSON (`cli converge "
                        "--emit-policy`): serve the compiled early-exit "
                        "flavors — per-bucket (tau, budget, min_iters) "
                        "replace --iters where the policy covers the "
                        "bucket; per-request iters_taken rides the "
                        "request/slo telemetry and /metrics")
    g.add_argument("--adaptive", choices=["auto", "on", "off"],
                   default="auto",
                   help="early-exit execution mode (auto: on iff "
                        "--iter_policy is given; off ignores a loaded "
                        "policy and serves the fixed-trip programs — the "
                        "bitwise pre-adaptive pin)")
    g.add_argument("--fused_width", type=int, default=0,
                   help="serve buckets padded to at least this width via "
                        "the memoryless 'fused' correlation flavor "
                        "(per-bucket program swap; 0 = off)")


def serve_config(args: argparse.Namespace):
    from raft_stereo_tpu.serve import ServeConfig
    return ServeConfig(
        max_batch=args.max_batch, queue_depth=args.queue_depth,
        window=args.window, default_iters=args.iters, bucket=args.bucket,
        linger_s=args.linger_ms / 1e3, aot=not args.no_aot,
        slo_every=args.slo_every, converge=not args.no_converge,
        numerics=args.numerics, iter_policy=args.iter_policy,
        adaptive={"auto": None, "on": True, "off": False}[args.adaptive],
        fused_width=getattr(args, "fused_width", 0))


def _parse_shapes(specs) -> list:
    """['48x96', ...] -> [(48, 96), ...] (the --shapes/--warm_shapes
    format)."""
    out = []
    for spec in specs:
        h, w = spec.lower().split("x")
        out.append((int(h), int(w)))
    return out


def build_serve_parser() -> argparse.ArgumentParser:
    """The serving flag surface (``cli serve``): HTTP front + scheduler."""
    parser = argparse.ArgumentParser(
        description="RAFT-Stereo TPU serving (continuous batching)")
    parser.add_argument("--restore_ckpt", default=None,
                        help="reference .pth or orbax state dir")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8600)
    parser.add_argument("--run_dir", default=None,
                        help="write request/queue/slo telemetry under this "
                             "run directory")
    parser.add_argument("--warm_shapes", nargs="+", default=[],
                        help="AOT-precompile these HxW raw shapes before "
                             "admitting traffic (e.g. 384x512 540x960)")
    parser.add_argument("--ckpt_dir", default=None,
                        help="watch this checkpoint dir: SIGHUP hot-reloads "
                             "the newest manifest-valid checkpoint without "
                             "dropping queued work")
    parser.add_argument("--ckpt_name", default="raft-stereo",
                        help="checkpoint name prefix inside --ckpt_dir")
    parser.add_argument("--drain_timeout_s", type=float, default=300.0,
                        help="max seconds to finish admitted work after "
                             "SIGTERM/SIGINT before giving up (exit 1)")
    parser.add_argument("--no_metrics", action="store_true",
                        help="disable the Prometheus GET /metrics "
                             "exposition endpoint (serve/http.py)")
    parser.add_argument("--no_fleet", action="store_true",
                        help="disable schema-v10 fleet stamping (host_id/"
                             "pid extras, clock_anchor, heartbeats) on "
                             "the telemetry stream")
    parser.add_argument("--host_id", default=None,
                        help="host identity stamped on every record and "
                             "labeled on /metrics (default: RAFT_HOST_ID "
                             "env, else <hostname>-<pid>)")
    parser.add_argument("--heartbeat_every", type=float, default=10.0,
                        help="serve heartbeat cadence in seconds (0 "
                             "disables the beats; stamping stays on)")
    add_serve_args(parser)
    add_model_args(parser)
    return parser


def build_timeline_parser() -> argparse.ArgumentParser:
    """The ``cli timeline`` flag surface (consumed by obs/timeline.py)."""
    parser = argparse.ArgumentParser(
        prog="cli timeline",
        description="Export a run's span/event/device timeline as "
                    "Chrome/Perfetto trace JSON")
    parser.add_argument("run_dir", help="run directory holding events.jsonl")
    parser.add_argument("--out", default=None,
                        help="output path (default <run_dir>/timeline.json)")
    return parser


def build_doctor_parser() -> argparse.ArgumentParser:
    """The ``cli doctor`` flag surface (consumed by obs/doctor.py)."""
    parser = argparse.ArgumentParser(
        prog="cli doctor",
        description="Rule-driven bottleneck diagnosis over a run's "
                    "events + spans")
    parser.add_argument("run_dir",
                        help="run directory (or events.jsonl path)")
    parser.add_argument("--json", action="store_true",
                        help="emit the report as JSON instead of text")
    return parser


def build_fleet_parser() -> argparse.ArgumentParser:
    """The ``cli fleet`` flag surface (consumed by obs/fleet.py)."""
    parser = argparse.ArgumentParser(
        prog="cli fleet",
        description="Merge N per-host run dirs into one clock-aligned "
                    "rollup (per-host step-time/throughput, skew table, "
                    "heartbeat gaps, cross-host trace joins) plus a "
                    "merged Perfetto timeline with a process-group per "
                    "host")
    parser.add_argument("fleet_dir",
                        help="directory whose child directories are the "
                             "per-host run dirs (each holding an "
                             "events.jsonl)")
    parser.add_argument("--out", default=None,
                        help="merged timeline output path (default "
                             "<fleet_dir>/fleet_timeline.json)")
    parser.add_argument("--json", action="store_true",
                        help="emit the rollup as JSON instead of text")
    return parser


def build_converge_parser() -> argparse.ArgumentParser:
    """The ``cli converge`` flag surface (consumed by obs/converge.py)."""
    parser = argparse.ArgumentParser(
        prog="cli converge",
        description="Early-exit what-if simulator: replay a run's recorded "
                    "convergence curves against a grid of exit thresholds "
                    "and print the decision table (iterations saved vs "
                    "predicted EPE delta) — no model re-run")
    parser.add_argument("run_dir",
                        help="run directory (or events.jsonl path) holding "
                             "converge events")
    parser.add_argument("--taus", type=float, nargs="+", default=None,
                        help="exit thresholds on the per-iteration mean "
                             "|delta disparity| (px); default "
                             "0.5 0.2 0.1 0.05 0.02 0.01")
    parser.add_argument("--bucket_by", choices=["bucket", "all", "both"],
                        default="both",
                        help="row granularity: per shape bucket, pooled "
                             "across buckets, or both")
    parser.add_argument("--json", default=None, metavar="PATH",
                        help="write the decision-table JSON to this path; "
                             "'-' prints the JSON to stdout INSTEAD of the "
                             "text table (compare's convention — "
                             "converge_drill's replay leg parses this)")
    parser.add_argument("--out", default=None,
                        help="also write the JSON table to this path")
    p = parser.add_argument_group(
        "policy emission", "freeze one simulated operating point into a "
        "checked-in iter_policy.json artifact — per-bucket (tau, budget, "
        "min_iters) with row provenance — that eval (--iter_policy), serve "
        "(--iter_policy) and the AOT cache compile in as the early-exit "
        "execution mode (schema lint: scripts/check_events.py)")
    p.add_argument("--emit-policy", default=None, metavar="PATH",
                   help="write the policy JSON here (the decision table "
                        "still prints)")
    p.add_argument("--policy-tau", type=float, default=None,
                   help="exit threshold frozen into the policy (px mean "
                        "|delta disparity|; default: the doctor's 0.05)")
    p.add_argument("--policy-min-iters", type=int, default=1,
                   help="iteration floor before a sample may freeze")
    p.add_argument("--policy-margin", type=int, default=1,
                   help="budget = recorded exit p95 + this safety margin "
                        "(clamped to the recorded valid_iters)")
    return parser


def build_numerics_parser() -> argparse.ArgumentParser:
    """The ``cli numerics`` flag surface (consumed by obs/numerics.py)."""
    parser = argparse.ArgumentParser(
        prog="cli numerics",
        description="Numerics-observatory replay: per-leaf gradient-norm "
                    "trends, per-tap activation-range trends, the bf16 "
                    "saturation leaderboard and the first-nonfinite (NaN "
                    "provenance) report over a run's recorded `numerics` "
                    "events — no model re-run")
    parser.add_argument("run_dir",
                        help="run directory (or events.jsonl path) holding "
                             "numerics events")
    parser.add_argument("--top", type=int, default=10,
                        help="rows per table section (worst-first)")
    parser.add_argument("--json", default=None, metavar="PATH",
                        help="write the report JSON to this path; '-' "
                             "prints the JSON to stdout INSTEAD of the "
                             "text report (compare's convention)")
    return parser


def build_loadtest_parser() -> argparse.ArgumentParser:
    """The load-drill flag surface (``cli loadtest``): synthetic
    many-client trace vs a sequential-predict baseline."""
    parser = argparse.ArgumentParser(
        description="RAFT-Stereo TPU serving load test")
    parser.add_argument("--restore_ckpt", default=None,
                        help="reference .pth or orbax state dir")
    parser.add_argument("--run_dir", default="runs/loadtest",
                        help="telemetry root; the sequential baseline lands "
                             "in <run_dir>/seq, the served run in "
                             "<run_dir>/serve (gate: cli compare)")
    parser.add_argument("--shapes", nargs="+",
                        default=["48x96", "64x128", "96x64"],
                        help="raw HxW request shapes (>= 3 distinct buckets "
                             "for the drill)")
    parser.add_argument("--clients", type=int, default=8,
                        help="concurrent client threads")
    parser.add_argument("--requests_per_client", type=int, default=4)
    parser.add_argument("--video_streams", type=int, default=1,
                        help="how many clients are video sessions riding "
                             "flow_init warm starts")
    parser.add_argument("--poison_at", type=int, default=None,
                        help="global request ordinal to corrupt with a NaN "
                             "pixel (per-request isolation drill)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--no_baseline", action="store_true",
                        help="skip the sequential-predict baseline phase")
    parser.add_argument("--no_progress", action="store_true",
                        help="suppress LOADTEST progress lines")
    parser.add_argument("--no_fleet", action="store_true",
                        help="disable schema-v10 fleet stamping (host_id/"
                             "pid extras, clock_anchor, heartbeats) on "
                             "the telemetry streams")
    parser.add_argument("--host_id", default=None,
                        help="host identity stamped on every record "
                             "(default: RAFT_HOST_ID env, else "
                             "<hostname>-<pid>)")
    parser.add_argument("--heartbeat_every", type=float, default=10.0,
                        help="loadtest heartbeat cadence in seconds (0 "
                             "disables the beats; stamping stays on)")
    add_serve_args(parser)
    add_model_args(parser)
    return parser


def _serve_main():
    """Console entry point (``cli serve``): stdlib HTTP front over the
    continuous-batching scheduler; SIGTERM/SIGINT drain, SIGHUP reload."""
    import logging
    import signal

    args = build_serve_parser().parse_args()
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(filename)s:%(lineno)d %(message)s")
    from raft_stereo_tpu.serve import StereoServer
    from raft_stereo_tpu.serve.http import make_http_server, serve_forever
    from raft_stereo_tpu.training.resilience import SignalGuard

    cfg = model_config(args)
    _, variables = load_variables(args.restore_ckpt, cfg)
    tel = None
    if args.run_dir:
        from raft_stereo_tpu.obs import Telemetry
        from raft_stereo_tpu.obs.trace import Tracer
        tel = Telemetry(args.run_dir, stall_deadline_s=None,
                        host_id=args.host_id, fleet=not args.no_fleet)
        Tracer(tel)  # request-lifecycle spans (attaches as tel.tracer)
        tel.run_start(config={"mode": "serve", "port": args.port,
                              "max_batch": args.max_batch,
                              "window": args.window, "iters": args.iters,
                              "iter_policy": args.iter_policy,
                              "adaptive": args.adaptive})
    server = StereoServer(cfg, variables, serve_config(args), telemetry=tel)
    if tel is not None:
        # liveness beats carry the served-request counter so a fleet
        # rollup can see a host that is up but not making progress
        tel.start_heartbeat("serve", args.heartbeat_every,
                            probe=lambda: {"completed": server.slo.completed})
    if args.warm_shapes:
        n = server.warmup(_parse_shapes(args.warm_shapes),
                          batch_sizes=(1, args.max_batch))
        logging.getLogger(__name__).info("serve: warmed %d executables", n)

    reload_wanted = [False]
    if args.ckpt_dir and hasattr(signal, "SIGHUP"):
        signal.signal(signal.SIGHUP,
                      lambda *_: reload_wanted.__setitem__(0, True))

    def maybe_reload():
        if not reload_wanted[0]:
            return
        reload_wanted[0] = False
        from raft_stereo_tpu.training.resilience import find_latest_valid
        ckpt, _reports = find_latest_valid(args.ckpt_dir, args.ckpt_name)
        if ckpt is None:
            raise RuntimeError(
                f"no manifest-valid checkpoint under {args.ckpt_dir}")
        _, fresh = load_variables(ckpt, cfg)
        server.reload(fresh, note=ckpt)

    httpd = make_http_server(server, args.host, args.port,
                             metrics=not args.no_metrics,
                             host_id=tel.host_id if tel is not None else None)
    with SignalGuard() as guard:
        rc = serve_forever(server, httpd,
                           should_stop=lambda: guard.requested,
                           maybe_reload=maybe_reload if args.ckpt_dir
                           else None,
                           drain_timeout_s=args.drain_timeout_s)
    if tel is not None:
        tel.emit("run_end", steps=server.slo.completed, ok=rc == 0)
        tel.close()
    raise SystemExit(rc)


def _loadtest_main():
    """Console entry point (``cli loadtest``): drive the synthetic trace,
    print the accounting summary, exit 1 on any lost admitted request."""
    import json
    import logging
    import os
    import threading

    args = build_loadtest_parser().parse_args()
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(filename)s:%(lineno)d %(message)s")
    from raft_stereo_tpu.inference import StereoPredictor
    from raft_stereo_tpu.obs import Telemetry
    from raft_stereo_tpu.serve import StereoServer
    from raft_stereo_tpu.serve.loadtest import (LoadTestConfig, run_baseline,
                                                run_clients)
    from raft_stereo_tpu.training.resilience import SignalGuard

    cfg = model_config(args)
    _, variables = load_variables(args.restore_ckpt, cfg)
    lt = LoadTestConfig(
        shapes=_parse_shapes(args.shapes), clients=args.clients,
        requests_per_client=args.requests_per_client,
        video_streams=args.video_streams, iters=args.iters,
        poison_at=args.poison_at, seed=args.seed,
        progress=not args.no_progress)
    summary = {"config": {"shapes": args.shapes, "clients": args.clients,
                          "requests_per_client": args.requests_per_client,
                          "video_streams": args.video_streams,
                          "poison_at": args.poison_at,
                          "max_batch": args.max_batch,
                          "window": args.window, "iters": args.iters,
                          "iter_policy": args.iter_policy,
                          "adaptive": args.adaptive}}
    if not args.no_baseline:
        with Telemetry(os.path.join(args.run_dir, "seq"),
                       stall_deadline_s=None, host_id=args.host_id,
                       fleet=not args.no_fleet) as tel_seq:
            tel_seq.run_start(config={"mode": "loadtest-seq"})
            predictor = StereoPredictor(cfg, variables,
                                        valid_iters=args.iters,
                                        bucket=args.bucket)
            summary["sequential"] = run_baseline(predictor, lt, tel_seq)
        print(f"LOADTEST baseline {json.dumps(summary['sequential'])}",
              flush=True)
    tel = Telemetry(os.path.join(args.run_dir, "serve"),
                    stall_deadline_s=None, host_id=args.host_id,
                    fleet=not args.no_fleet)
    from raft_stereo_tpu.obs.trace import Tracer
    Tracer(tel)  # request-lifecycle spans (attaches as tel.tracer)
    tel.run_start(config={"mode": "loadtest-serve"})
    server = StereoServer(cfg, variables, serve_config(args), telemetry=tel)
    tel.start_heartbeat("loadtest", args.heartbeat_every,
                        probe=lambda: {"completed": server.slo.completed})
    # AOT-warm every program the trace can reach — cold buckets at every
    # batch size plus the video streams' warm flavor — so the timed phase
    # measures serving, not compilation
    server.warmup(lt.shapes, batch_sizes=range(1, args.max_batch + 1),
                  iters=lt.iters)
    video_shapes = {lt.shapes[c % len(lt.shapes)]
                    for c in range(lt.video_streams)}
    if video_shapes:
        server.warmup(sorted(video_shapes),
                      batch_sizes=range(
                          1, min(lt.video_streams, args.max_batch) + 1),
                      iters=lt.iters, warm=True)
    with SignalGuard() as guard:
        # mid-drill SIGTERM -> graceful drain: stop admitting, finish every
        # admitted request (the load_drill's zero-lost invariant)
        stop = threading.Event()

        def watch():
            while not stop.is_set():
                if guard.requested:
                    server.request_drain()
                    return
                stop.wait(0.05)

        watcher = threading.Thread(target=watch, daemon=True)
        watcher.start()
        try:
            summary["served"] = run_clients(server, lt, tel)
        finally:
            stop.set()
            watcher.join(timeout=2.0)
    server.request_drain()
    drained = server.join(timeout=600.0)
    summary["served"]["drained"] = drained
    summary["served"]["signal"] = guard.signame
    tel.emit("run_end", steps=server.slo.completed, ok=drained)
    tel.close()
    print(f"LOADTEST summary {json.dumps(summary, sort_keys=True)}",
          flush=True)
    lost = summary["served"]["lost"]
    raise SystemExit(0 if drained and lost == 0 else 1)


def _train_main():
    """Console entry point (`raft-stereo-train`); same surface as
    train_stereo.py."""
    import logging

    args = build_train_parser().parse_args()
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(filename)s:%(lineno)d %(message)s")
    from raft_stereo_tpu.training.trainer import train
    print(f"final checkpoint: {train(model_config(args), train_config(args))}")


def _eval_main():
    """Console entry point (`raft-stereo-eval`); same surface as
    evaluate_stereo.py."""
    import logging

    from raft_stereo_tpu.eval.validate import VALIDATORS, validate_middlebury
    from raft_stereo_tpu.inference import StereoPredictor

    args = build_eval_parser().parse_args()
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(filename)s:%(lineno)d %(message)s")
    # the reference enables mixed precision automatically for the kernel
    # implementations (evaluate_stereo.py:229-231); mirror that for the
    # pallas/fused variants (and their *_cuda aliases)
    if (args.corr_implementation.endswith(("_cuda", "_pallas"))
            or args.corr_implementation in ("fused", "memoryless")) \
            and not args.mixed_precision:
        logging.getLogger(__name__).info(
            "enabling mixed precision for %s", args.corr_implementation)
        args.mixed_precision = True
    cfg = model_config(args)
    _, variables = load_variables(args.restore_ckpt, cfg)
    if args.iter_policy and not args.no_numerics:
        # the adaptive path carries no numerics taps (inference.py guard)
        logging.getLogger(__name__).info(
            "disabling numerics taps for --iter_policy run")
    predictor = StereoPredictor(cfg, variables, valid_iters=args.valid_iters,
                                bucket=args.bucket,
                                converge=not args.no_converge,
                                iter_epe=args.iter_epe,
                                numerics=(not args.no_numerics
                                          and not args.iter_policy),
                                iter_policy=args.iter_policy)
    from raft_stereo_tpu.eval.stream import StreamConfig
    stream = StreamConfig(
        enabled={"auto": None, "on": True, "off": False}[args.stream],
        window=args.stream_window, microbatch=args.stream_microbatch,
        decode_workers=args.decode_workers)
    tel = None
    if args.run_dir:
        from raft_stereo_tpu.obs import Telemetry
        tel = Telemetry(args.run_dir, stall_deadline_s=None)
        tel.run_start(config={"dataset": args.dataset,
                              "valid_iters": args.valid_iters,
                              "stream": args.stream,
                              "stream_window": args.stream_window,
                              "stream_microbatch": args.stream_microbatch,
                              "converge": not args.no_converge,
                              "iter_epe": args.iter_epe,
                              "numerics": not args.no_numerics,
                              "iter_policy": args.iter_policy,
                              "iter_policy_digest": predictor.policy_digest})
    try:
        if args.dataset.startswith("middlebury_"):
            results = validate_middlebury(predictor, args.data_root,
                                          args.valid_iters,
                                          split=args.dataset.split("_")[1],
                                          telemetry=tel, stream=stream)
        else:
            results = VALIDATORS[args.dataset](predictor, args.data_root,
                                               args.valid_iters,
                                               telemetry=tel, stream=stream)
    except BaseException as e:
        if tel is not None:
            tel.error(e)
            tel.emit("run_end", steps=0, ok=False)
            tel.close()
        raise
    if tel is not None:
        tel.emit("run_end", steps=tel.steps, ok=True)
        tel.close()
    print(results)


def main(argv: Optional[Sequence[str]] = None) -> int:
    """Subcommand dispatch for ``python -m raft_stereo_tpu.cli``:

    * ``telemetry <run_dir>`` — summarize a run's events.jsonl + profiler
      trace (obs/summarize.py),
    * ``compare <baseline> <candidate>`` — regression-gate two runs' event
      logs (obs/compare.py; exit 1 on regression),
    * ``lint [--graph|--ast]`` — graftlint: jaxpr/HLO contract rules +
      tracer-safety AST lint (raft_stereo_tpu/analysis/; exit 1 on
      unsuppressed error-severity findings),
    * ``timeline <run_dir>`` — export the run's span/event/device-trace
      timeline as Chrome/Perfetto JSON (obs/timeline.py),
    * ``doctor <run_dir>`` — rule-driven bottleneck diagnosis with
      evidence lines (obs/doctor.py); pointed at a directory of per-host
      run dirs it emits the fleet verdicts (STRAGGLER / DEAD_HOST /
      DESYNC),
    * ``fleet <fleet_dir>`` — merge N per-host run dirs into one
      clock-aligned rollup + a merged Perfetto timeline with a
      process-group per host (obs/fleet.py),
    * ``converge <run_dir>`` — the early-exit what-if simulator over a
      run's recorded convergence curves (obs/converge.py; the ROADMAP 1(b)
      decision table, computed offline),
    * ``numerics <run_dir>`` — the numerics-observatory replay: per-leaf
      gradient-norm trends, per-tap activation ranges, the bf16
      saturation leaderboard and the first-nonfinite NaN-provenance
      report (obs/numerics.py),
    * ``serve`` — continuous-batching HTTP serving with SLO telemetry,
      graceful drain and SIGHUP hot reload (raft_stereo_tpu/serve),
    * ``loadtest`` — the synthetic many-client serving drill vs a
      sequential baseline (exit 1 on any lost admitted request),
    * ``train`` / ``eval`` — the console entry points, for environments
      without the installed scripts.
    """
    import sys

    # opt-in lock-order witness (RAFT_LOCK_WITNESS=<dump path>): installed
    # before dispatch so every subcommand's threads are witnessed
    from raft_stereo_tpu.obs.lockwitness import maybe_install
    maybe_install()

    argv = list(sys.argv[1:] if argv is None else argv)
    commands = ("telemetry", "compare", "lint", "timeline", "doctor",
                "fleet", "converge", "numerics", "train", "eval", "serve",
                "loadtest")
    if not argv or argv[0] not in commands:
        print(f"usage: python -m raft_stereo_tpu.cli {{{'|'.join(commands)}}} "
              "...", file=sys.stderr)
        return 2
    cmd, rest = argv[0], argv[1:]
    if cmd == "telemetry":
        from raft_stereo_tpu.obs.summarize import main as telemetry_main
        return telemetry_main(rest)
    if cmd == "compare":
        from raft_stereo_tpu.obs.compare import main as compare_main
        return compare_main(rest)
    if cmd == "lint":
        from raft_stereo_tpu.analysis.runner import main as lint_main
        return lint_main(rest)
    if cmd == "timeline":
        from raft_stereo_tpu.obs.timeline import main as timeline_main
        return timeline_main(rest)
    if cmd == "doctor":
        from raft_stereo_tpu.obs.doctor import main as doctor_main
        return doctor_main(rest)
    if cmd == "fleet":
        from raft_stereo_tpu.obs.fleet import main as fleet_main
        return fleet_main(rest)
    if cmd == "converge":
        from raft_stereo_tpu.obs.converge import main as converge_main
        return converge_main(rest)
    if cmd == "numerics":
        from raft_stereo_tpu.obs.numerics import main as numerics_main
        return numerics_main(rest)
    # the remaining mains parse sys.argv via argparse; present the
    # remainder as the whole command line
    sys.argv = [f"{sys.argv[0]} {cmd}"] + rest
    {"train": _train_main, "eval": _eval_main,
     "serve": _serve_main, "loadtest": _loadtest_main}[cmd]()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
