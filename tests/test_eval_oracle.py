"""Validator metric parity against the reference's evaluate_stereo.py (oracle).

The r4 review found two validator deviations (ETH3D/Middlebury D1 weighting,
Middlebury mask) that survived four rounds because tests/test_eval.py only
checked key names and ranges. This module runs the reference's actual
``validate_*`` functions (torch, CPU) as the oracle, two ways:

* **aggregation parity** — both sides score IDENTICAL stub predictions on the
  same synthetic dataset trees, so mask semantics, thresholds, and image- vs
  pixel-weighting must match to float tolerance (the model is out of the
  loop);
* **end-to-end** — a randomly-initialized reference model's converted weights
  drive real forwards on both stacks over the same tree (looser tolerance:
  forward parity is the converter test's job, here it bounds the metric gap).

The reference validators hardcode ``.cuda()`` and relative dataset roots and
their import chain pulls torchvision/skimage (absent in this image), so the
oracle runs under a monkeypatched environment: ``Tensor.cuda`` -> identity,
cwd -> the synthetic tree, stub torchvision/skimage modules (the validators
never instantiate an augmentor — ``aug_params={}`` has no crop_size).
"""

import importlib.util
import os
import sys
import types
import zlib

import numpy as np
import pytest
from PIL import Image

import jax

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.data import frame_utils
from raft_stereo_tpu.eval import validate
from raft_stereo_tpu.inference import StereoPredictor
from raft_stereo_tpu.models import init_model
from raft_stereo_tpu.utils import convert_state_dict
from raft_stereo_tpu.utils.checkpoint_convert import validate_against_variables

from conftest import REFERENCE_DIR, requires_reference
from test_checkpoint_convert import _torch_reference_model

H, W = 48, 96


# --------------------------------------------------------------- ref import

def _stub_module(name, **attrs):
    mod = types.ModuleType(name)
    for k, v in attrs.items():
        setattr(mod, k, v)
    return mod


@pytest.fixture(scope="module")
def ref_eval(torch_reference):
    """Import /root/reference/evaluate_stereo.py with its import chain
    satisfied (torchvision/skimage stubs; the validators never touch them)."""
    class _NoOp:
        def __init__(self, *a, **k):
            pass

    for name, attrs in [
        ("torchvision", {}),
        ("torchvision.transforms",
         dict(ColorJitter=_NoOp, Compose=_NoOp, functional=None)),
        ("skimage", dict(color=None, io=None)),
    ]:
        if name not in sys.modules:
            sys.modules[name] = _stub_module(name, **attrs)
    sys.modules["torchvision"].transforms = sys.modules["torchvision.transforms"]
    core_dir = os.path.join(REFERENCE_DIR, "core")
    if core_dir not in sys.path:
        sys.path.insert(0, core_dir)
    spec = importlib.util.spec_from_file_location(
        "ref_evaluate_stereo",
        os.path.join(REFERENCE_DIR, "evaluate_stereo.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _cpu_torch_cuda(monkeypatch):
    """The reference validators call ``.cuda()`` unconditionally."""
    import torch

    monkeypatch.setattr(torch.Tensor, "cuda",
                        lambda self, *a, **k: self, raising=True)


# ------------------------------------------------------------ synthetic trees

def _save_png(path, arr):
    path.parent.mkdir(parents=True, exist_ok=True)
    Image.fromarray(arr).save(path)


def _images(rng, path_l, path_r):
    _save_png(path_l, rng.integers(0, 255, (H, W, 3), dtype=np.uint8))
    _save_png(path_r, rng.integers(0, 255, (H, W, 3), dtype=np.uint8))


def _write_trees(root):
    """One shared tree per dataset family, in the reference's layout, with
    GT crafted to exercise every mask branch: disp >= 512 (ETH3D/Things
    validity), disp >= 192 (Things D1 range), inf disp (Middlebury
    ``gt > -1000``), zero disp (KITTI sparsity), mixed nocc masks."""
    import cv2

    rng = np.random.default_rng(42)
    ds = root / "datasets"

    for i in range(2):  # ETH3D
        scene = ds / "ETH3D" / "two_view_training" / f"scene_{i}"
        gt = ds / "ETH3D" / "two_view_training_gt" / f"scene_{i}"
        _images(rng, scene / "im0.png", scene / "im1.png")
        disp = rng.uniform(0, 8, (H, W)).astype(np.float32)
        disp[rng.uniform(size=(H, W)) < 0.07] = 600.0  # fails disp < 512
        gt.mkdir(parents=True, exist_ok=True)
        frame_utils.write_pfm(str(gt / "disp0GT.pfm"), disp)
        # nocc mask exists on disk but must NOT be consulted (read_gen path)
        _save_png(gt / "mask0nocc.png",
                  (rng.uniform(size=(H, W)) > 0.3).astype(np.uint8) * 255)

    for i in range(2):  # KITTI-15
        kroot = ds / "KITTI" / "training"
        _images(rng, kroot / "image_2" / f"00000{i}_10.png",
                kroot / "image_3" / f"00000{i}_10.png")
        disp = rng.uniform(0.5, 40, (H, W))
        disp[rng.uniform(size=(H, W)) < 0.2] = 0.0  # sparse: invalid
        (kroot / "disp_occ_0").mkdir(parents=True, exist_ok=True)
        cv2.imwrite(str(kroot / "disp_occ_0" / f"00000{i}_10.png"),
                    (disp * 256.0).astype(np.uint16))

    mb = ds / "Middlebury" / "MiddEval3"  # Middlebury F
    scene = mb / "trainingF" / "SceneA"
    _images(rng, scene / "im0.png", scene / "im1.png")
    disp = rng.uniform(0, 8, (H, W)).astype(np.float32)
    disp[rng.uniform(size=(H, W)) < 0.1] = np.inf  # fails gt > -1000
    frame_utils.write_pfm(str(scene / "disp0GT.pfm"), disp)
    _save_png(scene / "mask0nocc.png",
              (rng.uniform(size=(H, W)) > 0.3).astype(np.uint8) * 255)
    (mb / "official_train.txt").write_text("SceneA\n")

    for i in range(2):  # FlyingThings3D TEST
        froot = ds / "FlyingThings3D"
        left = froot / "frames_finalpass" / "TEST" / "A" / f"{i:04d}" / "left"
        right = froot / "frames_finalpass" / "TEST" / "A" / f"{i:04d}" / "right"
        _images(rng, left / "0006.png", right / "0006.png")
        disp = rng.uniform(0, 8, (H, W)).astype(np.float32)
        disp[rng.uniform(size=(H, W)) < 0.07] = 250.0  # fails |disp| < 192
        disp[rng.uniform(size=(H, W)) < 0.05] = 600.0  # fails disp < 512
        dpath = froot / "disparity" / "TEST" / "A" / f"{i:04d}" / "left"
        dpath.mkdir(parents=True, exist_ok=True)
        frame_utils.write_pfm(str(dpath / "0006.pfm"), disp)
    return ds


@pytest.fixture(scope="module")
def tree(tmp_path_factory):
    root = tmp_path_factory.mktemp("oracle")
    _write_trees(root)
    return root


# ------------------------------------------------------------------- stubs

def _stub_flows(n, seed):
    rng = np.random.default_rng(seed)
    return [rng.uniform(-9, 1, (H, W)).astype(np.float32) for _ in range(n)]


class _RefStubModel:
    """Drop-in for the torch model: returns precomputed flows, padded the way
    the validator's own InputPadder will unpad them (pad->unpad is exact)."""

    def __init__(self, flows):
        self.flows = flows
        self.i = 0

    def eval(self):
        pass

    def __call__(self, image1, image2, iters=None, test_mode=True):
        import torch

        from utils.utils import InputPadder  # the reference's

        t = torch.from_numpy(self.flows[self.i])[None, None]
        self.i += 1
        padder = InputPadder(t.shape, divis_by=32)
        return None, padder.pad(t)[0]


class _OurStubPredictor:
    def __init__(self, flows):
        self.flows = flows
        self.i = 0

    def __call__(self, image1, image2, iters=None):
        return self.predict_timed(image1, image2, iters)[0]

    def predict_timed(self, image1, image2, iters=None):
        f = self.flows[self.i]
        self.i += 1
        return f[None, :, :, None], 1e-3


# ------------------------------------------------------- aggregation parity

CASES = [
    ("eth3d", 2, "validate_eth3d", validate.validate_eth3d, {}),
    ("kitti", 2, "validate_kitti", validate.validate_kitti, {}),
    ("things", 2, "validate_things", validate.validate_things, {}),
    ("middlebury", 1, "validate_middlebury", validate.validate_middlebury,
     {"split": "F"}),
]


@requires_reference
@pytest.mark.parametrize("name,n,ref_fn,our_fn,kw",
                         CASES, ids=[c[0] for c in CASES])
def test_aggregation_matches_reference(tree, ref_eval, monkeypatch,
                                       name, n, ref_fn, our_fn, kw):
    """Identical predictions -> metrics must agree to float tolerance. This
    pins mask semantics (ETH3D disp<512 via read_gen, Middlebury's no-op
    valid>=-0.5, KITTI disp>0, Things |disp|<192) AND aggregation (image-
    weighted D1 for ETH3D/Middlebury, pixel-weighted for KITTI/Things)."""
    flows = _stub_flows(n, seed=zlib.crc32(name.encode()))

    monkeypatch.chdir(tree)  # the reference's roots are cwd-relative
    ref_kw = {"split": kw["split"]} if "split" in kw else {}
    ref = getattr(ref_eval, ref_fn)(_RefStubModel(flows), iters=2, **ref_kw)

    ours = our_fn(_OurStubPredictor(flows), root=str(tree / "datasets"),
                  iters=2, **kw)

    for key, ref_val in ref.items():
        assert key in ours, (key, ours)
        np.testing.assert_allclose(ours[key], ref_val, rtol=1e-5, atol=1e-5,
                                   err_msg=f"{name}:{key}")


# ------------------------------------------------------------- end-to-end

@requires_reference
def test_end_to_end_converted_weights(tree, ref_eval, monkeypatch):
    """Reference model + converted weights, real forwards on both stacks.
    Tolerances bound compounded forward drift over 2 refinement iterations
    (bitwise parity is the converter test's job, not this one's)."""
    cfg = RAFTStereoConfig()
    tmodel = _torch_reference_model(cfg)
    converted = convert_state_dict(tmodel.state_dict())
    _, variables = init_model(jax.random.PRNGKey(0), cfg, (1, H, W, 3))
    converted = validate_against_variables(converted, variables)
    predictor = StereoPredictor(cfg, converted, valid_iters=2)

    monkeypatch.chdir(tree)
    ref = ref_eval.validate_eth3d(tmodel, iters=2)
    ours = validate.validate_eth3d(predictor, root=str(tree / "datasets"),
                                   iters=2)
    np.testing.assert_allclose(ours["eth3d-epe"], ref["eth3d-epe"],
                               rtol=2e-3)
    assert abs(ours["eth3d-d1"] - ref["eth3d-d1"]) < 0.5
