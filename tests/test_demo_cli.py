"""demo.py driven end-to-end: PNG pair -> console script -> colormap + .npy.

The reference demo (demo.py:23-52) is a glob -> model -> jet-PNG pipeline;
this pins ours as an actual CLI drive (arg parsing, checkpoint restore,
predictor, output files), not just library calls — r4 review asked for the
"driven end-to-end in verification" claim to live in the suite.
"""

import importlib.util
import os
import sys

import numpy as np
import pytest
from PIL import Image

import jax

from raft_stereo_tpu.config import RAFTStereoConfig, TrainConfig
from raft_stereo_tpu.models import init_model
from raft_stereo_tpu.training.checkpoint import save_train_state
from raft_stereo_tpu.training.optim import fetch_optimizer
from raft_stereo_tpu.training.state import TrainState


def _load_demo():
    """Load the REPO-ROOT demo.py by path: a bare ``import demo`` resolves
    to the reference checkout's demo.py once any torch-oracle test has run
    (conftest's session fixture puts /root/reference at sys.path[0]), which
    then fails on its own CUDA-repo imports — the suite-order flake this
    helper removes."""
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "demo.py")
    spec = importlib.util.spec_from_file_location("repo_root_demo", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def tiny_ckpt(tmp_path_factory):
    """Orbax full-train-state checkpoint with random-init weights."""
    root = tmp_path_factory.mktemp("demo_ckpt")
    _, variables = init_model(jax.random.PRNGKey(0), RAFTStereoConfig(),
                              (1, 48, 96, 3))
    state = TrainState.create(variables, fetch_optimizer(TrainConfig()))
    save_train_state(str(root), "tiny", jax.device_get(state))
    return str(root / "tiny")


def test_demo_end_to_end(tmp_path, tiny_ckpt, monkeypatch):
    rng = np.random.default_rng(3)
    for i in range(2):
        for side in ("left", "right"):
            Image.fromarray(rng.integers(0, 255, (48, 96, 3), dtype=np.uint8)
                            ).save(tmp_path / f"{side}_{i}.png")
    out_dir = tmp_path / "out"

    demo = _load_demo()  # repo-root CLI (console script `raft-stereo-demo`)

    monkeypatch.setattr(sys, "argv", [
        "demo.py", "--restore_ckpt", tiny_ckpt,
        "-l", str(tmp_path / "left_*.png"),
        "-r", str(tmp_path / "right_*.png"),
        "--output_directory", str(out_dir),
        "--valid_iters", "2", "--save_numpy",
    ])
    demo.main()

    for i in range(2):
        png = out_dir / f"left_{i}-disparity.png"
        npy = out_dir / f"left_{i}.npy"
        assert png.exists() and npy.exists()
        disp = np.load(npy)
        assert disp.shape == (48, 96)
        assert np.isfinite(disp).all()
        # the colormapped PNG decodes to the input's spatial shape
        assert np.asarray(Image.open(png)).shape[:2] == (48, 96)


def test_demo_mismatched_globs_exit(tmp_path, tiny_ckpt, monkeypatch):
    Image.fromarray(np.zeros((48, 96, 3), np.uint8)).save(tmp_path / "l0.png")
    demo = _load_demo()

    monkeypatch.setattr(sys, "argv", [
        "demo.py", "--restore_ckpt", tiny_ckpt,
        "-l", str(tmp_path / "l*.png"), "-r", str(tmp_path / "r*.png"),
        "--output_directory", str(tmp_path / "out"),
    ])
    with pytest.raises(SystemExit):
        demo.main()
