import numpy as np
import jax
import jax.numpy as jnp
import pytest

from raft_stereo_tpu.nn import (
    BasicEncoder,
    BasicMotionEncoder,
    BasicMultiUpdateBlock,
    ConvGRU,
    FlowHead,
    FrozenBatchNorm,
    InstanceNorm,
    MultiBasicEncoder,
    ResidualBlock,
)
from raft_stereo_tpu.config import RAFTStereoConfig


def n_params(variables):
    return sum(x.size for x in jax.tree.leaves(variables.get("params", {})))


class TestNorms:
    def test_frozen_batchnorm_matches_torch_eval(self):
        torch = pytest.importorskip("torch")
        rng = np.random.default_rng(0)
        x = rng.standard_normal((2, 4, 5, 8)).astype(np.float32)
        mean = rng.standard_normal(8).astype(np.float32)
        var = rng.uniform(0.5, 2.0, 8).astype(np.float32)
        scale = rng.standard_normal(8).astype(np.float32)
        bias = rng.standard_normal(8).astype(np.float32)

        bn = FrozenBatchNorm(features=8)
        variables = {
            "params": {"scale": jnp.asarray(scale), "bias": jnp.asarray(bias)},
            "batch_stats": {"mean": jnp.asarray(mean), "var": jnp.asarray(var)},
        }
        got = np.asarray(bn.apply(variables, jnp.asarray(x)))

        tbn = torch.nn.BatchNorm2d(8).eval()
        with torch.no_grad():
            tbn.weight.copy_(torch.from_numpy(scale))
            tbn.bias.copy_(torch.from_numpy(bias))
            tbn.running_mean.copy_(torch.from_numpy(mean))
            tbn.running_var.copy_(torch.from_numpy(var))
            want = tbn(torch.from_numpy(x).permute(0, 3, 1, 2)) \
                .permute(0, 2, 3, 1).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_instance_norm_matches_torch(self):
        torch = pytest.importorskip("torch")
        rng = np.random.default_rng(1)
        x = rng.standard_normal((2, 6, 7, 5)).astype(np.float32)
        got = np.asarray(InstanceNorm().apply({}, jnp.asarray(x)))
        want = torch.nn.InstanceNorm2d(5)(
            torch.from_numpy(x).permute(0, 3, 1, 2)).permute(0, 2, 3, 1).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


class TestBlocks:
    @pytest.mark.parametrize("norm", ["group", "batch", "instance", "none"])
    def test_residual_block_shapes(self, norm):
        block = ResidualBlock(in_planes=16, planes=24, norm_fn=norm, stride=2)
        x = jnp.zeros((1, 8, 8, 16))
        variables = block.init(jax.random.PRNGKey(0), x)
        out = block.apply(variables, x)
        assert out.shape == (1, 4, 4, 24)

    def test_residual_identity_path_has_no_projection(self):
        block = ResidualBlock(in_planes=16, planes=16, norm_fn="none", stride=1)
        variables = block.init(jax.random.PRNGKey(0), jnp.zeros((1, 4, 4, 16)))
        assert "down_conv" not in variables["params"]

    def test_convgru_blend(self):
        """z=0 keeps h; the gate structure matches update.py:23-32."""
        gru = ConvGRU(hidden_dim=4)
        h = jnp.ones((1, 3, 3, 4))
        x = jnp.zeros((1, 3, 3, 6))
        cz = jnp.full((1, 3, 3, 4), -100.0)  # sigmoid -> 0: keep hidden state
        cr = jnp.zeros((1, 3, 3, 4))
        cq = jnp.zeros((1, 3, 3, 4))
        variables = gru.init(jax.random.PRNGKey(0), h, cz, cr, cq, x)
        out = gru.apply(variables, h, cz, cr, cq, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(h), atol=1e-5)


class TestEncoders:
    @pytest.mark.parametrize("downsample,scale", [(2, 4), (3, 8)])
    def test_basic_encoder_output_scale(self, downsample, scale):
        enc = BasicEncoder(output_dim=256, norm_fn="instance",
                           downsample=downsample)
        x = jnp.zeros((2, 64, 96, 3))
        variables = enc.init(jax.random.PRNGKey(0), x)
        out = enc.apply(variables, x)
        assert out.shape == (2, 64 // scale, 96 // scale, 256)

    def test_multi_encoder_three_scales(self):
        enc = MultiBasicEncoder(output_dim=((128,) * 3, (128,) * 3),
                                norm_fn="batch", downsample=2)
        x = jnp.zeros((1, 64, 96, 3))
        variables = enc.init(jax.random.PRNGKey(0), x)
        o08, o16, o32 = enc.apply(variables, x)
        assert len(o08) == 2 and len(o16) == 2 and len(o32) == 2
        assert o08[0].shape == (1, 16, 24, 128)
        assert o16[0].shape == (1, 8, 12, 128)
        assert o32[0].shape == (1, 4, 6, 128)

    def test_multi_encoder_dual_inp_splits_batch(self):
        enc = MultiBasicEncoder(output_dim=((128,) * 3,), norm_fn="batch",
                                downsample=2)
        x = jnp.zeros((4, 32, 32, 3))  # doubled batch (left+right)
        variables = enc.init(jax.random.PRNGKey(0), x, dual_inp=True)
        o08, o16, o32, trunk = enc.apply(variables, x, dual_inp=True)
        assert o08[0].shape[0] == 2
        assert trunk.shape[0] == 4


class TestUpdateBlock:
    def _make(self, cfg):
        block = BasicMultiUpdateBlock(cfg)
        hd = cfg.hidden_dims
        b, h, w = 1, 8, 12
        net = (jnp.zeros((b, h, w, hd[2])), jnp.zeros((b, h // 2, w // 2, hd[1])),
               jnp.zeros((b, h // 4, w // 4, hd[0])))[:cfg.n_gru_layers]
        inp = tuple(
            (jnp.zeros_like(net[i]),) * 3 for i in range(cfg.n_gru_layers))
        corr = jnp.zeros((b, h, w, cfg.corr_channels))
        flow = jnp.zeros((b, h, w, 2))
        return block, net, inp, corr, flow

    def test_full_update_outputs(self):
        cfg = RAFTStereoConfig()
        block, net, inp, corr, flow = self._make(cfg)
        variables = block.init(jax.random.PRNGKey(0), net, inp, corr, flow)
        net2, mask, delta = block.apply(variables, net, inp, corr, flow)
        assert len(net2) == 3
        assert mask.shape == (1, 8, 12, 9 * 16)
        assert delta.shape == (1, 8, 12, 2)

    def test_gru_only_update_false(self):
        cfg = RAFTStereoConfig(slow_fast_gru=True)
        block, net, inp, corr, flow = self._make(cfg)
        variables = block.init(jax.random.PRNGKey(0), net, inp, corr, flow)
        net2 = block.apply(variables, net, inp, iter08=False, iter16=True,
                           iter32=True, update=False)
        assert len(net2) == 3 and net2[0].shape == net[0].shape


class TestTorchParamParity:
    """Param-count parity with the reference model (SURVEY §2: ~11M params).

    Exact per-module counts are compared so a missing head or a wrong kernel
    size shows up as a specific component, not a diff of totals."""

    def test_total_param_count_matches_reference(self, torch_reference):
        import argparse
        import torch
        from core.raft_stereo import RAFTStereo as TorchRAFTStereo

        args = argparse.Namespace(
            hidden_dims=[128, 128, 128], corr_implementation="reg",
            shared_backbone=False, corr_levels=4, corr_radius=4,
            n_downsample=2, context_norm="batch", slow_fast_gru=False,
            n_gru_layers=3, mixed_precision=False)
        tmodel = TorchRAFTStereo(args)
        want = sum(p.numel() for p in tmodel.parameters() if p.requires_grad)

        from raft_stereo_tpu.models import init_model
        cfg = RAFTStereoConfig()
        _, variables = init_model(jax.random.PRNGKey(0), cfg, (1, 32, 32, 3))
        got = n_params(variables)
        assert got == want, f"param count {got} != reference {want}"

    def test_shared_backbone_param_count(self, torch_reference):
        import argparse
        from core.raft_stereo import RAFTStereo as TorchRAFTStereo

        args = argparse.Namespace(
            hidden_dims=[128, 128, 128], corr_implementation="reg",
            shared_backbone=True, corr_levels=4, corr_radius=4,
            n_downsample=3, context_norm="batch", slow_fast_gru=True,
            n_gru_layers=2, mixed_precision=False)
        tmodel = TorchRAFTStereo(args)
        want = sum(p.numel() for p in tmodel.parameters() if p.requires_grad)
        # torch instantiates modules its forward never uses at n_gru_layers=2
        # (cnet.layer5 + outputs32 heads, update_block.gru32); our functional
        # init only materializes executed params, so subtract exactly those.
        unused = sum(
            p.numel() for m in [tmodel.cnet.layer5, tmodel.cnet.outputs32,
                                tmodel.update_block.gru32]
            for p in m.parameters())

        from raft_stereo_tpu.models import init_model
        cfg = RAFTStereoConfig(shared_backbone=True, n_downsample=3,
                               n_gru_layers=2, slow_fast_gru=True)
        _, variables = init_model(jax.random.PRNGKey(0), cfg, (1, 32, 32, 3))
        got = n_params(variables)
        assert got == want - unused, \
            f"param count {got} != reference used {want - unused}"


def test_split_input_conv_paths_agree(monkeypatch):
    """The split (per-part kernel slices) and concat gate-conv formulations
    must agree — the area threshold only picks between them."""
    import jax
    import jax.numpy as jnp
    from raft_stereo_tpu.nn import gru as gru_mod
    from raft_stereo_tpu.nn.gru import ConvGRU

    rng = np.random.default_rng(0)
    h = jnp.asarray(rng.normal(size=(1, 10, 12, 16)), jnp.float32)
    x1 = jnp.asarray(rng.normal(size=(1, 10, 12, 8)), jnp.float32)
    x2 = jnp.asarray(rng.normal(size=(1, 10, 12, 4)), jnp.float32)
    cz = cr = cq = jnp.zeros((1, 10, 12, 16), jnp.float32)

    cell = ConvGRU(hidden_dim=16)
    variables = cell.init(jax.random.PRNGKey(0), h, cz, cr, cq, x1, x2)

    monkeypatch.setattr(gru_mod, "_SPLIT_CONV_MIN_AREA", 1)  # force split
    split_out = cell.apply(variables, h, cz, cr, cq, x1, x2)
    monkeypatch.setattr(gru_mod, "_SPLIT_CONV_MIN_AREA", 1 << 30)  # concat
    concat_out = cell.apply(variables, h, cz, cr, cq, x1, x2)

    np.testing.assert_allclose(np.asarray(split_out), np.asarray(concat_out),
                               atol=1e-5, rtol=1e-5)
