"""Checkpoint converter: torch state_dict -> flax variables, end-to-end parity.

The reference ships no weights in-repo, so the oracle is a *randomly
initialized* reference model: build core/raft_stereo.py's RAFTStereo, convert
its ``state_dict()``, and require the flax forward to match the torch forward
on the same images. This is the strictest possible converter test — every
renamed tensor, layout transpose, and BN-stat mapping must be right or the
iterative refinement diverges.
"""

import numpy as np
import pytest

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.models import init_model
from raft_stereo_tpu.utils import convert_state_dict
from raft_stereo_tpu.utils.checkpoint_convert import validate_against_variables

from conftest import requires_reference


def _torch_reference_model(cfg: RAFTStereoConfig, seed: int = 7):
    import argparse
    import torch

    from core.raft_stereo import RAFTStereo as TorchRAFTStereo

    args = argparse.Namespace(
        hidden_dims=list(cfg.hidden_dims),
        corr_implementation="reg",
        shared_backbone=cfg.shared_backbone,
        corr_levels=cfg.corr_levels,
        corr_radius=cfg.corr_radius,
        n_downsample=cfg.n_downsample,
        context_norm=cfg.context_norm,
        slow_fast_gru=cfg.slow_fast_gru,
        n_gru_layers=cfg.n_gru_layers,
        mixed_precision=False,
    )
    torch.manual_seed(seed)
    model = TorchRAFTStereo(args)
    model.eval()
    return model


@requires_reference
@pytest.mark.parametrize("cfg", [
    RAFTStereoConfig(),
    RAFTStereoConfig(context_norm="instance"),   # iRaftStereo_RVC preset
], ids=["default", "rvc-instance"])
def test_converted_forward_matches_torch(torch_reference, cfg):
    import torch

    tmodel = _torch_reference_model(cfg)
    converted = convert_state_dict(tmodel.state_dict())

    model, variables = init_model(
        __import__("jax").random.PRNGKey(0), cfg, (1, 64, 96, 3))
    converted = validate_against_variables(converted, variables)

    rng = np.random.default_rng(3)
    img1 = rng.uniform(0, 255, (1, 48, 96, 3)).astype(np.float32)
    img2 = rng.uniform(0, 255, (1, 48, 96, 3)).astype(np.float32)

    with torch.no_grad():
        t1 = torch.from_numpy(img1.transpose(0, 3, 1, 2))
        t2 = torch.from_numpy(img2.transpose(0, 3, 1, 2))
        t_low, t_up = tmodel(t1, t2, iters=5, test_mode=True)

    j_low, j_up = model.apply(converted, img1, img2, iters=5, test_mode=True)

    t_up_np = t_up.numpy().transpose(0, 2, 3, 1)      # NCHW -> NHWC
    t_low_np = t_low.numpy().transpose(0, 2, 3, 1)

    np.testing.assert_allclose(np.asarray(j_low), t_low_np, atol=2e-3,
                               rtol=1e-4)
    np.testing.assert_allclose(np.asarray(j_up), t_up_np, atol=5e-3, rtol=1e-4)


@requires_reference
def test_shared_backbone_conversion(torch_reference):
    """The realtime preset's shared-backbone path converts and validates."""
    import jax

    cfg = RAFTStereoConfig(shared_backbone=True, n_downsample=3,
                           n_gru_layers=2, slow_fast_gru=True)
    tmodel = _torch_reference_model(cfg)
    converted = convert_state_dict(tmodel.state_dict())
    # width >= 128: at 1/8 resolution the corr pyramid needs W2 divisible
    # through num_levels poolings (the torch oracle hard-fails below that)
    model, variables = init_model(jax.random.PRNGKey(0), cfg, (1, 64, 128, 3))
    # the torch model instantiates layer5/outputs32 even with n_gru_layers=2;
    # those weights are dead and pruned here
    converted = validate_against_variables(converted, variables)

    import torch
    rng = np.random.default_rng(5)
    img1 = rng.uniform(0, 255, (1, 64, 128, 3)).astype(np.float32)
    img2 = rng.uniform(0, 255, (1, 64, 128, 3)).astype(np.float32)
    with torch.no_grad():
        t_low, t_up = tmodel(
            torch.from_numpy(img1.transpose(0, 3, 1, 2)),
            torch.from_numpy(img2.transpose(0, 3, 1, 2)),
            iters=4, test_mode=True)
    j_low, j_up = model.apply(converted, img1, img2, iters=4, test_mode=True)
    np.testing.assert_allclose(
        np.asarray(j_up), t_up.numpy().transpose(0, 2, 3, 1),
        atol=5e-3, rtol=1e-4)


@requires_reference
def test_strict_validation_catches_mismatch(torch_reference):
    import jax

    cfg = RAFTStereoConfig()
    tmodel = _torch_reference_model(cfg)
    converted = convert_state_dict(tmodel.state_dict())
    del converted["params"]["fnet"]["conv2"]
    _, variables = init_model(jax.random.PRNGKey(0), cfg, (1, 64, 96, 3))
    with pytest.raises(ValueError, match="missing"):
        validate_against_variables(converted, variables)


@requires_reference
def test_reverse_conversion_strict_roundtrip(torch_reference):
    """flax -> torch state_dict loads strict=True and reproduces the model."""
    import torch

    from raft_stereo_tpu.utils.checkpoint_convert import (
        convert_to_torch_state_dict)

    cfg = RAFTStereoConfig()
    tmodel = _torch_reference_model(cfg, seed=11)
    converted = convert_state_dict(tmodel.state_dict())

    back = convert_to_torch_state_dict(converted, data_parallel_prefix=False)
    tmodel2 = _torch_reference_model(cfg, seed=99)  # different init
    tmodel2.load_state_dict(back, strict=True)

    rng = np.random.default_rng(13)
    img1 = rng.uniform(0, 255, (1, 48, 96, 3)).astype(np.float32)
    img2 = rng.uniform(0, 255, (1, 48, 96, 3)).astype(np.float32)
    t1 = torch.from_numpy(img1.transpose(0, 3, 1, 2))
    t2 = torch.from_numpy(img2.transpose(0, 3, 1, 2))
    with torch.no_grad():
        _, up_a = tmodel(t1, t2, iters=4, test_mode=True)
        _, up_b = tmodel2(t1, t2, iters=4, test_mode=True)
    np.testing.assert_allclose(up_b.numpy(), up_a.numpy(), atol=1e-6)
