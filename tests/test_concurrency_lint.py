"""graftlint engine 4 (analysis/concurrency_rules.py): every concurrency
rule fires on a minimal seeded fixture AND stays silent on the clean
pair, the thread-topology fingerprint gates doctored drift, the dynamic
lock-order witness contradicts/confirms the static order, and HEAD —
after this round's triage — passes ``cli lint --concurrency`` with zero
unsuppressed error-severity findings.

Fixtures are tiny synthetic packages written to tmp_path so each rule's
trigger condition is explicit; the model-scale path is the HEAD test,
which walks the real serve/obs/data/training thread topology.
"""

import json
import os
import textwrap

import pytest

from raft_stereo_tpu.analysis.concurrency_rules import (CONCURRENCY_RULES,
                                                        RULE_VERSIONS,
                                                        build_topology,
                                                        check_witness,
                                                        diff_topology,
                                                        load_topology,
                                                        run_concurrency_rules,
                                                        write_topology)
from raft_stereo_tpu.analysis.runner import main as lint_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _pkg(tmp_path, source, name="fixpkg"):
    pkg = tmp_path / name
    pkg.mkdir(exist_ok=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "mod.py").write_text(textwrap.dedent(source))
    return str(pkg)


def _rules(findings, rule):
    return [f for f in findings if f.rule == rule]


def _empty_baseline(tmp_path):
    path = tmp_path / "baseline.json"
    path.write_text(json.dumps({"version": 1, "suppressions": []}))
    return str(path)


# --------------------------------------------------- shared-write-unlocked

DIRTY_SHARED = """
    import threading

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0
            self._t = threading.Thread(target=self._run, daemon=True)

        def _run(self):
            while True:
                self.count += 1

        def bump(self):
            self.count += 1

        def stop(self):
            self._t.join(timeout=1.0)
"""

CLEAN_SHARED = """
    import threading

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0
            self._t = threading.Thread(target=self._run, daemon=True)

        def _run(self):
            while True:
                with self._lock:
                    self.count += 1

        def bump(self):
            with self._lock:
                self.count += 1

        def stop(self):
            self._t.join(timeout=1.0)
"""


def test_shared_write_unlocked_fires(tmp_path):
    fs = _rules(run_concurrency_rules(_pkg(tmp_path, DIRTY_SHARED)),
                "shared-write-unlocked")
    assert len(fs) == 1 and fs[0].severity == "error"
    assert fs[0].location.endswith("::Worker.count")
    # both writing entries are named in the message
    assert "_run[thread]" in fs[0].message
    assert "[callers]" in fs[0].message


def test_shared_write_locked_is_clean(tmp_path):
    fs = run_concurrency_rules(_pkg(tmp_path, CLEAN_SHARED))
    assert not [f for f in fs if f.severity == "error"], \
        [f"{f.rule}@{f.location}" for f in fs]


# ------------------------------------------------------- lock-order-cycle

DIRTY_ORDER = """
    import threading

    class AB:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()
            self._t = threading.Thread(target=self._fwd, daemon=True)

        def _fwd(self):
            with self._a:
                with self._b:
                    pass

        def back(self):
            with self._b:
                with self._a:
                    pass

        def stop(self):
            self._t.join(timeout=1.0)
"""

CLEAN_ORDER = """
    import threading

    class AB:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()
            self._t = threading.Thread(target=self._fwd, daemon=True)

        def _fwd(self):
            with self._a:
                with self._b:
                    pass

        def back(self):
            with self._a:
                with self._b:
                    pass

        def stop(self):
            self._t.join(timeout=1.0)
"""


def test_lock_order_cycle_fires(tmp_path):
    fs = _rules(run_concurrency_rules(_pkg(tmp_path, DIRTY_ORDER)),
                "lock-order-cycle")
    assert len(fs) == 1 and fs[0].severity == "error"
    assert "AB._a" in fs[0].message and "AB._b" in fs[0].message


def test_consistent_lock_order_is_clean(tmp_path):
    fs = run_concurrency_rules(_pkg(tmp_path, CLEAN_ORDER))
    assert not _rules(fs, "lock-order-cycle")
    assert not [f for f in fs if f.severity == "error"]


# -------------------------------------------------- cond-wait-no-predicate

DIRTY_COND = """
    import threading

    class Waiter:
        def __init__(self):
            self._lock = threading.Lock()
            self._cv = threading.Condition(self._lock)
            self.ready = False
            self._t = threading.Thread(target=self._loop, daemon=True)

        def _loop(self):
            with self._cv:
                self._cv.wait()

        def stop(self):
            self._t.join(timeout=1.0)
"""

CLEAN_COND = """
    import threading

    class Waiter:
        def __init__(self):
            self._lock = threading.Lock()
            self._cv = threading.Condition(self._lock)
            self.ready = False
            self._t = threading.Thread(target=self._loop, daemon=True)

        def _loop(self):
            with self._cv:
                while not self.ready:
                    self._cv.wait()

        def stop(self):
            self._t.join(timeout=1.0)
"""


def test_cond_wait_without_while_fires(tmp_path):
    fs = _rules(run_concurrency_rules(_pkg(tmp_path, DIRTY_COND)),
                "cond-wait-no-predicate")
    assert len(fs) == 1 and fs[0].severity == "error"
    assert "Waiter._loop" in fs[0].location


def test_cond_wait_in_while_is_clean(tmp_path):
    fs = run_concurrency_rules(_pkg(tmp_path, CLEAN_COND))
    assert not _rules(fs, "cond-wait-no-predicate")
    assert not [f for f in fs if f.severity == "error"]


# --------------------------------------------------- signal-handler-unsafe

DIRTY_SIGNAL = """
    import signal
    import threading

    class Guard:
        def __init__(self):
            self._lock = threading.Lock()
            signal.signal(signal.SIGTERM, self._handle)

        def _handle(self, signum, frame):
            with self._lock:
                print("terminating")
"""

CLEAN_SIGNAL = """
    import signal

    class Guard:
        def __init__(self):
            self.requested = False
            signal.signal(signal.SIGTERM, self._handle)

        def _handle(self, signum, frame):
            self.requested = True
"""


def test_emitting_signal_handler_fires(tmp_path):
    fs = _rules(run_concurrency_rules(_pkg(tmp_path, DIRTY_SIGNAL)),
                "signal-handler-unsafe")
    assert len(fs) == 1 and fs[0].severity == "error"
    assert "acquire" in fs[0].message and "print" in fs[0].message


def test_flag_only_signal_handler_is_clean(tmp_path):
    fs = run_concurrency_rules(_pkg(tmp_path, CLEAN_SIGNAL))
    assert not [f for f in fs if f.severity == "error"], \
        [f"{f.rule}@{f.location}" for f in fs]


# ---------------------------------------------------------- daemon-no-join

DIRTY_DAEMON = """
    import threading

    class Pump:
        def __init__(self):
            self._t = threading.Thread(target=self._run, daemon=True)
            self._t.start()

        def _run(self):
            pass
"""

CLEAN_DAEMON = """
    import threading

    class Pump:
        def __init__(self):
            self._t = threading.Thread(target=self._run, daemon=True)
            self._t.start()

        def _run(self):
            pass

        def stop(self):
            self._t.join(timeout=1.0)
"""


def test_joinless_daemon_fires(tmp_path):
    fs = _rules(run_concurrency_rules(_pkg(tmp_path, DIRTY_DAEMON)),
                "daemon-no-join")
    assert len(fs) == 1 and fs[0].severity == "error"


def test_joined_daemon_is_clean(tmp_path):
    fs = run_concurrency_rules(_pkg(tmp_path, CLEAN_DAEMON))
    assert not [f for f in fs if f.severity == "error"]


# ------------------------------------------------- queue-timeout-discipline

DIRTY_QUEUE = """
    import queue
    import threading

    class Feeder:
        def __init__(self):
            self._q = queue.Queue()
            self._t = threading.Thread(target=self._producer, daemon=True)

        def consume(self):
            while True:
                item = self._q.get()
                if item is None:
                    break

        def _producer(self):
            self._q.put(1)

        def stop(self):
            self._t.join(timeout=1.0)
"""

CLEAN_QUEUE = """
    import queue
    import threading

    class Feeder:
        def __init__(self):
            self._q = queue.Queue()
            self._t = threading.Thread(target=self._producer, daemon=True)

        def consume(self):
            while True:
                item = self._q.get(timeout=5.0)
                if item is None:
                    break

        def _producer(self):
            self._q.put(1)

        def stop(self):
            self._t.join(timeout=1.0)
"""


def test_blocking_get_without_timeout_fires(tmp_path):
    fs = _rules(run_concurrency_rules(_pkg(tmp_path, DIRTY_QUEUE)),
                "queue-timeout-discipline")
    assert len(fs) == 1 and fs[0].severity == "error"
    assert "Feeder.consume" in fs[0].location


def test_get_with_timeout_is_clean(tmp_path):
    fs = run_concurrency_rules(_pkg(tmp_path, CLEAN_QUEUE))
    assert not _rules(fs, "queue-timeout-discipline")
    assert not [f for f in fs if f.severity == "error"]


# ------------------------------------------------ cli exit codes (gate)

@pytest.mark.parametrize("source", [DIRTY_SHARED, DIRTY_ORDER, DIRTY_COND,
                                    DIRTY_SIGNAL, DIRTY_DAEMON,
                                    DIRTY_QUEUE])
def test_cli_lint_concurrency_exits_1_on_violation(tmp_path, source):
    rc = lint_main(["--concurrency", "--package-root",
                    _pkg(tmp_path, source),
                    "--baseline", _empty_baseline(tmp_path)])
    assert rc == 1


def test_cli_lint_concurrency_exits_0_on_clean_fixture(tmp_path):
    rc = lint_main(["--concurrency", "--package-root",
                    _pkg(tmp_path, CLEAN_SHARED),
                    "--baseline", _empty_baseline(tmp_path)])
    assert rc == 0


def test_head_passes_concurrency_lint():
    """The real package, after this round's triage (telemetry heartbeat/
    watchdog under the bus lock, loadtest tally under its lock, loader
    get-with-timeout, the named single-owner/vetted-handler baseline
    entries), carries zero unsuppressed concurrency errors."""
    rc = lint_main(["--concurrency"])
    assert rc == 0


# ------------------------------------------------ thread-topology drift

def test_topology_roundtrip_and_doctored_drift(tmp_path):
    pkg = _pkg(tmp_path, CLEAN_SHARED)
    topo = build_topology(pkg)
    path = tmp_path / "threads.json"
    write_topology(str(path), topo)
    assert diff_topology(load_topology(str(path)), topo) == []

    # doctored: the current tree grew a thread entry the baseline never
    # reviewed -> error drift
    baseline = json.loads(path.read_text())
    eid = next(iter(baseline["entries"]))
    removed = baseline["entries"].pop(eid)
    fs = diff_topology(baseline, topo)
    assert any(f.severity == "error" and "new thread entry" in f.message
               for f in fs)

    # doctored: a lock dropped from a previously-guarded path -> error
    baseline["entries"][eid] = removed
    locked = next(e for e in baseline["entries"].values() if e["locks"])
    doctored = dict(topo)
    doctored["entries"] = {
        k: (dict(v, locks=[]) if v["locks"] else v)
        for k, v in topo["entries"].items()}
    fs = diff_topology(baseline, doctored)
    assert any(f.severity == "error" and "dropped" in f.message
               for f in fs), locked


def test_cli_fingerprint_gates_doctored_topology(tmp_path):
    """`cli lint --fingerprint` fails when the checked-in topology no
    longer matches the tree (the acceptance criterion's doctored-map
    case), and passes against the map it just banked."""
    pkg = _pkg(tmp_path, CLEAN_SHARED)
    fp = str(tmp_path / "fp.json")
    tb = str(tmp_path / "threads.json")
    common = ["--concurrency", "--package-root", pkg, "--no-compile",
              "--fingerprint", "--fingerprint-baseline", fp,
              "--threads-baseline", tb,
              "--baseline", _empty_baseline(tmp_path)]
    assert lint_main(common + ["--update-fingerprint"]) == 0
    assert lint_main(common) == 0

    doc = json.loads(open(tb).read())
    # a thread entry disappears from the baseline -> the current tree has
    # an unreviewed "new" entry -> gated
    doc["entries"].pop(next(iter(doc["entries"])))
    with open(tb, "w") as f:
        json.dump(doc, f)
    assert lint_main(common) == 1


def test_head_topology_baseline_is_current():
    """.graftlint-threads.json is checked in and matches HEAD."""
    path = os.path.join(REPO, ".graftlint-threads.json")
    assert os.path.exists(path), \
        "regenerate with: cli lint --fingerprint --update-fingerprint"
    baseline = load_topology(path)
    current = build_topology(os.path.join(REPO, "raft_stereo_tpu"))
    drift = [f for f in diff_topology(baseline, current)
             if f.severity == "error"]
    assert drift == [], [f"{f.location}: {f.message}" for f in drift]


# ------------------------------------------------- the lock-order witness

def test_witness_contradiction_is_error(tmp_path):
    """A hand-built acquisition log that reverses the static order fails
    the witness check."""
    pkg = _pkg(tmp_path, CLEAN_ORDER)  # static order: _a -> _b
    topo = build_topology(pkg)
    assert topo["lock_order"], "fixture should have a static order edge"
    a, b = topo["lock_order"][0]
    fs = check_witness(topo, {"version": 1, "locks": {}, "edges": [[b, a, 3]]})
    errors = [f for f in fs if f.severity == "error"]
    # the reversed edge both contradicts the static order AND closes the
    # 2-cycle with it — two findings, one deadlock window
    assert errors and any("contradicts" in f.message for f in errors)


def test_witness_closing_unseen_cycle_is_error(tmp_path):
    pkg = _pkg(tmp_path, CLEAN_ORDER)
    topo = build_topology(pkg)
    a, b = topo["lock_order"][0]
    # dynamics route b back to a through a third lock the static pass
    # never ordered: the union closes a cycle -> error
    wit = {"version": 1, "locks": {}, "edges": [[b, "x::C.l", 1],
                                               ["x::C.l", a, 1]]}
    fs = check_witness(topo, wit)
    assert any(f.severity == "error" and "cycle" in f.message for f in fs)


def test_consistent_witness_is_green(tmp_path):
    pkg = _pkg(tmp_path, CLEAN_ORDER)
    topo = build_topology(pkg)
    a, b = topo["lock_order"][0]
    fs = check_witness(topo, {"version": 1,
                              "locks": {a: "Lock", b: "Lock"},
                              "edges": [[a, b, 7]]})
    assert not [f for f in fs if f.severity == "error"]
    assert any("consistent" in f.message for f in fs)


def test_cli_witness_flag_gates(tmp_path):
    pkg = _pkg(tmp_path, CLEAN_ORDER)
    topo = build_topology(pkg)
    a, b = topo["lock_order"][0]
    wpath = tmp_path / "witness.json"
    wpath.write_text(json.dumps(
        {"version": 1, "locks": {}, "edges": [[b, a, 1]]}))
    args = ["--concurrency", "--package-root", pkg,
            "--witness", str(wpath),
            "--baseline", _empty_baseline(tmp_path)]
    assert lint_main(args) == 1
    wpath.write_text(json.dumps(
        {"version": 1, "locks": {}, "edges": [[a, b, 1]]}))
    assert lint_main(args) == 0


def test_witness_records_real_acquisitions(tmp_path):
    """obs/lockwitness.py end to end in-process: package-created locks
    are wrapped, nesting records an order edge with the canonical ids."""
    import threading

    from raft_stereo_tpu.obs import lockwitness

    reg = lockwitness._Registry()
    # simulate what install() does for two package locks
    outer = lockwitness._LockProxy(threading.Lock(), "m.py::A._outer", reg)
    inner = lockwitness._LockProxy(threading.Lock(), "m.py::A._inner", reg)
    reg.register("m.py::A._outer", "Lock")
    reg.register("m.py::A._inner", "Lock")
    with outer:
        with inner:
            pass
    with outer:
        pass
    doc = reg.dump()
    assert doc["edges"] == [["m.py::A._outer", "m.py::A._inner", 1]]
    assert set(doc["locks"]) == {"m.py::A._outer", "m.py::A._inner"}


# ----------------------------------------------------- engine metadata

def test_rule_surface_registered():
    """Every engine-4 rule is versioned and reported to the runner."""
    assert set(CONCURRENCY_RULES) == set(RULE_VERSIONS)
    assert {"shared-write-unlocked", "lock-order-cycle",
            "cond-wait-no-predicate", "signal-handler-unsafe",
            "daemon-no-join", "queue-timeout-discipline",
            "thread-topology-drift",
            "lock-order-witness"} == set(RULE_VERSIONS)
    from raft_stereo_tpu.analysis.runner import rule_versions
    merged = rule_versions()
    for rule, v in RULE_VERSIONS.items():
        assert merged[rule] == v


def test_cli_drift_v10_fires_on_seeded_drill_fixture(tmp_path):
    """cli-drift v10: the drill/runner scripts are self-consumed surfaces
    — a parsed-then-dropped flag fires, and an aliased dest= no longer
    false-fires."""
    from raft_stereo_tpu.analysis.ast_rules import (RULE_VERSIONS as ast_v,
                                                    check_entry_surface_drift)
    assert ast_v["cli-drift"] == 10
    sdir = tmp_path / "scripts"
    sdir.mkdir()
    (sdir / "load_drill.py").write_text(textwrap.dedent("""
        import argparse

        def main():
            p = argparse.ArgumentParser()
            p.add_argument("--shapes", nargs="+")
            p.add_argument("--orphan-flag", action="store_true")
            p.add_argument("--json", dest="json_out")
            args = p.parse_args()
            print(args.shapes, args.json_out)
    """))
    fs = [f for f in check_entry_surface_drift(str(tmp_path))
          if f.rule == "cli-drift"]
    assert [f.data["dest"] for f in fs] == ["orphan_flag"]


def test_real_lint_surfaces_are_self_consumed():
    """The runner's own argparse surface (--concurrency, --witness,
    --threads-baseline) and the drill scripts read every flag they
    declare on the real tree."""
    from raft_stereo_tpu.analysis.ast_rules import check_entry_surface_drift
    fs = [f for f in check_entry_surface_drift(REPO)
          if f.rule == "cli-drift"]
    assert fs == [], [f"{f.location}: {f.message}" for f in fs]
