"""End-to-end training loop: synthetic data -> train() -> checkpoint -> resume.

The reference's quality gate is validation-as-integration-test (SURVEY §4);
here the integration test is automated: a tiny synthetic SceneFlow tree, a few
optimizer steps on the 8-device CPU mesh, full-state checkpointing, and an
exact-resume check (which the reference cannot do — it restarts schedules).
"""

import os

import numpy as np
import pytest

import jax

from raft_stereo_tpu.config import RAFTStereoConfig, TrainConfig
from raft_stereo_tpu.data import frame_utils
from raft_stereo_tpu.training.checkpoint import (restore_train_state,
                                                 save_train_state)
from raft_stereo_tpu.training.logger import SUM_FREQ, Logger
from raft_stereo_tpu.training.optim import fetch_optimizer
from raft_stereo_tpu.training.state import TrainState
from raft_stereo_tpu.training.trainer import train


def _make_sceneflow_tree(root, n=4, h=64, w=96):
    from PIL import Image
    rng = np.random.default_rng(0)
    for dstype in ("frames_cleanpass", "frames_finalpass"):
        for side in ("left", "right"):
            (root / "FlyingThings3D" / dstype / "TRAIN" / "A" / "0000" / side
             ).mkdir(parents=True, exist_ok=True)
        (root / "FlyingThings3D" / "disparity" / "TRAIN" / "A" / "0000" /
         "left").mkdir(parents=True, exist_ok=True)
        for i in range(n):
            for side in ("left", "right"):
                img = rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
                Image.fromarray(img).save(
                    root / "FlyingThings3D" / dstype / "TRAIN" / "A" / "0000" /
                    side / f"{i:04d}.png")
            frame_utils.write_pfm(
                str(root / "FlyingThings3D" / "disparity" / "TRAIN" / "A" /
                    "0000" / "left" / f"{i:04d}.pfm"),
                rng.uniform(0.5, 8, (h, w)).astype(np.float32))


@pytest.mark.slow
def test_train_loop_end_to_end(tmp_path):
    _make_sceneflow_tree(tmp_path)
    model_cfg = RAFTStereoConfig()
    cfg = TrainConfig(
        name="tiny", batch_size=2, num_steps=3, image_size=(48, 64),
        train_iters=2, valid_iters=2, data_root=str(tmp_path),
        ckpt_dir=str(tmp_path / "ckpts"), validation_frequency=2,
        num_workers=2, data_parallel=2, seq_parallel=1, lr=1e-4)
    final = train(model_cfg, cfg)
    assert os.path.isdir(final)

    # resume restores the exact step counter
    model_cfg2 = RAFTStereoConfig()
    from raft_stereo_tpu.models import init_model
    _, variables = init_model(jax.random.PRNGKey(0), model_cfg2, (1, 48, 64, 3))
    state = TrainState.create(variables, fetch_optimizer(cfg))
    restored = restore_train_state(final, jax.device_get(state))
    assert int(restored.step) == 3


def test_checkpoint_roundtrip(tmp_path):
    from raft_stereo_tpu.models import init_model
    cfg = TrainConfig(num_steps=10)
    _, variables = init_model(jax.random.PRNGKey(1), RAFTStereoConfig(),
                              (1, 32, 64, 3))
    state = TrainState.create(variables, fetch_optimizer(cfg))
    path = save_train_state(str(tmp_path), "t", state, step=5)
    assert path.endswith("5_t")
    restored = restore_train_state(path, jax.device_get(state))
    np.testing.assert_array_equal(
        np.asarray(restored.params["fnet"]["conv2"]["kernel"]),
        np.asarray(state.params["fnet"]["conv2"]["kernel"]))


def test_logger_windows(tmp_path, caplog):
    import logging
    log = Logger(log_dir=str(tmp_path / "runs"))
    with caplog.at_level(logging.INFO,
                         logger="raft_stereo_tpu.training.logger"):
        for i in range(SUM_FREQ):
            log.push({"loss": 2.0, "epe": 1.0}, lr=1e-4)
    assert any("loss" in r.message for r in caplog.records)
    log.close()
