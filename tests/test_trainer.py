"""End-to-end training loop: synthetic data -> train() -> checkpoint -> resume.

The reference's quality gate is validation-as-integration-test (SURVEY §4);
here the integration test is automated: a tiny synthetic SceneFlow tree, a few
optimizer steps on the 8-device CPU mesh, full-state checkpointing, and an
exact-resume check (which the reference cannot do — it restarts schedules).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import jax

from raft_stereo_tpu.config import RAFTStereoConfig, TrainConfig
from raft_stereo_tpu.data import frame_utils
from raft_stereo_tpu.obs import read_events, validate_events
from raft_stereo_tpu.training.checkpoint import (restore_train_state,
                                                 save_train_state)
from raft_stereo_tpu.training.logger import SUM_FREQ, Logger
from raft_stereo_tpu.training.optim import fetch_optimizer
from raft_stereo_tpu.training.state import TrainState
from raft_stereo_tpu.training.trainer import train


def _make_sceneflow_tree(root, n=4, h=64, w=96):
    from PIL import Image
    rng = np.random.default_rng(0)
    for dstype in ("frames_cleanpass", "frames_finalpass"):
        for side in ("left", "right"):
            (root / "FlyingThings3D" / dstype / "TRAIN" / "A" / "0000" / side
             ).mkdir(parents=True, exist_ok=True)
        (root / "FlyingThings3D" / "disparity" / "TRAIN" / "A" / "0000" /
         "left").mkdir(parents=True, exist_ok=True)
        for i in range(n):
            for side in ("left", "right"):
                img = rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
                Image.fromarray(img).save(
                    root / "FlyingThings3D" / dstype / "TRAIN" / "A" / "0000" /
                    side / f"{i:04d}.png")
            frame_utils.write_pfm(
                str(root / "FlyingThings3D" / "disparity" / "TRAIN" / "A" /
                    "0000" / "left" / f"{i:04d}.pfm"),
                rng.uniform(0.5, 8, (h, w)).astype(np.float32))


@pytest.mark.slow
def test_train_loop_end_to_end(tmp_path):
    _make_sceneflow_tree(tmp_path)
    model_cfg = RAFTStereoConfig()
    cfg = TrainConfig(
        name="tiny", batch_size=2, num_steps=3, image_size=(48, 64),
        train_iters=2, valid_iters=2, data_root=str(tmp_path),
        ckpt_dir=str(tmp_path / "ckpts"), validation_frequency=2,
        num_workers=2, data_parallel=2, seq_parallel=1, lr=1e-4,
        run_dir=str(tmp_path / "runs"))
    final = train(model_cfg, cfg)
    assert os.path.isdir(final)

    # resume restores the exact step counter
    model_cfg2 = RAFTStereoConfig()
    from raft_stereo_tpu.models import init_model
    _, variables = init_model(jax.random.PRNGKey(0), model_cfg2, (1, 48, 64, 3))
    state = TrainState.create(variables, fetch_optimizer(cfg))
    restored = restore_train_state(final, jax.device_get(state))
    assert int(restored.step) == 3

    # the run left a conforming telemetry artifact with the mid-run
    # validation + checkpoint on record (validation_frequency=2 fired once)
    events = read_events(str(tmp_path / "runs" / "tiny" / "events.jsonl"))
    assert validate_events(events) == []
    kinds = [e["event"] for e in events]
    assert kinds.count("step") == 3
    assert "validation" in kinds and "checkpoint" in kinds
    val = next(e for e in events if e["event"] == "validation")
    assert "things-epe" in val["results"]


def test_train_smoke_emits_telemetry(tmp_path):
    """Acceptance: a CPU smoke train run produces a parseable events.jsonl
    (run_start, phase-split step timing, checkpoint, run_end) and
    ``python -m raft_stereo_tpu.cli telemetry`` renders it with non-zero
    phase timings."""
    _make_sceneflow_tree(tmp_path)
    model_cfg = RAFTStereoConfig(hidden_dims=(32, 32, 32))  # fast compile
    cfg = TrainConfig(
        name="smoke", batch_size=2, num_steps=2, image_size=(48, 64),
        train_iters=1, valid_iters=1, data_root=str(tmp_path),
        ckpt_dir=str(tmp_path / "ckpts"), validation_frequency=5,
        num_workers=2, data_parallel=2, seq_parallel=1, lr=1e-4,
        run_dir=str(tmp_path / "runs"), stall_deadline_s=120.0)
    final = train(model_cfg, cfg)
    assert os.path.isdir(final)

    run_dir = tmp_path / "runs" / "smoke"
    events = read_events(str(run_dir / "events.jsonl"))
    assert validate_events(events) == []
    kinds = [e["event"] for e in events]
    assert kinds[0] == "run_start" and kinds[-1] == "run_end"
    steps = [e for e in events if e["event"] == "step"]
    assert [s["step"] for s in steps] == [1, 2]
    # the phase split is real: data decode waited, the device dispatched
    assert all(s["data_wait_s"] > 0 and s["dispatch_s"] > 0 for s in steps)
    assert any(e["event"] == "compile" for e in events)
    # the first step was AOT-compiled and introspected (obs/xla.py): the
    # executable's memory/cost analyses are on the run record
    xm = next(e for e in events if e["event"] == "xla_memory")
    assert xm["source"] == "train_step" and xm["peak_bytes"] > 0
    xc = next(e for e in events if e["event"] == "xla_cost")
    assert xc["flops"] > 0
    ck = next(e for e in events if e["event"] == "checkpoint")
    assert ck["step"] == 2 and os.path.isdir(ck["path"])
    end = events[-1]
    assert end["ok"] is True and end["steps"] == 2

    # the summarizer CLI — the literal `python -m` surface — renders it
    out = subprocess.run(
        [sys.executable, "-m", "raft_stereo_tpu.cli", "telemetry",
         str(run_dir)],
        capture_output=True, text=True, timeout=120,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert out.returncode == 0, out.stderr
    assert "per-step phases" in out.stdout
    # non-zero dispatch totals made it into the rendered report
    dispatch = next(line for line in out.stdout.splitlines()
                    if line.strip().startswith("dispatch_s"))
    assert float(dispatch.split()[-1]) > 0


def test_checkpoint_roundtrip(tmp_path):
    from raft_stereo_tpu.models import init_model
    cfg = TrainConfig(num_steps=10)
    _, variables = init_model(jax.random.PRNGKey(1), RAFTStereoConfig(),
                              (1, 32, 64, 3))
    state = TrainState.create(variables, fetch_optimizer(cfg))
    path = save_train_state(str(tmp_path), "t", state, step=5)
    assert path.endswith("5_t")
    restored = restore_train_state(path, jax.device_get(state))
    np.testing.assert_array_equal(
        np.asarray(restored.params["fnet"]["conv2"]["kernel"]),
        np.asarray(state.params["fnet"]["conv2"]["kernel"]))


def test_logger_windows(tmp_path, caplog):
    import logging
    log = Logger(log_dir=str(tmp_path / "runs"))
    with caplog.at_level(logging.INFO,
                         logger="raft_stereo_tpu.training.logger"):
        for i in range(SUM_FREQ):
            log.push({"loss": 2.0, "epe": 1.0}, lr=1e-4)
    assert any("loss" in r.message for r in caplog.records)
    log.close()
