"""Fleet observatory (obs/fleet.py) and its schema-v10 plumbing.

What is pinned here, per the r17 acceptance bar:

* the traceparent envelope round-trips and degrades to "no remote
  parent" on anything malformed;
* clock alignment reproduces a hand-built two-host fixture exactly —
  anchored offsets from ``clock_anchor``, the ts-derived fallback for
  pre-v10 logs;
* the skew table and the STRAGGLER / DEAD_HOST / DESYNC verdicts fire on
  seeded logs with correct host attribution, and a clean fleet reads
  FLEET_OK (the negatives);
* a cross-process trace join: one trace_id across two hosts' logs, the
  client span parenting the server's request root, and the
  ``remote_parent`` exemption in the span lint;
* ``check_fleet_integrity`` catches inconsistent host identity,
  duplicate anchors and heartbeat seq regressions — per run_start
  segment, so auto-resume appends stay clean;
* the Telemetry bus stamps host_id/pid on every record, anchors the
  clock once, prefixes flight-recorder dumps with the host, and with
  ``fleet=False`` the stream is byte-shaped like a pre-v10 single-process
  run (the additive pin);
* ``cli fleet`` / ``cli doctor`` consume a fleet dir end to end, and
  cli-drift rule v8 covers the build_fleet_parser surface.
"""

import json
import os

import pytest

from raft_stereo_tpu.obs import fleet
from raft_stereo_tpu.obs.telemetry import Telemetry
from raft_stereo_tpu.obs.trace import SpanContext, Tracer
from raft_stereo_tpu.obs.validate import (check_fleet_integrity, check_path,
                                          check_span_integrity)

TS = "2026-08-07T00:00:00"


def _rec(event, t, **payload):
    """A hand-built v10 record with a controlled monotonic ``t``."""
    return dict({"schema": 10, "ts": TS, "event": event,
                 "t": round(float(t), 6)}, **payload)


def _host_log(fleet_dir, name, records):
    d = fleet_dir / name
    d.mkdir(parents=True, exist_ok=True)
    with open(d / "events.jsonl", "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
    return d


def _step(t, step, dispatch_s, host_id, **extra):
    return _rec("step", t, step=step, data_wait_s=0.001,
                dispatch_s=dispatch_s, fetch_s=0.001, batch_size=2,
                host_id=host_id, pid=1000, **extra)


def _train_host(host_id, offset, n_steps, dispatch_s, run_end=True,
                beats=(), every_s=0.5):
    """A synthetic trainer log: run_start, anchor (wall = t + offset),
    n_steps steps 1s apart, optional heartbeats and run_end."""
    recs = [_rec("run_start", 0.0, run=host_id, host_id=host_id, pid=1000),
            _rec("clock_anchor", 0.0, host_id=host_id, pid=1000,
                 monotonic=0.0, wall=offset)]
    for i in range(1, n_steps + 1):
        recs.append(_step(float(i), i, dispatch_s, host_id))
    for seq, t in enumerate(beats):
        recs.append(_rec("heartbeat", t, host_id=host_id, pid=1000,
                         role="trainer", seq=seq, every_s=every_s))
    if run_end:
        recs.append(_rec("run_end", n_steps + 1.0, steps=n_steps, ok=True,
                         host_id=host_id, pid=1000))
    return recs


# ------------------------------------------------ traceparent / host id

def test_traceparent_round_trip_and_malformed():
    ctx = SpanContext(trace_id="t00abc", span_id="s00def")
    header = fleet.format_traceparent(ctx)
    assert header == "00-t00abc-s00def-01"
    assert fleet.parse_traceparent(header) == ctx
    for bad in (None, "", "garbage", "00-only-three", "00--s1-01", 7):
        assert fleet.parse_traceparent(bad) is None


def test_resolve_host_id_precedence(monkeypatch):
    monkeypatch.setenv(fleet.HOST_ID_ENV, "from-env")
    assert fleet.resolve_host_id("explicit") == "explicit"
    assert fleet.resolve_host_id() == "from-env"
    monkeypatch.delenv(fleet.HOST_ID_ENV)
    default = fleet.resolve_host_id()
    assert default.endswith(f"-{os.getpid()}")


# --------------------------------------------------- clock alignment

def test_clock_alignment_matches_hand_built_two_host_fixture(tmp_path):
    """Anchored offsets place both hosts on one epoch axis: hostB's clock
    starts 2.5s after hostA's, so its step at t=1 lands at aligned 1003.5
    while hostA's lands at 1001.0 — and the fleet wall is the exact
    hand-computed span, not either host's local extent."""
    _host_log(tmp_path, "hostA", _train_host("hostA", 1000.0, 3, 0.01))
    _host_log(tmp_path, "hostB", _train_host("hostB", 1002.5, 3, 0.01))
    roll = fleet.aggregate_fleet(str(tmp_path))
    by_id = {h["host_id"]: h for h in roll["hosts"]}
    assert by_id["hostA"]["offset"] == 1000.0
    assert by_id["hostB"]["offset"] == 1002.5
    assert by_id["hostA"]["anchored"] and by_id["hostB"]["anchored"]
    assert by_id["hostA"]["aligned_start"] == 1000.0   # run_start at t=0
    assert by_id["hostB"]["aligned_end"] == 1006.5     # run_end at t=4
    # fleet wall: earliest aligned record 1000.0 -> latest 1006.5
    assert roll["wall_s"] == 6.5


def test_unanchored_log_falls_back_to_ts_offset(tmp_path):
    """A pre-v10 log (no clock_anchor, no host stamps) still lands on the
    fleet axis via ts - t, and its host_id falls back to the dirname."""
    recs = [{"schema": 9, "ts": "2026-08-07T00:00:10", "event": "run_start",
             "t": 10.0, "run": "old"},
            {"schema": 9, "ts": "2026-08-07T00:00:11", "event": "run_end",
             "t": 11.0, "steps": 0, "ok": True}]
    d = _host_log(tmp_path, "legacy", recs)
    h = fleet.load_host(str(d))
    assert not h["anchored"]
    assert h["host_id"] == "legacy"
    import datetime
    expect = datetime.datetime.fromisoformat(
        "2026-08-07T00:00:10").timestamp() - 10.0
    assert h["offset"] == expect


def test_lenient_reader_survives_sigkill_truncation(tmp_path):
    d = _host_log(tmp_path, "killed", _train_host("killed", 0.0, 2, 0.01,
                                                  run_end=False))
    with open(d / "events.jsonl", "a") as f:
        f.write('{"schema": 10, "ts": "2026-08-07T00:0')  # torn final line
    recs = fleet.read_events_lenient(str(d / "events.jsonl"))
    assert [r["event"] for r in recs] == ["run_start", "clock_anchor",
                                         "step", "step"]


# ------------------------------------------------------- fleet verdicts

def test_straggler_verdict_names_the_slow_host(tmp_path):
    for name, dispatch in (("h0", 0.01), ("h1", 0.01), ("h2", 0.25)):
        _host_log(tmp_path, name, _train_host(name, 1000.0, 5, dispatch))
    roll = fleet.aggregate_fleet(str(tmp_path))
    row = next(r for r in roll["skew"] if r["host_id"] == "h2")
    assert row["vs_others"] >= fleet.STRAGGLER_FACTOR
    verdicts = fleet.fleet_verdicts(roll)
    stragglers = [v for v in verdicts if v["verdict"] == "STRAGGLER"]
    assert [v["host"] for v in stragglers] == ["h2"]
    # evidence quotes both the host's and the fleet's numbers
    assert "h2" in stragglers[0]["evidence"][0]
    assert str(row["others_p95_ms"]) in stragglers[0]["evidence"][0]


def test_clean_fleet_reads_fleet_ok(tmp_path):
    for name in ("h0", "h1", "h2"):
        _host_log(tmp_path, name, _train_host(
            name, 1000.0, 5, 0.01, beats=(0.5, 1.0, 1.5, 2.0)))
    verdicts = fleet.fleet_verdicts(fleet.aggregate_fleet(str(tmp_path)))
    assert [v["verdict"] for v in verdicts] == ["FLEET_OK"]


def test_dead_host_fires_on_heartbeat_gap_but_not_on_clean_exit(tmp_path):
    # h0 runs the full 20s window with beats throughout; h1's beats stop
    # at t=1.0 with no run_end — 19s of silence >> 3x the 0.5s cadence
    long_beats = tuple(i * 0.5 for i in range(1, 41))
    _host_log(tmp_path, "h0", _train_host("h0", 1000.0, 20, 0.01,
                                          beats=long_beats))
    _host_log(tmp_path, "h1", _train_host("h1", 1000.0, 2, 0.01,
                                          run_end=False, beats=(0.5, 1.0)))
    verdicts = fleet.fleet_verdicts(fleet.aggregate_fleet(str(tmp_path)))
    dead = [v for v in verdicts if v["verdict"] == "DEAD_HOST"]
    assert [v["host"] for v in dead] == ["h1"]
    assert "h1" in dead[0]["evidence"][0]
    # the same silent log WITH a run_end is an exit, not a death
    _host_log(tmp_path, "h1", _train_host("h1", 1000.0, 2, 0.01,
                                          run_end=True, beats=(0.5, 1.0)))
    verdicts = fleet.fleet_verdicts(fleet.aggregate_fleet(str(tmp_path)))
    assert not any(v["verdict"] == "DEAD_HOST" for v in verdicts)


def test_desync_judged_over_live_hosts_only(tmp_path):
    # both hosts live and beating, step counters 10 vs 3: DESYNC
    beats = tuple(i * 0.5 for i in range(1, 23))
    _host_log(tmp_path, "h0", _train_host("h0", 1000.0, 10, 0.01,
                                          beats=beats))
    _host_log(tmp_path, "h1", _train_host("h1", 1000.0, 3, 0.012,
                                          beats=beats))
    verdicts = fleet.fleet_verdicts(fleet.aggregate_fleet(str(tmp_path)))
    desync = [v for v in verdicts if v["verdict"] == "DESYNC"]
    assert len(desync) == 1 and desync[0]["host"] == "h1"
    # a DEAD host's stale counter must not double-report as DESYNC
    _host_log(tmp_path, "h1", _train_host("h1", 1000.0, 3, 0.012,
                                          run_end=False, beats=(0.5, 1.0)))
    verdicts = fleet.fleet_verdicts(fleet.aggregate_fleet(str(tmp_path)))
    kinds = [v["verdict"] for v in verdicts]
    assert "DEAD_HOST" in kinds and "DESYNC" not in kinds


def test_serving_logs_are_excluded_from_straggler_stats(tmp_path):
    """A serve host's ``step`` records are per-request accounting, not
    train steps — they must not feed the skew table."""
    recs = _train_host("srv", 1000.0, 5, 0.5, run_end=True)
    recs.append(_rec("request", 2.0, id="r1", status="ok", host_id="srv",
                     pid=1000))
    _host_log(tmp_path, "srv", recs)
    _host_log(tmp_path, "h0", _train_host("h0", 1000.0, 5, 0.01))
    roll = fleet.aggregate_fleet(str(tmp_path))
    assert [r["host_id"] for r in roll["skew"]] == ["h0"]


# ------------------------------------------- cross-process trace joins

def _span(t, name, span_id, trace_id, host_id, parent_id=None, **extra):
    r = _rec("span", t, name=name, span_id=span_id, trace_id=trace_id,
             start_s=t, dur_s=0.01, host_id=host_id, pid=1000, **extra)
    if parent_id is not None:
        r["parent_id"] = parent_id
    return r


def test_cross_process_trace_join_and_remote_parent_exemption(tmp_path):
    """The propagated-context proof: the client's span and the server's
    request root share one trace_id across two files, the root names the
    client span as parent, and the span lint accepts the cross-file
    parent only because the span is marked ``remote_parent``."""
    client = _train_host("client", 1000.0, 3, 0.01)
    client.append(_span(1.2, "client_request", "s00001", "t00001", "client"))
    _host_log(tmp_path, "client", client)
    server = _train_host("server", 1001.0, 3, 0.01)
    server.append(_span(0.3, "request", "s00002", "t00001", "server",
                        parent_id="s00001", remote_parent=True))
    server.append(_span(0.3, "queue_wait", "s00003", "t00001", "server",
                        parent_id="s00002"))
    _host_log(tmp_path, "server", server)

    roll = fleet.aggregate_fleet(str(tmp_path))
    joins = roll["cross_host_traces"]
    assert len(joins) == 1
    j = joins[0]
    assert j["trace_id"] == "t00001"
    assert j["hosts"] == ["client", "server"] and j["spans"] == 3
    assert j["remote_links"] == [{"child": "request",
                                  "child_host": "server",
                                  "parent_host": "client"}]

    # lint: the marked span's unresolvable parent is exempt ...
    srv_recs = fleet.read_events_lenient(
        str(tmp_path / "server" / "events.jsonl"))
    assert check_span_integrity(srv_recs) == []
    # ... and without the mark the same shape is still an orphan error
    for r in srv_recs:
        r.pop("remote_parent", None)
    assert any("parent" in e for e in check_span_integrity(srv_recs))


# ------------------------------------------------------ schema-v10 lint

def test_fleet_integrity_positives_and_negatives():
    clean = _train_host("h0", 1000.0, 2, 0.01, beats=(0.5, 1.0))
    assert check_fleet_integrity(clean) == []
    # inconsistent host identity within one segment
    bad = [dict(r) for r in clean]
    bad[2]["host_id"] = "imposter"
    assert any("host_id" in e for e in check_fleet_integrity(bad))
    # a second clock_anchor in the same segment
    bad = clean + [_rec("clock_anchor", 1.5, host_id="h0", pid=1000,
                        monotonic=1.5, wall=1001.5)]
    assert any("clock_anchor" in e for e in check_fleet_integrity(bad))
    # heartbeat seq must be strictly increasing per (host, role)
    bad = clean + [_rec("heartbeat", 1.5, host_id="h0", pid=1000,
                        role="trainer", seq=0, every_s=0.5)]
    assert any("seq" in e for e in check_fleet_integrity(bad))
    # heartbeats with no clock_anchor cannot be aligned offline
    noanchor = [r for r in clean if r["event"] != "clock_anchor"]
    assert any("clock_anchor" in e
               for e in check_fleet_integrity(noanchor))


def test_fleet_integrity_resets_per_run_start_segment():
    """Auto-resume appends a second process's records — new host_id, its
    own anchor, fresh heartbeat seqs — to the SAME file; each run_start
    opens a new segment, so the combined file lints clean."""
    first = _train_host("h0-pid1", 1000.0, 2, 0.01, beats=(0.5, 1.0))
    resumed = _train_host("h0-pid2", 1030.0, 2, 0.01, beats=(0.5, 1.0))
    assert check_fleet_integrity(first + resumed) == []


# --------------------------------------------- Telemetry bus stamping

def test_telemetry_stamps_host_identity_and_anchors_once(tmp_path):
    tel = Telemetry(str(tmp_path / "run"), host_id="stamp-host",
                    coords=(0, 1))
    tel.run_start(config={"mode": "test"})
    tel.step(1, data_wait_s=0.0, dispatch_s=0.01, fetch_s=0.0,
             batch_size=2)
    tel.emit("run_end", steps=1, ok=True)
    tel.close()
    recs = fleet.read_events_lenient(str(tmp_path / "run" / "events.jsonl"))
    assert all(r["host_id"] == "stamp-host" for r in recs)
    assert all(r["pid"] == os.getpid() for r in recs)
    assert all(r["coords"] == [0, 1] for r in recs)
    anchors = [r for r in recs if r["event"] == "clock_anchor"]
    assert len(anchors) == 1
    assert check_path(str(tmp_path / "run" / "events.jsonl")) == []


def test_traceparent_envelope_rides_run_start(tmp_path, monkeypatch):
    monkeypatch.setenv(fleet.TRACEPARENT_ENV, "00-t00abc-s00def-01")
    tel = Telemetry(str(tmp_path / "run"), host_id="child")
    tel.run_start(config={})
    tel.close()
    recs = fleet.read_events_lenient(str(tmp_path / "run" / "events.jsonl"))
    start = next(r for r in recs if r["event"] == "run_start")
    assert start["traceparent"] == "00-t00abc-s00def-01"


def test_flight_dump_filenames_carry_the_host(tmp_path):
    tel = Telemetry(str(tmp_path / "run"), host_id="dump/host",
                    flightrec_min_interval_s=0.0)
    tel.run_start(config={})
    tel.emit("anomaly", kind="test_trigger")
    tel.close()
    dumps = [f for f in os.listdir(tmp_path / "run")
             if f.startswith("flightrec-")]
    # the host tag is sanitized into the filename — two hosts sharing a
    # run dir can no longer clobber each other's dumps
    assert dumps and all(f.startswith("flightrec-dump_host-")
                         for f in dumps)
    recs = fleet.read_events_lenient(str(tmp_path / "run" / "events.jsonl"))
    pointer = next(r for r in recs if r["event"] == "flightrec")
    assert pointer["host_id"] == "dump/host"


def test_no_fleet_stream_is_bitwise_plain(tmp_path):
    """The additive pin: fleet=False must leave the stream byte-shaped
    like a pre-v10 run — drop the stamps and the v10 records from a
    fleet=True stream and the two are identical (modulo clocks)."""
    def run(dirname, fleet_on):
        tel = Telemetry(str(tmp_path / dirname), run_name="pin",
                        host_id="pin-host" if fleet_on else None,
                        fleet=fleet_on)
        tel.run_start(config={"mode": "pin"})
        for i in range(3):
            tel.step(i, data_wait_s=0.01, dispatch_s=0.02, fetch_s=0.005,
                     batch_size=2, loss=1.5)
        tel.emit("run_end", steps=3, ok=True)
        tel.close()
        return fleet.read_events_lenient(
            str(tmp_path / dirname / "events.jsonl"))

    plain = run("plain", fleet_on=False)
    stamped = run("stamped", fleet_on=True)
    assert not any("host_id" in r or "pid" in r for r in plain)
    assert not any(r["event"] in ("clock_anchor", "heartbeat")
                   for r in plain)

    def scrub(events):
        return [{k: v for k, v in e.items()
                 if k not in ("t", "ts", "host_id", "pid")}
                for e in events
                if e["event"] not in ("clock_anchor", "heartbeat")]

    assert scrub(stamped) == scrub(plain)


def test_heartbeat_thread_beats_with_increasing_seq(tmp_path):
    tel = Telemetry(str(tmp_path / "run"), host_id="beater")
    assert tel.start_heartbeat("trainer", 0.0) is None   # cadence off
    tel.run_start(config={})
    t = tel.start_heartbeat("trainer", 0.02,
                            probe=lambda: {"step_now": 7})
    assert t is not None
    import time
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        recs = fleet.read_events_lenient(
            str(tmp_path / "run" / "events.jsonl"))
        if sum(r["event"] == "heartbeat" for r in recs) >= 3:
            break
        time.sleep(0.02)
    tel.close()
    recs = fleet.read_events_lenient(str(tmp_path / "run" / "events.jsonl"))
    beats = [r for r in recs if r["event"] == "heartbeat"]
    assert len(beats) >= 3
    assert [b["seq"] for b in beats] == list(range(len(beats)))
    assert all(b["role"] == "trainer" and b["every_s"] == 0.02
               and b["step_now"] == 7 for b in beats)
    assert check_fleet_integrity(recs) == []
    # fleet off: no thread, ever
    off = Telemetry(str(tmp_path / "off"), fleet=False)
    assert off.host_id is None
    assert off.start_heartbeat("trainer", 0.02) is None
    off.close()


# ------------------------------------------------- timeline + consumers

def test_fleet_timeline_one_process_group_per_host(tmp_path):
    client = _train_host("client", 1000.0, 2, 0.01, beats=(0.5, 1.0))
    client.append(_span(1.2, "client_request", "s00001", "t00001",
                        "client"))
    _host_log(tmp_path, "client", client)
    server = _train_host("server", 1002.0, 2, 0.01, beats=(0.5, 1.0))
    server.append(_span(0.3, "request", "s00002", "t00001", "server",
                        parent_id="s00001", remote_parent=True))
    _host_log(tmp_path, "server", server)
    info = fleet.build_fleet_timeline(str(tmp_path))
    assert info["hosts"] == 2 and info["spans"] == 2
    assert info["markers"] >= 2          # the heartbeats render as markers
    doc = json.load(open(info["path"]))
    names = {e["args"]["name"] for e in doc["traceEvents"]
             if e.get("name") == "process_name"}
    assert {"client spans", "client events",
            "server spans", "server events"} <= names
    # both spans on ONE aligned clock: the server span (local t=0.3,
    # offset 1002) must land AFTER the client span (t=1.2, offset 1000)
    by_name = {e["name"]: e for e in doc["traceEvents"]
               if e.get("ph") == "X"}
    assert by_name["request"]["ts"] > by_name["client_request"]["ts"]


def test_cli_fleet_writes_rollup_and_doctor_routes(tmp_path, capsys):
    from raft_stereo_tpu.obs import doctor
    for name, dispatch in (("h0", 0.01), ("h1", 0.25)):
        _host_log(tmp_path / "fleet", name,
                  _train_host(name, 1000.0, 5, dispatch))
    assert fleet.main([str(tmp_path / "fleet"), "--json"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["n_hosts"] == 2
    assert any(v["verdict"] == "STRAGGLER" and v["host"] == "h1"
               for v in report["verdicts"])
    assert os.path.exists(tmp_path / "fleet" / "fleet_rollup.json")
    assert os.path.exists(tmp_path / "fleet" / "fleet_timeline.json")
    # doctor pointed at the fleet dir routes to the fleet verdicts
    diag = doctor.diagnose(str(tmp_path / "fleet"))
    assert any(v["verdict"] == "STRAGGLER" for v in diag["verdicts"])
    # an empty dir is a loud exit 1, not a stack trace
    (tmp_path / "empty").mkdir()
    assert fleet.main([str(tmp_path / "empty")]) == 1


def test_cli_drift_v8_fires_on_seeded_fleet_fixture(tmp_path):
    """Rule v8: an orphan flag on the fleet surface is an error — the
    fixture seeds an unconsumed flag next to consumed ones; flags the
    obs/fleet.py consumer reads stay clean."""
    from raft_stereo_tpu.analysis.ast_rules import (
        RULE_VERSIONS, check_entry_surface_drift)

    assert RULE_VERSIONS["cli-drift"] == 10
    pkg = tmp_path / "raft_stereo_tpu"
    (pkg / "obs").mkdir(parents=True)
    (pkg / "cli.py").write_text(
        "def build_fleet_parser():\n"
        "    import argparse\n"
        "    p = argparse.ArgumentParser()\n"
        "    p.add_argument('fleet_dir')\n"
        "    p.add_argument('--out')\n"
        "    p.add_argument('--fleet_orphan')\n"
        "    return p\n")
    (pkg / "obs" / "fleet.py").write_text(
        "def main(args):\n"
        "    return (args.fleet_dir, args.out)\n")
    findings = check_entry_surface_drift(str(tmp_path))
    errors = [f for f in findings
              if f.rule == "cli-drift" and f.severity == "error"]
    assert {f.data.get("dest") for f in errors} == {"fleet_orphan"}
    assert {f.data.get("surface") for f in errors} == {"build_fleet_parser"}
