"""Pallas correlation kernels vs the pure-JAX oracles (CPU interpreter mode).

The reference validates its CUDA kernels only implicitly (reg is reg_cuda's
oracle, SURVEY §4.3); here the cross-implementation parity — forward AND
backward — is an explicit test, runnable without a TPU via the Pallas
interpreter.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_stereo_tpu.ops.corr import corr_lookup, init_corr
from raft_stereo_tpu.ops.geometry import coords_grid
from raft_stereo_tpu.ops.pallas.corr_kernels import (
    alt_windowed_corr_pallas,
    windowed_sample_pallas,
)
from raft_stereo_tpu.ops.sampler import windowed_linear_sample


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(0)
    b, h, w, d = 2, 4, 16, 32
    f1 = jnp.asarray(rng.normal(size=(b, h, w, d)), jnp.float32)
    f2 = jnp.asarray(rng.normal(size=(b, h, w, d)), jnp.float32)
    vol = jnp.asarray(rng.normal(size=(b, h, w, w)), jnp.float32)
    centers = jnp.asarray(rng.uniform(-4, w + 4, size=(b, h, w)), jnp.float32)
    return f1, f2, vol, centers


class TestWindowedSamplePallas:
    def test_forward_matches_oracle(self, data):
        _, _, vol, centers = data
        for r in (1, 4):
            want = windowed_linear_sample(vol, centers, r)
            got = windowed_sample_pallas(vol, centers, r)
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       atol=1e-5, rtol=1e-5)

    def test_backward_matches_oracle(self, data):
        _, _, vol, centers = data
        rng = np.random.default_rng(1)
        ct = jnp.asarray(rng.normal(size=(2, 4, 16, 9)), jnp.float32)

        def fast(v, c):
            return jnp.sum(windowed_sample_pallas(v, c, 4) * ct)

        def oracle(v, c):
            return jnp.sum(windowed_linear_sample(v, c, 4) * ct)

        gv_f, gc_f = jax.grad(fast, argnums=(0, 1))(vol, centers)
        gv_o, gc_o = jax.grad(oracle, argnums=(0, 1))(vol, centers)
        np.testing.assert_allclose(np.asarray(gv_f), np.asarray(gv_o),
                                   atol=1e-5, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(gc_f), np.asarray(gc_o),
                                   atol=1e-4, rtol=1e-4)


class TestAltFusedPallas:
    def test_forward_matches_alt(self, data):
        f1, f2, _, centers = data
        d = f1.shape[-1]
        vol = jnp.einsum("bhwd,bhvd->bhwv", f1, f2) / jnp.sqrt(jnp.float32(d))
        want = windowed_linear_sample(vol, centers, 4)
        got = alt_windowed_corr_pallas(f1, f2, centers, 4)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-4, rtol=1e-4)

    def test_backward_matches_alt(self, data):
        f1, f2, _, centers = data
        rng = np.random.default_rng(2)
        ct = jnp.asarray(rng.normal(size=(2, 4, 16, 9)), jnp.float32)
        d = f1.shape[-1]

        def fused(a, b):
            return jnp.sum(alt_windowed_corr_pallas(a, b, centers, 4) * ct)

        def oracle(a, b):
            vol = jnp.einsum("bhwd,bhvd->bhwv", a, b) / jnp.sqrt(jnp.float32(d))
            return jnp.sum(windowed_linear_sample(vol, centers, 4) * ct)

        g_f = jax.grad(fused, argnums=(0, 1))(f1, f2)
        g_o = jax.grad(oracle, argnums=(0, 1))(f1, f2)
        for a, b in zip(g_f, g_o):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)


class TestRegistryIntegration:
    @pytest.mark.parametrize("impl", ["reg_pallas", "alt_pallas"])
    def test_lookup_matches_reg(self, impl, data):
        f1, f2, _, _ = data
        b, h, w, _ = f1.shape
        coords = coords_grid(b, h, w) + 1.3
        ref_state = init_corr("reg", f1, f2, num_levels=2, radius=3)
        want = corr_lookup(ref_state, coords)
        state = init_corr(impl, f1, f2, num_levels=2, radius=3)
        got = corr_lookup(state, coords)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-4, rtol=1e-4)
