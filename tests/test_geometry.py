import numpy as np
import jax.numpy as jnp
import pytest

from raft_stereo_tpu.ops.geometry import (
    InputPadder,
    avg_pool2d,
    coords_grid,
    extract_3x3_patches,
    pool2x,
    pool_w2,
    resize_bilinear_align_corners,
    upflow,
    upsample_flow_convex,
)


class TestCoordsGrid:
    def test_channels_are_x_then_y(self):
        g = coords_grid(1, 2, 3)
        assert g.shape == (1, 2, 3, 2)
        np.testing.assert_allclose(g[0, :, :, 0], [[0, 1, 2], [0, 1, 2]])
        np.testing.assert_allclose(g[0, :, :, 1], [[0, 0, 0], [1, 1, 1]])


class TestAvgPool:
    def test_pool_w2_floor_drops_odd_tail(self):
        x = jnp.arange(5.0).reshape(1, 1, 5, 1)
        out = pool_w2(x)
        np.testing.assert_allclose(out[0, 0, :, 0], [0.5, 2.5])

    def test_pool2x_count_include_pad(self):
        """3x3 s2 p1 pool divides by 9 even at padded borders (torch default)."""
        x = jnp.ones((1, 4, 4, 1))
        out = pool2x(x)
        assert out.shape == (1, 2, 2, 1)
        np.testing.assert_allclose(out[0, 0, 0, 0], 4.0 / 9.0, rtol=1e-6)
        np.testing.assert_allclose(out[0, 1, 1, 0], 1.0, rtol=1e-6)

    def test_matches_torch_avg_pool(self):
        torch = pytest.importorskip("torch")
        rng = np.random.default_rng(2)
        x = rng.standard_normal((2, 7, 9, 3)).astype(np.float32)
        got = np.asarray(pool2x(jnp.asarray(x)))
        want = torch.nn.functional.avg_pool2d(
            torch.from_numpy(x).permute(0, 3, 1, 2), 3, stride=2, padding=1
        ).permute(0, 2, 3, 1).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


class TestResize:
    def test_matches_torch_interpolate_align_corners(self):
        torch = pytest.importorskip("torch")
        rng = np.random.default_rng(3)
        x = rng.standard_normal((2, 5, 8, 4)).astype(np.float32)
        got = np.asarray(resize_bilinear_align_corners(jnp.asarray(x), (10, 16)))
        want = torch.nn.functional.interpolate(
            torch.from_numpy(x).permute(0, 3, 1, 2), (10, 16),
            mode="bilinear", align_corners=True,
        ).permute(0, 2, 3, 1).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_upflow_matches_torch(self):
        torch = pytest.importorskip("torch")
        rng = np.random.default_rng(4)
        x = rng.standard_normal((1, 3, 4, 2)).astype(np.float32)
        got = np.asarray(upflow(jnp.asarray(x), 8))
        want = 8 * torch.nn.functional.interpolate(
            torch.from_numpy(x).permute(0, 3, 1, 2), (24, 32),
            mode="bilinear", align_corners=True,
        ).permute(0, 2, 3, 1).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-4)


class TestConvexUpsample:
    def test_patch_order_matches_unfold(self):
        torch = pytest.importorskip("torch")
        rng = np.random.default_rng(5)
        x = rng.standard_normal((1, 4, 5, 2)).astype(np.float32)
        got = np.asarray(extract_3x3_patches(jnp.asarray(x)))  # (B,H,W,9,C)
        unf = torch.nn.functional.unfold(
            torch.from_numpy(x).permute(0, 3, 1, 2), [3, 3], padding=1
        ).view(1, 2, 9, 4, 5).permute(0, 3, 4, 2, 1).numpy()  # (B,H,W,9,C)
        np.testing.assert_allclose(got, unf, rtol=1e-6)

    def test_matches_reference_upsample_flow(self):
        """Full convex upsampling vs a torch transcription of
        core/raft_stereo.py:55-67 executed as an oracle."""
        torch = pytest.importorskip("torch")
        rng = np.random.default_rng(6)
        n, h, w, factor = 2, 3, 4, 4
        flow = rng.standard_normal((n, h, w, 2)).astype(np.float32)
        mask = rng.standard_normal((n, h, w, 9 * factor * factor)).astype(np.float32)

        got = np.asarray(upsample_flow_convex(jnp.asarray(flow), jnp.asarray(mask),
                                              factor))

        tf = torch.from_numpy(flow).permute(0, 3, 1, 2)
        tm = torch.from_numpy(mask).permute(0, 3, 1, 2)
        tm = tm.view(n, 1, 9, factor, factor, h, w)
        tm = torch.softmax(tm, dim=2)
        up = torch.nn.functional.unfold(factor * tf, [3, 3], padding=1)
        up = up.view(n, 2, 9, 1, 1, h, w)
        up = torch.sum(tm * up, dim=2)
        up = up.permute(0, 1, 4, 2, 5, 3)
        want = up.reshape(n, 2, factor * h, factor * w).permute(0, 2, 3, 1).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


class TestInputPadder:
    def test_pad_unpad_roundtrip(self):
        x = jnp.asarray(np.random.default_rng(7).standard_normal((1, 37, 51, 3)),
                        dtype=jnp.float32)
        padder = InputPadder(x.shape, divis_by=32)
        padded = padder.pad(x)
        assert padded.shape[1] % 32 == 0 and padded.shape[2] % 32 == 0
        np.testing.assert_allclose(padder.unpad(padded), x)

    def test_already_divisible_no_pad(self):
        x = jnp.zeros((1, 64, 96, 3))
        padder = InputPadder(x.shape, divis_by=32)
        assert padder.pad(x).shape == x.shape

    def test_kitti_mode_pads_top(self):
        x = jnp.zeros((1, 37, 64, 3))
        padder = InputPadder(x.shape, mode="kitti", divis_by=32)
        assert padder._pad == [0, 0, 0, 27]


def test_upsample_disparity_matches_generic():
    """Single-channel TPU-layout upsample == generic convex upsample, ch 0."""
    from raft_stereo_tpu.ops.geometry import (upsample_disparity_convex,
                                              upsample_flow_convex)
    rng = np.random.default_rng(5)
    for factor in (2, 4, 8):
        flow = jnp.asarray(rng.normal(size=(2, 6, 8, 2)), jnp.float32)
        mask = jnp.asarray(rng.normal(size=(2, 6, 8, 9 * factor * factor)),
                           jnp.float32)
        want = upsample_flow_convex(flow, mask, factor)[..., :1]
        got = upsample_disparity_convex(flow, mask, factor)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5, rtol=1e-5)
