"""The convergence observatory (obs/converge.py + schema v8):

* in-graph per-iteration EPE aux vs a NumPy oracle on a seeded frame,
  and the per_sample/batch-mean consistency of the residual curves;
* event emission + v8 lint across the eval paths (sequential and
  streaming) with a real tiny predictor, and across the serve retire
  path (converge events, slo quality rollup, Prometheus gauges);
* the early-exit simulator's math pinned on hand-built curves
  (downsample/exit_iter/simulate/decision_table/exit_percentile);
* the OVER_ITERATED doctor verdict on a seeded log, plus its negative
  case;
* the --no_converge zero-overhead pin: converge-off predictors keep the
  exact prior HLO and a same-seed double run emits an identical event
  stream; converge-on flows stay bitwise-equal to converge-off ones;
* schema v8 is additive: v1-v7-stamped records still validate, a
  v7-stamped converge record flags drift, and the converge lint catches
  malformed curves;
* cli-drift rule v7: the build_converge_parser surface fires on a
  seeded orphan flag while the consumed policy-emission flags
  (--emit-policy/--policy-tau) stay clean.
"""

import json
from pathlib import Path

import numpy as np
import pytest

import jax

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.eval.stream import StreamConfig, run_frames
from raft_stereo_tpu.inference import StereoPredictor
from raft_stereo_tpu.models import init_model
from raft_stereo_tpu.obs import Telemetry, read_events
from raft_stereo_tpu.obs import converge as cv
from raft_stereo_tpu.obs.events import make_record, validate_record
from raft_stereo_tpu.obs.validate import (check_converge_integrity,
                                          check_path)

REPO = Path(__file__).resolve().parents[1]

H, W = 32, 64          # /32-exact so model-level oracles need no padding
ITERS = 3


@pytest.fixture(scope="module")
def tiny():
    cfg = RAFTStereoConfig(hidden_dims=(32, 32, 32))
    model, variables = init_model(jax.random.PRNGKey(0), cfg, (1, H, W, 3))
    return cfg, model, variables


# module-scoped predictors: the compiled flavors are shared across tests
# (each StereoPredictor carries its own jit cache, and tier-1 wall time is
# dominated by tiny-model compiles, not by the work itself)

@pytest.fixture(scope="module")
def pred_on(tiny):
    cfg, _, variables = tiny
    return StereoPredictor(cfg, variables, valid_iters=ITERS, iter_epe=True)


@pytest.fixture(scope="module")
def pred_off(tiny):
    cfg, _, variables = tiny
    return StereoPredictor(cfg, variables, valid_iters=ITERS)


def _frame(seed, h=H, w=W):
    rng = np.random.default_rng(seed)
    left = rng.integers(0, 255, (h, w, 3)).astype(np.float32)
    right = rng.integers(0, 255, (h, w, 3)).astype(np.float32)
    flow = -np.abs(rng.normal(4.0, 1.0, (h, w, 1))).astype(np.float32)
    valid = np.ones((h, w, 1), np.float32)
    valid[: h // 4] = 0.0      # a masked-out band exercises the pooling
    return {"image1": left, "image2": right, "flow": flow, "valid": valid}


class _GTData:
    """Stub dataset with GT flow for run_frames."""

    def __init__(self, n=3, h=H, w=W, seed=0):
        self._samples = [_frame(seed + i, h, w) for i in range(n)]

    def __len__(self):
        return len(self._samples)

    def sample(self, i):
        return self._samples[i]


def _oracle_epe(flow_lr, flow_gt, valid, factor):
    """NumPy twin of the model's pooled low-res EPE aux for one batch."""
    b, h, w, _ = flow_lr.shape
    gt = flow_gt[..., 0]
    m = valid[..., 0]
    gt_c = gt.reshape(b, h, factor, w, factor)
    m_c = m.reshape(b, h, factor, w, factor)
    msum = m_c.sum(axis=(2, 4))
    gt_pool = (gt_c * m_c).sum(axis=(2, 4)) / np.maximum(msum, 1.0)
    cell_valid = (msum > 0).astype(np.float64)
    denom = np.maximum(cell_valid.sum(axis=(1, 2)), 1.0)
    err = np.abs(flow_lr[..., 0] * factor - gt_pool)
    return (err * cell_valid).sum(axis=(1, 2)) / denom


# ------------------------------------------------ in-graph aux vs oracle

def test_iter_epe_aux_matches_numpy_oracle(tiny):
    cfg, model, variables = tiny
    s = _frame(7)
    im = s["image1"][None]
    out = model.apply(variables, im, s["image2"][None], iters=ITERS,
                      test_mode=True, iter_metrics="per_sample",
                      flow_gt=s["flow"][None], loss_mask=s["valid"][None])
    flow_lr, flow_up, deltas, epes = out
    assert deltas.shape == (ITERS, 1) and epes.shape == (ITERS, 1)
    assert np.all(np.isfinite(np.asarray(epes)))
    oracle = _oracle_epe(np.asarray(flow_lr, np.float64),
                         s["flow"][None].astype(np.float64),
                         s["valid"][None].astype(np.float64), cfg.factor)
    np.testing.assert_allclose(np.asarray(epes)[-1], oracle,
                               rtol=1e-4, atol=1e-5)
    # the aux rides along without perturbing the prediction
    _, up_plain = model.apply(variables, im, s["image2"][None], iters=ITERS,
                              test_mode=True)
    np.testing.assert_array_equal(np.asarray(up_plain), np.asarray(flow_up))


def test_per_sample_curves_consistent_with_batch_mean(tiny):
    cfg, model, variables = tiny
    a, b = _frame(1), _frame(2)
    im1 = np.stack([a["image1"], b["image1"]])
    im2 = np.stack([a["image2"], b["image2"]])
    gt = np.stack([a["flow"], b["flow"]])
    va = np.stack([a["valid"], b["valid"]])
    kw = dict(iters=ITERS, test_mode=True, flow_gt=gt, loss_mask=va)
    _, _, d_ps, e_ps = model.apply(variables, im1, im2,
                                   iter_metrics="per_sample", **kw)
    _, _, d_mean, e_mean = model.apply(variables, im1, im2,
                                       iter_metrics=True, **kw)
    assert d_ps.shape == (ITERS, 2) and d_mean.shape == (ITERS,)
    np.testing.assert_allclose(np.asarray(d_ps).mean(axis=1),
                               np.asarray(d_mean), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(e_ps).mean(axis=1),
                               np.asarray(e_mean), rtol=1e-5, atol=1e-6)


def test_gt_without_iter_metrics_is_loud(tiny):
    _, model, variables = tiny
    s = _frame(3)
    with pytest.raises(ValueError, match="iter_metrics"):
        model.apply(variables, s["image1"][None], s["image2"][None],
                    iters=2, test_mode=True, flow_gt=s["flow"][None])


# ------------------------------------------------- simulator math pins

def test_downsample_keeps_endpoints_strictly_increasing():
    vals = list(np.linspace(1.0, 0.0, 50))
    idx, res = cv.downsample(vals, max_points=8)
    assert len(idx) <= 8 and idx[0] == 0 and idx[-1] == 49
    assert all(b > a for a, b in zip(idx, idx[1:]))
    assert res == [vals[i] for i in idx]
    # short curves come back whole
    idx, res = cv.downsample([3.0, 2.0], max_points=8)
    assert idx == [0, 1] and res == [3.0, 2.0]
    assert cv.downsample([], 4) == ([], [])


def test_half_life_and_payload():
    payload = cv.converge_payload("eval:t", 4, [1.0, 0.4, 0.1, 0.02],
                                  epe=[2.0, 1.5, 1.2, 1.1], bucket="32x64")
    assert payload["idx"] == [0, 1, 2, 3]
    assert payload["half_life"] == 1          # 0.4 <= 1.0 / 2
    assert payload["final_residual"] == 0.02
    assert payload["epe"] == [2.0, 1.5, 1.2, 1.1]
    rec = make_record("converge", t=1.0, **payload)
    assert validate_record(rec) == []
    assert check_converge_integrity([rec]) == []


def test_simulate_pins_on_hand_built_curve():
    rec = {"iters": 4, "idx": [0, 1, 2, 3],
           "residual": [1.0, 0.4, 0.1, 0.02], "epe": [2.0, 1.5, 1.2, 1.1]}
    assert cv.exit_iter(rec["idx"], rec["residual"], 0.5) == 2
    assert cv.exit_iter(rec["idx"], rec["residual"], 0.01) is None
    s = cv.simulate(rec, 0.5)
    assert s == {"converged": True, "exit_iter": 2, "saved": 2,
                 "epe_delta": pytest.approx(0.4)}
    s = cv.simulate(rec, 0.01)     # never converges: full budget, no delta
    assert s == {"converged": False, "exit_iter": 4, "saved": 0,
                 "epe_delta": pytest.approx(0.0)}


def test_decision_table_and_exit_percentile():
    fast = {"iters": 8, "idx": [0, 3, 7], "residual": [1.0, 0.04, 0.01],
            "source": "eval:things", "bucket": "32x64"}
    slow = {"iters": 8, "idx": [0, 3, 7], "residual": [1.0, 0.5, 0.2],
            "source": "eval:things", "bucket": "64x128"}
    recs = [fast] * 3 + [slow]
    ev = cv.exit_percentile(recs, tau=0.05, q=95.0)
    # the never-converged curve counts as the full budget
    assert ev["budget"] == 8 and ev["exit_iter"] == 8
    assert ev["n"] == 4 and ev["n_converged"] == 3
    assert cv.exit_percentile([fast] * 4, tau=0.05)["exit_iter"] == 4
    assert cv.exit_percentile([], tau=0.05) is None
    rows = cv.decision_table(recs, taus=(0.05,), bucket_by="both")
    by_bucket = {r["bucket"]: r for r in rows}
    assert set(by_bucket) == {"32x64", "64x128", "*"}
    assert by_bucket["32x64"]["converged_frac"] == 1.0
    assert by_bucket["32x64"]["exit_p50"] == 4
    assert by_bucket["32x64"]["saved_mean"] == 4.0
    assert by_bucket["64x128"]["converged_frac"] == 0.0
    assert by_bucket["*"]["n"] == 4
    assert by_bucket["*"]["epe_delta_mean"] is None   # no epe curves
    only_all = cv.decision_table(recs, taus=(0.05,), bucket_by="all")
    assert {r["bucket"] for r in only_all} == {"*"}
    assert "saved" in cv.format_table(rows)


# --------------------------------------------------- converge lint (v8)

def test_converge_lint_catches_malformed_curves():
    def rec(**kw):
        base = dict(source="eval:t", iters=4, idx=[0, 1, 2, 3],
                    residual=[1.0, 0.4, 0.1, 0.02])
        base.update(kw)
        return make_record("converge", t=1.0, **base)

    assert check_converge_integrity([rec()]) == []
    assert any("residual values" in e for e in check_converge_integrity(
        [rec(residual=[1.0, 0.4])]))
    assert any("strictly increasing" in e for e in check_converge_integrity(
        [rec(idx=[0, 2, 1, 3])]))
    assert any("cover [0, iters-1]" in e for e in check_converge_integrity(
        [rec(idx=[0, 1, 2, 2])]))   # last != iters-1 (also non-monotone)
    assert any("exceed the iteration budget" in e
               for e in check_converge_integrity(
                   [rec(iters=2, idx=[0, 1, 2, 3])]))
    assert any("non-finite residual" in e for e in check_converge_integrity(
        [rec(residual=[1.0, float("nan"), 0.1, 0.02])]))
    assert any("epe curve length" in e for e in check_converge_integrity(
        [rec(epe=[1.0])]))
    assert any("malformed" in e for e in check_converge_integrity(
        [rec(idx="nope")]))


def test_schema_v8_additive_and_v7_stamp_is_drift():
    good = make_record("converge", t=1.0, source="eval:t", iters=4,
                       idx=[0, 3], residual=[1.0, 0.1])
    assert validate_record(good) == []
    stale = dict(good, schema=7)
    assert any("introduced in schema 8" in e for e in validate_record(stale))
    missing = {k: v for k, v in good.items() if k != "idx"}
    assert any("idx" in e for e in validate_record(missing))
    # pre-v8 records validate against their own surface (additive bump)
    for ver, event, payload in [
            (1, "step", dict(step=1, data_wait_s=0.1, dispatch_s=0.1,
                             fetch_s=0.1)),
            (5, "anomaly", dict(kind="nonfinite_grad")),
            (6, "slo", dict(p50_ms=1.0, p99_ms=2.0, pairs_per_sec=3.0,
                            in_flight=1)),
            (7, "span", dict(name="x", span_id="s1", trace_id="t1",
                             start_s=0.0, dur_s=0.1))]:
        rec = dict(make_record(event, t=1.0, **payload), schema=ver)
        assert validate_record(rec) == [], (ver, event)
    # the v8 slo quality extra rides along without a required-field change
    slo = make_record("slo", t=1.0, p50_ms=1.0, p99_ms=2.0,
                      pairs_per_sec=3.0, in_flight=1,
                      quality={"32x64": {"final_residual_p50": 0.01,
                                         "final_residual_p95": 0.02,
                                         "n": 4}})
    assert validate_record(slo) == []


def test_checked_in_artifacts_still_lint_clean_under_v8():
    import glob as globmod
    olds = sorted(globmod.glob(str(REPO / "runs" / "**" / "events.jsonl"),
                               recursive=True))
    for path in olds:
        assert check_path(path) == [], path


# --------------------------------------- eval paths: emission + v8 lint

def _eval_run(tmp_path, name, ds, predictor, stream, **kw):
    tel = Telemetry(str(tmp_path / name), stall_deadline_s=None)
    tel.run_start(config={"mode": "eval"})
    run_frames(predictor, ds, lambda *a: None, iters=ITERS,
               stream=stream, telemetry=tel, source="things", **kw)
    tel.emit("run_end", steps=tel.steps, ok=True)
    tel.close()
    return read_events(str(tmp_path / name / "events.jsonl"))


def test_eval_emits_converge_events_both_paths(tmp_path, pred_on):
    ds = _GTData(n=3)
    predictor = pred_on
    assert predictor.converge    # iter_epe implies the residual aux
    seq = _eval_run(tmp_path, "seq", ds, predictor, stream=False)
    st = _eval_run(tmp_path, "stream", ds, predictor,
                   stream=StreamConfig(enabled=True, window=2, microbatch=2))
    for name, events in (("seq", seq), ("stream", st)):
        curves = [e for e in events if e.get("event") == "converge"]
        assert len(curves) == 3, name
        for c in curves:
            assert c["source"] == "eval:things"
            assert c["bucket"] == f"{H}x{W}"
            assert c["iters"] == ITERS and len(c["idx"]) == ITERS
            assert len(c["epe"]) == ITERS      # GT dataset -> epe rides
            assert "frame" in c and "final_residual" in c
        assert check_path(str(tmp_path / name)) == []
    # the recorded run feeds the simulator end to end
    rows = cv.decision_table(cv.load_records(str(tmp_path / "stream")),
                             taus=(1e9,), bucket_by="all")
    assert rows and rows[0]["n"] == 3 and rows[0]["converged_frac"] == 1.0
    assert rows[0]["n_epe"] == 3


def test_converge_without_gt_and_stub_predictors(tmp_path, tiny):
    """converge=True alone (no iter_epe) records residual-only curves; a
    GT-less sample set never sees gt kwargs; stub predictors without the
    aux API emit nothing."""
    cfg, _, variables = tiny
    ds = _GTData(n=2)
    predictor = StereoPredictor(cfg, variables, valid_iters=ITERS,
                                converge=True)
    events = _eval_run(tmp_path, "nogt", ds, predictor, stream=False)
    curves = [e for e in events if e.get("event") == "converge"]
    assert len(curves) == 2 and all("epe" not in c for c in curves)
    assert check_path(str(tmp_path / "nogt")) == []

    class _Stub:
        def __call__(self, im1, im2, iters, **kw):
            assert not kw          # no gt kwargs leak to stub predictors
            return np.zeros((im1.shape[0],) + im1.shape[1:3] + (1,),
                            np.float32)

    events = _eval_run(tmp_path, "stub", ds, _Stub(), stream=False)
    assert [e for e in events if e.get("event") == "converge"] == []


def test_no_converge_is_zero_overhead(tmp_path, tiny, pred_off, pred_on):
    """The --no_converge pin: converge-off keeps the exact prior HLO, a
    same-seed double run emits an identical event stream (modulo wall
    clock), and converge-on flows are bitwise-equal to converge-off."""
    cfg, model, variables = tiny
    ds = _GTData(n=2)
    off1 = off2 = pred_off
    on = pred_on
    ev1 = _eval_run(tmp_path, "off1", ds, off1, stream=False)
    ev2 = _eval_run(tmp_path, "off2", ds, off2, stream=False)

    def scrub(events):
        # compile events depend on the process-level jit cache (the first
        # run pays for shared helpers), and the wall-clock/run-name fields
        # differ by construction — the semantic stream must not (the v10
        # clock_anchor is monotonic/wall by definition, so it goes too)
        return [{k: v for k, v in e.items()
                 if k not in ("t", "ts", "run", "path", "data_wait_s",
                              "dispatch_s", "fetch_s")}
                for e in events
                if e.get("event") not in ("compile", "clock_anchor")]

    assert scrub(ev1) == scrub(ev2)
    assert [e for e in ev1 if e.get("event") == "converge"] == []
    assert off1.take_aux() is None
    # numerics: the aux never perturbs the flow
    s = ds.sample(0)
    flow_off = off1(s["image1"][None], s["image2"][None], ITERS)
    flow_on = on(s["image1"][None], s["image2"][None], ITERS,
                 flow_gt=s["flow"][None], valid=s["valid"][None])
    np.testing.assert_array_equal(flow_off, flow_on)
    aux = on.take_aux()
    assert set(aux) == {"residual", "epe"}
    assert aux["residual"].shape == (ITERS, 1)
    assert on.take_aux() is None          # popped once
    # HLO pin: the converge-off program IS the prior plain-test_mode one
    spec = jax.ShapeDtypeStruct((1, H, W, 3), np.float32)
    vspec = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype), variables)

    def run_off(v, a, b):
        return model.apply(v, a, b, iters=ITERS, test_mode=True,
                           iter_metrics=False, flow_gt=None)

    def run_prior(v, a, b):
        return model.apply(v, a, b, iters=ITERS, test_mode=True)

    run_off.__name__ = run_prior.__name__ = "forward"   # same module name
    text_off = jax.jit(run_off).lower(vspec, spec, spec).as_text()
    text_prior = jax.jit(run_prior).lower(vspec, spec, spec).as_text()
    assert text_off == text_prior


def test_predict_async_carries_aux_on_handle(pred_on, pred_off):
    predictor = pred_on
    s = _frame(9)
    handle = predictor.predict_async(
        s["image1"][None], s["image2"][None], ITERS,
        flow_gt=s["flow"][None], valid=s["valid"][None])
    flow = handle.result()
    aux = handle.aux_result()
    assert flow.shape == (1, H, W, 1)
    assert set(aux) == {"residual", "epe"}
    assert aux["residual"].shape == (ITERS, 1)
    assert handle.aux_result() is aux     # fetched once, then cached
    # converge-off handles carry no aux
    assert pred_off.predict_async(
        s["image1"][None], s["image2"][None], ITERS).aux_result() is None


# ------------------------------------------------- doctor: OVER_ITERATED

def _seeded_converge_log(tmp_path, exit_at, budget=22, n=8):
    """A run dir whose curves all settle below DOCTOR_TAU at exit_at."""
    run = tmp_path / "run"
    tel = Telemetry(str(run), stall_deadline_s=None)
    tel.run_start(config={})
    for i in range(n):
        residual = [1.0 if k < exit_at else cv.DOCTOR_TAU / 2
                    for k in range(budget)]
        cv.emit(tel, "eval:things", budget, residual,
                bucket="32x64", frame=i)
    tel.emit("run_end", steps=n, ok=True)
    tel.close()
    return str(run)


def test_doctor_over_iterated_verdict_with_evidence(tmp_path, capsys):
    from raft_stereo_tpu.obs.doctor import diagnose, main
    run = _seeded_converge_log(tmp_path, exit_at=7)
    report = diagnose(run)
    v = next(v for v in report["verdicts"] if v["phase"] == "converge")
    assert v["verdict"] == "OVER_ITERATED"
    assert any("p95 converged by iter 8 of 22" in e for e in v["evidence"])
    assert any("cli converge" in e for e in v["evidence"])
    assert main([run, "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert any(x["verdict"] == "OVER_ITERATED" for x in out["verdicts"])


def test_doctor_over_iterated_negative_cases(tmp_path):
    from raft_stereo_tpu.obs.doctor import diagnose
    # exits at the budget edge: inside the margin, no verdict
    run = _seeded_converge_log(tmp_path / "edge", exit_at=21)
    assert all(v["verdict"] != "OVER_ITERATED"
               for v in diagnose(run)["verdicts"])
    # too few curves: no verdict
    run = _seeded_converge_log(tmp_path / "few", exit_at=7, n=2)
    assert all(v["verdict"] != "OVER_ITERATED"
               for v in diagnose(run)["verdicts"])


# ----------------------------------------- serve: quality gauges + events

class _Fake4Cache:
    """Fake converge-flavor executable: 4 outputs incl. (iters, B) curves."""

    def __len__(self):
        return 1

    def __call__(self, key, im1, im2, flow_init=None):
        b, h, w, _ = im1.shape
        deltas = np.linspace(1.0, 0.01, key.iters)[:, None].repeat(b, 1)
        return (np.zeros((b, h // 4, w // 4, 2), np.float32),
                np.full((b, h, w, 1), 7.0, np.float32),
                np.ones((b,), bool),
                deltas.astype(np.float32))


def _serve_run(tmp_path, name, cache):
    from raft_stereo_tpu.serve import ServeConfig, StereoServer
    tel = Telemetry(str(tmp_path / name), stall_deadline_s=None)
    tel.run_start(config={"mode": "serve"})
    stub_vars = {"params": {"w": np.zeros((1,), np.float32)}}
    server = StereoServer(
        RAFTStereoConfig(), stub_vars,
        ServeConfig(max_batch=2, window=2, default_iters=4, linger_s=0.0,
                    slo_every=1),
        telemetry=tel, autostart=False)
    server.cache = cache
    server.start()
    rng = np.random.default_rng(0)
    results = []
    for i in range(3):
        left = rng.random((H, W, 3)).astype(np.float32)
        right = rng.random((H, W, 3)).astype(np.float32)
        results.append(server.submit(left, right).result(timeout=60))
    server.request_drain()
    assert server.join(timeout=60)
    stats = server.stats()
    tel.emit("run_end", steps=3, ok=True)
    tel.close()
    return results, stats, read_events(str(tmp_path / name /
                                           "events.jsonl"))


def test_serve_converge_events_and_quality_rollup(tmp_path):
    from raft_stereo_tpu.serve.http import prometheus_metrics
    results, stats, events = _serve_run(tmp_path, "serve", _Fake4Cache())
    assert all(r.ok for r in results)
    assert all(r.final_residual == pytest.approx(0.01) for r in results)
    curves = [e for e in events if e.get("event") == "converge"]
    assert len(curves) == 3
    for c in curves:
        assert c["source"].startswith("serve:")
        assert c["iters"] == 4 and c["idx"][-1] == 3
        assert c["bucket"].count("x") == 1 and c["id"].startswith("r")
    reqs = [e for e in events if e.get("event") == "request"]
    assert all(r["final_residual"] == pytest.approx(0.01) for r in reqs)
    # the slo rollup carries the per-bucket quality gauges
    (bucket, q), = stats["quality"].items()
    assert q["n"] == 3
    assert q["final_residual_p50"] == pytest.approx(0.01)
    assert q["final_residual_p95"] == pytest.approx(0.01)
    slo = [e for e in events if e.get("event") == "slo"]
    assert any("quality" in e for e in slo)
    assert check_path(str(tmp_path / "serve")) == []
    # Prometheus exposition renders the labeled quality gauges
    text = prometheus_metrics(stats)
    assert f'raft_serve_final_residual_p50{{bucket="{bucket}"}}' in text
    assert f'raft_serve_quality_window_requests{{bucket="{bucket}"}} 3' \
        in text


def test_serve_no_converge_emits_nothing_extra(tmp_path):
    """A 3-output program (the --no_converge flavor) leaves the stream
    exactly as schema v7 had it: no converge events, no final_residual,
    no quality rollup — and a same-seed double run pins the identical
    request stream."""
    from raft_stereo_tpu.serve.http import prometheus_metrics
    from test_serve import _FakeCache

    def run(name):
        results, stats, events = _serve_run(tmp_path, name, _FakeCache())
        assert all(r.ok and r.final_residual is None for r in results)
        assert [e for e in events if e.get("event") == "converge"] == []
        assert "quality" not in stats
        assert all("final_residual" not in e for e in events
                   if e.get("event") == "request")
        assert "final_residual" not in prometheus_metrics(stats)
        return events

    a, b = run("off_a"), run("off_b")

    def scrub(events):
        drop = ("t", "ts", "run", "path", "latency_s", "queue_wait_s",
                "p50_ms", "p99_ms", "pairs_per_sec", "batch_size",
                "in_flight", "depth")
        return [{k: v for k, v in e.items() if k not in drop}
                for e in events
                if e.get("event") not in ("compile", "clock_anchor")]

    assert scrub(a) == scrub(b)


def test_serve_config_and_cache_default_flavors():
    from raft_stereo_tpu.serve import ServeConfig
    from raft_stereo_tpu.serve.cache import ExecutableCache
    assert ServeConfig().converge is True       # serving records by default
    stub = {"params": {"w": np.zeros((1,), np.float32)}}
    assert ExecutableCache(RAFTStereoConfig(), stub).converge is False


# ------------------------------------------------- cli surfaces + lint

def test_build_converge_parser_defaults():
    from raft_stereo_tpu.cli import build_converge_parser
    args = build_converge_parser().parse_args(["runs/x"])
    assert args.run_dir == "runs/x"
    assert args.taus is None and args.bucket_by == "both"
    assert args.json is None and args.out is None
    args = build_converge_parser().parse_args(
        ["runs/x", "--taus", "0.5", "0.1", "--bucket_by", "all",
         "--json", "-"])
    assert args.taus == [0.5, 0.1] and args.bucket_by == "all"
    assert args.json == "-"


def test_eval_serve_parsers_carry_converge_flags():
    from raft_stereo_tpu.cli import (build_eval_parser, build_serve_parser,
                                     serve_config)
    args = build_eval_parser().parse_args(["--dataset", "things"])
    assert not args.no_converge and not args.iter_epe
    args = build_serve_parser().parse_args(["--no_converge"])
    assert serve_config(args).converge is False
    args = build_serve_parser().parse_args([])
    assert serve_config(args).converge is True


def test_cli_converge_main_on_recorded_run(tmp_path, capsys):
    from raft_stereo_tpu.cli import main
    run = tmp_path / "run"
    tel = Telemetry(str(run), stall_deadline_s=None)
    tel.run_start(config={})
    for i in range(4):
        cv.emit(tel, "eval:things", 8,
                [1.0, 0.5, 0.2, 0.1, 0.04, 0.03, 0.02, 0.01],
                epe=[2.0] * 7 + [1.0], bucket="32x64", frame=i)
    tel.emit("run_end", steps=4, ok=True)
    tel.close()
    out_json = tmp_path / "table.json"
    assert main(["converge", str(run), "--json", "-",
                 "--out", str(out_json)]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["curves"] == 4 and doc["table"]
    assert doc["taus"] == list(cv.DEFAULT_TAUS)
    assert json.loads(out_json.read_text())["table"] == doc["table"]
    # empty run dir: loud exit 1
    assert main(["converge", str(tmp_path / "empty")]) == 1
    assert "no converge records" in capsys.readouterr().err
    # the command is advertised
    assert main([]) == 2


def test_cli_drift_v7_fires_on_seeded_converge_fixture(tmp_path):
    """Rule v7: an orphan flag on the converge surface is an error — the
    fixture seeds an unconsumed adaptive-era flag (--emit-policy declared
    but never read) next to a consumed one; flags the obs/converge.py
    consumer reads stay clean."""
    from raft_stereo_tpu.analysis.ast_rules import (
        RULE_VERSIONS, check_entry_surface_drift)

    assert RULE_VERSIONS["cli-drift"] == 10
    pkg = tmp_path / "raft_stereo_tpu"
    (pkg / "obs").mkdir(parents=True)
    (pkg / "cli.py").write_text(
        "def build_converge_parser():\n"
        "    import argparse\n"
        "    p = argparse.ArgumentParser()\n"
        "    p.add_argument('run_dir')\n"
        "    p.add_argument('--taus')\n"
        "    p.add_argument('--emit-policy', dest='emit_policy')\n"
        "    p.add_argument('--policy-tau', dest='policy_tau')\n"
        "    p.add_argument('--converge_orphan')\n"
        "    return p\n")
    (pkg / "obs" / "converge.py").write_text(
        "def main(args):\n"
        "    if args.emit_policy:\n"
        "        return (args.emit_policy, args.policy_tau)\n"
        "    return (args.run_dir, args.taus)\n")
    findings = check_entry_surface_drift(str(tmp_path))
    errors = [f for f in findings
              if f.rule == "cli-drift" and f.severity == "error"]
    assert {f.data.get("dest") for f in errors} == {"converge_orphan"}
    assert {f.data.get("surface")
            for f in errors} == {"build_converge_parser"}
