"""Split-compilation step (training/split_step.py) vs the monolithic step.

The split step is the same computation scheduled as separate XLA programs;
differences are fp32 reassociation noise (jit-vs-eager-scale), so the gates
mirror the shard_map equivalence tolerances.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_stereo_tpu.config import RAFTStereoConfig, TrainConfig
from raft_stereo_tpu.models import init_model
from raft_stereo_tpu.training.optim import fetch_optimizer
from raft_stereo_tpu.training.split_step import (_split_params,
                                                 make_split_train_step)
from raft_stereo_tpu.training.state import TrainState, make_train_step


def _setup(cfg_kwargs=None, batch_size=2, h=32, w=48):
    cfg = RAFTStereoConfig(**(cfg_kwargs or {}))
    tcfg = TrainConfig(num_steps=10, batch_size=batch_size, lr=1e-4)
    model, variables = init_model(jax.random.PRNGKey(0), cfg, (1, h, w, 3))
    tx = fetch_optimizer(tcfg)
    state = TrainState.create(variables, tx)
    rng = np.random.default_rng(7)
    batch = {
        "image1": jnp.asarray(rng.uniform(0, 255, (batch_size, h, w, 3)),
                              jnp.float32),
        "image2": jnp.asarray(rng.uniform(0, 255, (batch_size, h, w, 3)),
                              jnp.float32),
        "flow": jnp.asarray(rng.uniform(-8, 0, (batch_size, h, w, 1)),
                            jnp.float32),
        "valid": jnp.ones((batch_size, h, w), jnp.float32),
    }
    return model, tx, state, batch


def _fresh(state):
    return jax.tree.map(lambda x: jnp.array(x), state)


@pytest.mark.parametrize("fused", [True, False])
def test_split_step_matches_monolithic(fused):
    model, tx, state, batch = _setup()
    mono = jax.jit(make_train_step(model, tx, train_iters=2,
                                   fused_loss=fused))
    ref_state, ref_metrics = mono(_fresh(state), batch)

    split = make_split_train_step(model, tx, train_iters=2, fused_loss=fused)
    got_state, got_metrics = split(_fresh(state), batch)

    assert float(got_metrics["loss"]) == pytest.approx(
        float(ref_metrics["loss"]), rel=1e-4)
    for k in ref_metrics:
        assert float(got_metrics[k]) == pytest.approx(
            float(ref_metrics[k]), rel=1e-3, abs=1e-5), k
    for a, b in zip(jax.tree_util.tree_leaves(ref_state.params),
                    jax.tree_util.tree_leaves(got_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-5)
    assert int(got_state.step) == 1


def test_split_step_multiple_steps_and_shared_backbone():
    """Two consecutive split steps (state threading, cache reuse) on the
    shared-backbone arch (the conv2_res/conv2_out encoder keys)."""
    model, tx, state, batch = _setup(
        dict(shared_backbone=True, n_downsample=3, n_gru_layers=2))
    mono = jax.jit(make_train_step(model, tx, train_iters=2, fused_loss=True))
    s_ref, _ = mono(_fresh(state), batch)
    s_ref, m_ref = mono(s_ref, batch)

    split = make_split_train_step(model, tx, train_iters=2, fused_loss=True)
    s_got, _ = split(_fresh(state), batch)
    s_got, m_got = split(s_got, batch)

    assert int(s_got.step) == 2
    assert float(m_got["loss"]) == pytest.approx(float(m_ref["loss"]),
                                                 rel=1e-3)
    # AdamW's early steps are ~±lr·sign(grad) (v ≈ 0), so fp32 reassociation
    # noise on near-zero grads can flip an element's update sign; bound the
    # deviation by a few lr (1e-4) rather than a tight relative gate.
    for a, b in zip(jax.tree_util.tree_leaves(s_ref.params),
                    jax.tree_util.tree_leaves(s_got.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-2, atol=5e-4)


def test_split_params_partition():
    """Every top-level param key lands in exactly one piece, for both archs."""
    for kwargs in ({}, dict(shared_backbone=True, n_downsample=3,
                            n_gru_layers=2)):
        cfg = RAFTStereoConfig(**kwargs)
        _, variables = init_model(jax.random.PRNGKey(0), cfg, (1, 32, 48, 3))
        enc, rest = _split_params(variables["params"])
        assert set(enc) | set(rest) == set(variables["params"])
        assert not (set(enc) & set(rest))
        assert "cnet" in enc
        assert "refinement" in rest


def test_split_step_composes_with_norms_remat():
    """The bench's split+norms experiment path: with remat_encoders="norms"
    the policy's nn.remat lives inside the encode stage, so piece_enc's
    traced-vjp residuals are the policy's saved set (conv outputs + stats)
    — the schedule that fits batch 8 where full residuals OOM'd. Must be
    the monolithic norms step's math."""
    model, tx, state, batch = _setup(dict(remat_encoders="norms"))
    mono = jax.jit(make_train_step(model, tx, train_iters=2, fused_loss=True))
    s_ref, m_ref = mono(_fresh(state), batch)

    split = make_split_train_step(model, tx, train_iters=2, fused_loss=True)
    s_got, m_got = split(_fresh(state), batch)

    assert float(m_got["loss"]) == pytest.approx(float(m_ref["loss"]),
                                                 rel=1e-3)
    for a, b in zip(jax.tree_util.tree_leaves(s_ref.params),
                    jax.tree_util.tree_leaves(s_got.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-2, atol=5e-4)
