"""graftlint engine 3 (analysis/spmd_rules) + the fingerprint gate
(analysis/fingerprint): every SPMD rule fires on a seeded minimal
violation under the conftest's fake 8-device CPU mesh, the ring-corr
ppermute whitelist keys off the shared structure tag, fingerprint diffs
catch each drift class, and an injected structural regression flips
``cli lint`` to exit 1.

Fixtures are tiny synthetic shard_map programs (not the full model) so
each rule's trigger condition is explicit; the model-scale sharded path is
covered by the clean-tree test at the bottom (which lowers the real
canonical targets jaxpr-only) and by rehearse_round's lint/fingerprint
legs running the full compiled path every round.
"""

import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from raft_stereo_tpu.analysis import fingerprint as fp
from raft_stereo_tpu.analysis.findings import Finding, apply_baseline
from raft_stereo_tpu.analysis.spmd_rules import (DEFAULT_SPMD_THRESHOLDS,
                                                 SpmdTarget,
                                                 rule_accidental_replication,
                                                 rule_axis_leak,
                                                 rule_collective_dtype,
                                                 rule_collective_in_loop,
                                                 rule_donation_under_mesh)
from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.parallel.compat import shard_map
from raft_stereo_tpu.parallel.mesh import make_mesh
from raft_stereo_tpu.parallel.ring_corr import is_ring_perm, ring_perm

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual CPU mesh")


def spmd_target(fn, *example_args, name="fixture", mesh_shape=None,
                reduce_axes=(), **kw):
    return SpmdTarget(name=name, cfg=RAFTStereoConfig(),
                      closed_jaxpr=jax.make_jaxpr(fn)(*example_args),
                      mesh_shape=mesh_shape or {},
                      reduce_axes=reduce_axes, **kw)


def th(**overrides):
    return dict(DEFAULT_SPMD_THRESHOLDS, **overrides)


# ------------------------------------------------------ collective-in-loop

def test_psum_in_scan_body_fires():
    """The canonical seeded violation: a psum injected into the scan body
    = one collective per refinement iteration on the serial chain."""
    mesh = make_mesh(8, 1)

    def sharded(x):
        def body(c, _):
            return c + jax.lax.psum(c, "data"), None
        c, _ = jax.lax.scan(body, x, None, length=3)
        return c

    f = shard_map(sharded, mesh=mesh, in_specs=P("data"),
                  out_specs=P("data"))
    t = spmd_target(f, jnp.ones((8, 16)))
    fs = rule_collective_in_loop(t, th())
    assert len(fs) == 1
    assert fs[0].severity == "error"
    # shard_map's replication-rule rewrite spells the primitive psum2
    assert fs[0].data["primitive"].startswith("psum")
    assert "/scan[" in fs[0].location


def test_psum_outside_scan_is_clean():
    mesh = make_mesh(8, 1)

    def sharded(x):
        def body(c, _):
            return c * 2, None
        c, _ = jax.lax.scan(body, x, None, length=3)
        return jax.lax.psum(c, "data")

    f = shard_map(sharded, mesh=mesh, in_specs=P("data"), out_specs=P())
    assert rule_collective_in_loop(spmd_target(f, jnp.ones((8, 16))),
                                  th()) == []


def test_ring_ppermute_whitelisted_but_non_ring_fires():
    """The ring-corr block rotation keeps its in-loop exemption through the
    shared structure tag; any other permutation in the same position loses
    it."""
    mesh = make_mesh(1, 8)

    def make(perm):
        def sharded(x):
            def body(c, _):
                return jax.lax.ppermute(c, "seq", perm=perm), None
            c, _ = jax.lax.scan(body, x, None, length=3)
            return c
        return shard_map(sharded, mesh=mesh, in_specs=P(None, "seq"),
                         out_specs=P(None, "seq"))

    ring = spmd_target(make(ring_perm(8)), jnp.ones((4, 8)))
    fs = rule_collective_in_loop(ring, th())
    assert fs == []

    swap = [(i, i ^ 1) for i in range(8)]       # pairwise swap: not a ring
    broken = spmd_target(make(swap), jnp.ones((4, 8)))
    fs = rule_collective_in_loop(broken, th())
    assert len(fs) == 1 and fs[0].data["primitive"] == "ppermute"


def test_is_ring_perm_structure_tag():
    assert is_ring_perm(ring_perm(4))
    assert is_ring_perm(ring_perm(8))
    assert is_ring_perm([(k, (k + 3) % 8) for k in range(8)])  # stride ring
    assert not is_ring_perm([(k, k) for k in range(4)])        # identity
    assert not is_ring_perm([(0, 1), (1, 0), (2, 3), (3, 2)])  # swaps
    assert not is_ring_perm([(0, 1), (1, 2)])                  # partial
    assert not is_ring_perm([(0, 1)])                          # degenerate
    assert not is_ring_perm("nonsense")


# -------------------------------------------------- accidental-replication

def test_replicated_volume_fires_sharded_is_clean():
    """The hand-mis-sharded fixture: a correlation-shaped B*H*W*W einsum
    whose inputs are replicated materializes the full volume on every
    device; the same program with the batch sharded stays under the
    per-device threshold."""
    mesh = make_mesh(8, 1)

    def volume(a, b):
        v = jnp.einsum("bhwd,bhvd->bhwv", a, b,
                       preferred_element_type=jnp.float32)
        return v.sum()

    a = np.ones((8, 16, 64, 8), np.float32)
    threshold = th(replicated_bytes=1 << 20)    # 1 MiB

    with mesh:
        rep = jax.device_put(a, NamedSharding(mesh, P()))
        compiled_rep = jax.jit(volume).lower(rep, rep).compile()
        shd = jax.device_put(a, NamedSharding(mesh, P("data")))
        compiled_shd = jax.jit(volume).lower(shd, shd).compile()

    # full volume: 8*16*64*64 f32 = 2 MiB on EVERY device
    t = SpmdTarget(name="rep", cfg=RAFTStereoConfig(), closed_jaxpr=None,
                   compiled=compiled_rep)
    fs = rule_accidental_replication(t, threshold)
    assert fs and all(f.severity == "error" for f in fs)
    assert max(f.data["bytes"] for f in fs) >= 8 * 16 * 64 * 64 * 4

    # batch-sharded: 1/8th per device = 256 KiB, under the threshold
    t = SpmdTarget(name="shd", cfg=RAFTStereoConfig(), closed_jaxpr=None,
                   compiled=compiled_shd)
    assert rule_accidental_replication(t, threshold) == []


# -------------------------------------------------------- collective-dtype

def test_fp32_psum_over_upcast_bf16_warns():
    mesh = make_mesh(8, 1)

    def widened(x):
        return jax.lax.psum(x.astype(jnp.float32), "data")

    f = shard_map(widened, mesh=mesh, in_specs=P("data"), out_specs=P())
    t = spmd_target(f, jnp.ones((8, 2048), jnp.bfloat16))
    fs = rule_collective_dtype(t, th())
    assert len(fs) == 1 and fs[0].severity == "warning"
    assert fs[0].data["elems"] >= 2048

    def native(x):                               # bf16 psum: clean
        return jax.lax.psum(x, "data")

    f = shard_map(native, mesh=mesh, in_specs=P("data"), out_specs=P())
    assert rule_collective_dtype(
        spmd_target(f, jnp.ones((8, 2048), jnp.bfloat16)), th()) == []

    def small(x):                                # scalar glue: under floor
        return jax.lax.psum(x.astype(jnp.float32), "data")

    f = shard_map(small, mesh=mesh, in_specs=P("data"), out_specs=P())
    assert rule_collective_dtype(
        spmd_target(f, jnp.ones((8, 4), jnp.bfloat16)), th()) == []


# --------------------------------------------------------------- axis-leak

def test_promised_reduction_missing_fires():
    """The dropped-psum seed: a DP step whose gradient reduction vanished
    — every device would train on 1/8th of the batch and believe it."""
    mesh = make_mesh(8, 1)

    f = shard_map(lambda x: x * 2, mesh=mesh, in_specs=P("data"),
                  out_specs=P("data"))
    t = spmd_target(f, jnp.ones((8, 16)),
                    mesh_shape={"data": 8, "seq": 1},
                    reduce_axes=("data",))
    fs = rule_axis_leak(t, th())
    errors = [f for f in fs if f.severity == "error"]
    assert len(errors) == 1 and errors[0].data["axis"] == "data"

    def reduced(x):
        return jax.lax.psum(x, "data")

    f = shard_map(reduced, mesh=mesh, in_specs=P("data"), out_specs=P())
    t = spmd_target(f, jnp.ones((8, 16)),
                    mesh_shape={"data": 8, "seq": 1},
                    reduce_axes=("data",))
    assert [f for f in rule_axis_leak(t, th())
            if f.severity == "error"] == []


def test_unsharded_program_with_promise_fires():
    t = spmd_target(lambda x: x * 2, jnp.ones((8, 16)),
                    mesh_shape={"data": 8}, reduce_axes=("data",))
    fs = rule_axis_leak(t, th())
    assert len(fs) == 1 and fs[0].severity == "error"
    assert "no shard_map" in fs[0].message


def test_dead_axis_plumbing_warns():
    mesh = make_mesh(4, 2)

    f = shard_map(lambda x: x * 2, mesh=mesh, in_specs=P("data"),
                  out_specs=P("data"))
    fs = rule_axis_leak(spmd_target(f, jnp.ones((8, 16))), th())
    warns = [f for f in fs if f.severity == "warning"]
    assert len(warns) == 1 and warns[0].data["axis"] == "seq"


# ------------------------------------------------------ donation-under-mesh

def test_dropped_mesh_donation_fires():
    mesh = make_mesh(8, 1)

    def step(state, x):
        return jax.tree.map(lambda a: a + x.sum(), state)

    f = shard_map(step, mesh=mesh, in_specs=(P(), P("data")),
                  out_specs=P(), check_vma=False)
    state = {"p": jnp.zeros((256, 256))}
    x = jnp.ones((8, 16))
    with mesh:
        donated = jax.jit(f, donate_argnums=(0,)).lower(state, x).compile()
        dropped = jax.jit(f).lower(state, x).compile()

    ok = SpmdTarget(name="t", cfg=RAFTStereoConfig(), closed_jaxpr=None,
                    compiled=donated, donate_declared=True,
                    mesh_shape={"data": 8})
    assert rule_donation_under_mesh(ok, th()) == []

    broken = SpmdTarget(name="t", cfg=RAFTStereoConfig(), closed_jaxpr=None,
                        compiled=dropped, donate_declared=True,
                        mesh_shape={"data": 8})
    fs = rule_donation_under_mesh(broken, th())
    assert [f.severity for f in fs] == ["error"]
    assert "aliases 0 bytes" in fs[0].message

    undeclared = SpmdTarget(name="t", cfg=RAFTStereoConfig(),
                            closed_jaxpr=None, compiled=dropped)
    assert rule_donation_under_mesh(undeclared, th()) == []


# ---------------------------------------------------------- HLO walkers

def test_hlo_collective_profile_counts_and_loop_bucket():
    from raft_stereo_tpu.obs.xla import hlo_collective_profile
    mesh = make_mesh(8, 1)

    def body_psum(x):
        def body(c, _):
            return c + jax.lax.psum(c, "data"), None
        c, _ = jax.lax.scan(body, x, None, length=3)
        return c

    f = shard_map(body_psum, mesh=mesh, in_specs=P("data"),
                  out_specs=P("data"))
    with mesh:
        x = jax.device_put(np.ones((8, 16), np.float32),
                           NamedSharding(mesh, P("data")))
        compiled = jax.jit(f).lower(x).compile()
    prof = hlo_collective_profile(compiled.as_text())
    assert prof["by_kind"].get("all-reduce", 0) >= 1
    assert prof["in_loop"].get("all-reduce", 0) >= 1


# ------------------------------------------------------- fingerprint gate

def fixture_doc():
    """A hand-built two-target fingerprint doc (no lowering needed)."""
    return {
        "version": fp.FINGERPRINT_VERSION,
        "meta": {"jax": jax.__version__, "platform": "cpu",
                 "device_count": 8},
        "targets": {
            "train_step[dp]": {
                "convs": {"outside_scans": 172,
                          "scans": [{"length": 3, "convs_per_step": 15},
                                    {"length": 3, "convs_per_step": 36}],
                          "total": 223},
                "collectives": {"by_kind": {"psum": 9}, "in_loop": {}},
                "hlo_collectives": {"by_kind": {"all-reduce": 226},
                                    "in_loop": {}},
                "peak_bytes": 100_000_000,
                "donation": {"declared": True, "aliased": True,
                             "alias_bytes": 133424076},
            },
            "inference[ring]": {
                "convs": {"outside_scans": 73,
                          "scans": [{"length": 1, "convs_per_step": 13}],
                          "total": 86},
                "collectives": {"by_kind": {"ppermute": 9},
                                "in_loop": {"ppermute": 3}},
            },
        },
    }


def errs(findings):
    return [f for f in findings if f.severity == "error"]


def test_identical_fingerprint_is_clean():
    assert fp.diff_fingerprint(fixture_doc(), fixture_doc()) == []


def test_wgrad_reentering_backward_loop_fires():
    cur = fixture_doc()
    # last scan = the backward loop; +6 per-step convs = the wgrad set back
    cur["targets"]["train_step[dp]"]["convs"]["scans"][1][
        "convs_per_step"] = 42
    cur["targets"]["train_step[dp]"]["convs"]["outside_scans"] = 166
    fs = errs(fp.diff_fingerprint(fixture_doc(), cur))
    assert len(fs) == 2
    assert any("re-entered the backward" in f.message for f in fs)


def test_new_collective_and_loop_entry_fire():
    cur = fixture_doc()
    tgt = cur["targets"]["train_step[dp]"]["collectives"]
    tgt["by_kind"]["all_gather"] = 2             # a kind the contract
    fs = errs(fp.diff_fingerprint(fixture_doc(), cur))  # never named
    assert len(fs) == 1 and "NEW collective" in fs[0].message

    cur = fixture_doc()
    tgt = cur["targets"]["train_step[dp]"]["collectives"]
    tgt["in_loop"]["psum"] = 1                   # psum moved into the loop
    fs = errs(fp.diff_fingerprint(fixture_doc(), cur))
    assert any("MOVED INTO a loop body" in f.message for f in fs)


def test_peak_bytes_gate_and_tolerance():
    cur = fixture_doc()
    cur["targets"]["train_step[dp]"]["peak_bytes"] = 108_000_000  # +8%
    assert errs(fp.diff_fingerprint(fixture_doc(), cur)) == []
    cur["targets"]["train_step[dp]"]["peak_bytes"] = 115_000_000  # +15%
    fs = errs(fp.diff_fingerprint(fixture_doc(), cur))
    assert len(fs) == 1 and "peak bytes jumped" in fs[0].message
    fs = fp.diff_fingerprint(fixture_doc(), cur, peak_tolerance=0.20)
    assert errs(fs) == []


def test_donation_drop_and_missing_target():
    cur = fixture_doc()
    cur["targets"]["train_step[dp]"]["donation"]["aliased"] = False
    fs = errs(fp.diff_fingerprint(fixture_doc(), cur))
    assert len(fs) == 1 and "donation pairing changed" in fs[0].message

    cur = fixture_doc()
    del cur["targets"]["inference[ring]"]
    fs = errs(fp.diff_fingerprint(fixture_doc(), cur))
    assert len(fs) == 1 and "missing from the current build" in fs[0].message
    # partial run (engine deselected / compile skipped): not drift
    assert errs(fp.diff_fingerprint(fixture_doc(), cur, partial=True)) == []


def test_fingerprint_round_trip_and_version_check(tmp_path):
    path = str(tmp_path / "fp.json")
    fp.write_fingerprint(path, fixture_doc())
    assert fp.load_fingerprint(path) == fixture_doc()
    bad = fixture_doc()
    bad["version"] = 99
    fp.write_fingerprint(path, bad)
    with pytest.raises(ValueError):
        fp.load_fingerprint(path)


def test_target_fingerprint_jaxpr_only():
    mesh = make_mesh(8, 1)

    def sharded(x):
        def body(c, _):
            return c * 2, None
        c, _ = jax.lax.scan(body, x, None, length=3)
        return jax.lax.psum(c, "data")

    f = shard_map(sharded, mesh=mesh, in_specs=P("data"), out_specs=P())
    t = spmd_target(f, jnp.ones((8, 16)))
    rec = fp.target_fingerprint(t)
    assert rec["collectives"]["by_kind"] == {"psum2": 1}  # shard_map spelling
    assert rec["collectives"]["in_loop"] == {}
    assert "peak_bytes" not in rec              # uncompiled: jaxpr fields only


# -------------------------------------- the CLI gate flips on injected drift

def test_injected_regression_flips_cli_gate(tmp_path, capsys):
    """Acceptance criterion: a structural regression (psum moved into the
    scan body) against the CHECKED-IN fingerprint baseline makes
    ``cli lint --fingerprint`` exit 1; the unmodified doc is green."""
    from raft_stereo_tpu.analysis.runner import main as lint_main

    baseline_path = os.path.join(REPO, fp.DEFAULT_FINGERPRINT)
    if not os.path.exists(baseline_path):
        pytest.skip("no checked-in fingerprint baseline")
    clean = fp.load_fingerprint(baseline_path)
    empty_baseline = str(tmp_path / ".graftlint.json")

    current = str(tmp_path / "current.json")
    fp.write_fingerprint(current, clean)
    rc = lint_main(["--fingerprint-current", current,
                    "--fingerprint-baseline", baseline_path,
                    "--baseline", empty_baseline])
    assert rc == 0, capsys.readouterr().out

    doctored = json.loads(json.dumps(clean))
    tgt = doctored["targets"]["train_step[dp]"]["collectives"]
    tgt["in_loop"]["psum"] = 1
    fp.write_fingerprint(current, doctored)
    rc = lint_main(["--fingerprint-current", current,
                    "--fingerprint-baseline", baseline_path,
                    "--baseline", empty_baseline])
    out = capsys.readouterr().out
    assert rc == 1
    assert "MOVED INTO a loop body" in out


def test_missing_baseline_is_an_error(tmp_path, capsys):
    from raft_stereo_tpu.analysis.runner import main as lint_main

    current = str(tmp_path / "current.json")
    fp.write_fingerprint(current, fixture_doc())
    rc = lint_main(["--fingerprint-current", current,
                    "--fingerprint-baseline", str(tmp_path / "absent.json"),
                    "--baseline", str(tmp_path / ".graftlint.json")])
    capsys.readouterr()
    assert rc == 1


# --------------------------------------------- rule_version staleness (#2)

def test_rule_version_mismatch_flags_suppression_stale():
    finding = Finding("cli-drift", "error", "cli.py::f", "drifted")
    entries = [{"rule": "cli-drift", "location": "cli.py::f",
                "reason": "known", "rule_version": 1}]
    # same version: suppresses
    applied, stale = apply_baseline([finding], entries,
                                    rule_versions={"cli-drift": 1})
    assert applied[0].suppressed and stale == []
    # rule bumped to v2: entry goes stale and NO LONGER matches
    finding = Finding("cli-drift", "error", "cli.py::f", "drifted")
    applied, stale = apply_baseline([finding], entries,
                                    rule_versions={"cli-drift": 2})
    assert not applied[0].suppressed
    assert len(stale) == 1 and "rule_version 1" in stale[0]["stale_reason"]
    # renamed/retired rule: stale with its own reason
    entries = [{"rule": "old-rule", "location": "x", "reason": "r"}]
    applied, stale = apply_baseline([], entries,
                                    rule_versions={"cli-drift": 2})
    assert len(stale) == 1 and "renamed or retired" in stale[0]["stale_reason"]
    # un-versioned legacy entry against a known rule still matches
    finding = Finding("cli-drift", "error", "cli.py::f", "drifted")
    entries = [{"rule": "cli-drift", "location": "cli.py::f", "reason": "r"}]
    applied, stale = apply_baseline([finding], entries,
                                    rule_versions={"cli-drift": 2})
    assert applied[0].suppressed and stale == []


def test_update_baseline_records_rule_versions(tmp_path):
    from raft_stereo_tpu.analysis.findings import (baseline_from_findings,
                                                   load_baseline,
                                                   write_baseline)
    doc = baseline_from_findings(
        [Finding("cli-drift", "error", "cli.py::f", "m")],
        rule_versions={"cli-drift": 2})
    assert doc["suppressions"][0]["rule_version"] == 2
    path = str(tmp_path / "b.json")
    write_baseline(path, doc)
    assert load_baseline(path)[0]["rule_version"] == 2


# ------------------------------------------- entry-surface cli-drift (#1)

def test_entry_surface_drift_fires_on_seeded_fixture(tmp_path):
    from raft_stereo_tpu.analysis.ast_rules import check_entry_surface_drift

    pkg = tmp_path / "raft_stereo_tpu"
    pkg.mkdir()
    (pkg / "cli.py").write_text(
        "def build_eval_parser():\n"
        "    import argparse\n"
        "    p = argparse.ArgumentParser()\n"
        "    p.add_argument('--dataset')\n"
        "    p.add_argument('--orphan_flag')\n"
        "    return p\n")
    (tmp_path / "evaluate_stereo.py").write_text(
        "from raft_stereo_tpu.cli import build_eval_parser\n"
        "args = build_eval_parser().parse_args()\n"
        "print(args.dataset)\n")
    (tmp_path / "bench.py").write_text(
        "from raft_stereo_tpu.config import RAFTStereoConfig\n"
        "def run():\n"
        "    return RAFTStereoConfig(bogus_field=3)\n")
    fs = check_entry_surface_drift(str(tmp_path))
    errors = {(f.data.get("dest") or f.data.get("keyword")) for f in fs}
    assert errors == {"orphan_flag", "bogus_field"}
    assert all(f.rule == "cli-drift" for f in fs)


def test_entry_surfaces_clean_on_head():
    from raft_stereo_tpu.analysis.ast_rules import check_entry_surface_drift

    fs = check_entry_surface_drift(REPO)
    assert [f for f in fs if f.severity == "error"] == []


# ----------------------------------------------------------- clean tree

@pytest.mark.slow  # 3 full-model traces (~17 s) — the non-slow tier's
# budget is already spent on test_training's compile walls; the same
# clean-tree guarantee runs every round in rehearse_round's
# lint/fingerprint legs (full compile path, green runs in
# runs/rehearsal.log)
def test_head_passes_spmd_rules_jaxpr_only():
    """The canonical sharded programs (shard_map DP step, the batched
    custom-VJP twin, the dp x sp ring inference) carry zero SPMD-rule
    violations at the jaxpr level. The compiled path (replication/mesh-
    donation rules + the full fingerprint) runs in rehearse_round's
    lint/fingerprint legs — green runs on record in runs/rehearsal.log."""
    from raft_stereo_tpu.analysis.spmd_rules import (build_spmd_targets,
                                                     run_spmd_rules)

    targets = build_spmd_targets(compile_programs=False)
    assert [t.name for t in targets] == [
        "train_step[dp]", "train_step[dp,batched]", "inference[ring]"]
    fs = run_spmd_rules(targets=targets)
    assert [f for f in fs if f.severity == "error"] == [], \
        [f.to_dict() for f in fs]
    # the DP step's psum'd gradients and the ring's rotation are visible
    from raft_stereo_tpu.obs.xla import collective_profile
    dp = collective_profile(targets[0].closed_jaxpr)
    assert dp["by_kind"].get("psum", 0) > 0 and not dp["in_loop"]
    ring = collective_profile(targets[2].closed_jaxpr)
    assert ring["in_loop"].get("ppermute", 0) > 0   # whitelisted by shape
