"""graftlint (raft_stereo_tpu/analysis): every rule fires on a minimal
seeded violation, the suppression baseline round-trips, and HEAD passes
``cli lint`` with zero unsuppressed error-severity findings.

The graph-rule fixtures are tiny synthetic jaxprs (not the full model) so
each rule's trigger condition is explicit and the suite stays fast; the
model-scale path is covered by the clean-tree test (which lowers the real
canonical targets) and by tests/test_scan_grad.py asserting through the
shared ``wgrad-in-loop`` rule.
"""

import json
import os
import sys
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_stereo_tpu.analysis.ast_rules import (check_cli_config_drift,
                                                lint_source, run_ast_rules)
from raft_stereo_tpu.analysis.findings import (Finding, apply_baseline,
                                               baseline_from_findings, gate,
                                               load_baseline, make_report,
                                               severity_counts,
                                               write_baseline)
from raft_stereo_tpu.analysis.graph_rules import (DEFAULT_THRESHOLDS,
                                                  GraphTarget,
                                                  check_wgrad_hoisting,
                                                  rule_carry_growth,
                                                  rule_constant_bloat,
                                                  rule_donation,
                                                  rule_dtype_drift,
                                                  rule_host_sync,
                                                  rule_residual_dtype)
from raft_stereo_tpu.config import RAFTStereoConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def target_for(fn, *example_args, name="fixture", cfg=None, **kw):
    return GraphTarget(name=name, cfg=cfg or RAFTStereoConfig(),
                       closed_jaxpr=jax.make_jaxpr(fn)(*example_args), **kw)


def th(**overrides):
    return dict(DEFAULT_THRESHOLDS, **overrides)


# ------------------------------------------------------------- graph rules

def test_host_sync_fires_and_clean():
    def dirty(x):
        jax.debug.print("x {x}", x=x)
        y = jax.pure_callback(lambda a: np.asarray(a) * 2,
                              jax.ShapeDtypeStruct((2,), jnp.float32), x)
        return x + y

    fs = rule_host_sync(target_for(dirty, jnp.ones(2)), th())
    prims = {f.data["primitive"] for f in fs}
    assert {"debug_callback", "pure_callback"} <= prims
    assert all(f.severity == "error" for f in fs)

    fs = rule_host_sync(target_for(lambda x: x * 2, jnp.ones(2)), th())
    assert fs == []


def test_dtype_drift_roundtrip_fires():
    def dirty(x):
        return x.astype(jnp.bfloat16).astype(jnp.float32) * 2

    fs = rule_dtype_drift(target_for(dirty, jnp.ones((4, 4))), th())
    assert [f.severity for f in fs] == ["warning"]
    assert "round-trip" in fs[0].message

    # narrowing without the widen-back is NOT drift
    fs = rule_dtype_drift(
        target_for(lambda x: x.astype(jnp.bfloat16) * 2, jnp.ones((4, 4))),
        th())
    assert fs == []


def test_dtype_drift_f64_fires():
    from jax.experimental import enable_x64

    def dirty(x):
        return x.astype(jnp.float64) * 2

    with enable_x64():
        t = target_for(dirty, jnp.ones(2, jnp.float32))
    fs = rule_dtype_drift(t, th())
    assert any(f.severity == "error" and "float64" in f.message for f in fs)


def test_carry_growth_fires_on_threshold():
    def scanned(x):
        def body(c, _):
            return c * 2, c.sum()
        return jax.lax.scan(body, x, None, length=3)

    t = target_for(scanned, jnp.ones((64, 64)))     # 16 KiB carry
    assert rule_carry_growth(t, th()) == []          # default 1 GiB: clean
    fs = rule_carry_growth(t, th(carry_bytes=1024))
    assert len(fs) == 1 and fs[0].severity == "warning"
    assert fs[0].data["carry_bytes"] == 64 * 64 * 4
    assert "scan[0]" in fs[0].location


def test_constant_bloat_fires_on_threshold():
    big = jnp.asarray(np.ones((128, 128), np.float32))

    def closure(x):
        return x + big.sum()

    t = target_for(closure, jnp.ones(()))
    assert rule_constant_bloat(t, th()) == []        # 64 KiB < 2 MiB
    fs = rule_constant_bloat(t, th(const_bytes=1024))
    assert fs and fs[0].severity == "warning"
    assert fs[0].data["const_bytes"] == 128 * 128 * 4


def test_donation_rules():
    def step(state, x):
        return jax.tree.map(lambda a: a + x.sum(), state), x.mean()

    state = {"p": jnp.zeros((128, 128))}
    x = jnp.ones((8, 8))
    donated = jax.jit(step, donate_argnums=(0,)).lower(state, x).compile()
    undonated = jax.jit(step).lower(state, x).compile()

    ok = GraphTarget(name="t", cfg=RAFTStereoConfig(), closed_jaxpr=None,
                     compiled=donated, donate_declared=True)
    assert rule_donation(ok, th()) == []

    # declared donation that the executable dropped -> error
    broken = GraphTarget(name="t", cfg=RAFTStereoConfig(), closed_jaxpr=None,
                         compiled=undonated, donate_declared=True)
    fs = rule_donation(broken, th())
    assert [f.severity for f in fs] == ["error"]
    assert "aliases 0 bytes" in fs[0].message

    # large undonated arguments -> info flag
    quiet = GraphTarget(name="t", cfg=RAFTStereoConfig(), closed_jaxpr=None,
                        compiled=undonated, donate_declared=False)
    fs = rule_donation(quiet, th(nondonated_arg_bytes=1024))
    assert [f.severity for f in fs] == ["info"]


def test_residual_dtype_conformance():
    cfg = RAFTStereoConfig(batched_scan_wgrad=True,
                           residual_dtype="bfloat16")

    def fp32_stacks(x):
        def body(c, _):
            return c * 2, c              # f32 ys only
        return jax.lax.scan(body, x, None, length=3)

    fs = rule_residual_dtype(target_for(fp32_stacks, jnp.ones((16, 128)),
                                        cfg=cfg), th())
    assert any(f.severity == "error" and "dead" in f.message for f in fs)

    def bf16_stacks(x):
        def fwd(c, _):
            return c * 2, c.astype(jnp.bfloat16)
        c, saves = jax.lax.scan(fwd, x, None, length=3)

        def bwd(c2, s):
            return c2 + s.astype(jnp.float32), s  # bf16 ys in scan 2
        return jax.lax.scan(bwd, c, saves)

    fs = rule_residual_dtype(target_for(bf16_stacks, jnp.ones((16, 128)),
                                        cfg=cfg), th())
    assert fs == []

    # rule only applies on the custom path with a configured dtype
    assert rule_residual_dtype(
        target_for(fp32_stacks, jnp.ones((16, 128))), th()) == []


def test_wgrad_rule_fires_on_unhoisted_profile():
    hoisted = {"outside_scans": 30,
               "scans": [{"length": 3, "convs_per_step": 40, "convs": 40},
                         {"length": 3, "convs_per_step": 20, "convs": 20}]}
    unhoisted = {"outside_scans": 24,
                 "scans": [{"length": 3, "convs_per_step": 40, "convs": 40},
                           {"length": 3, "convs_per_step": 26,
                            "convs": 26}]}
    assert check_wgrad_hoisting(unhoisted, hoisted) == []
    fs = check_wgrad_hoisting(unhoisted, unhoisted)
    assert fs and all(f.severity == "error" for f in fs)
    assert {"wgrad-in-loop"} == {f.rule for f in fs}
    # degenerate profile (no scans at all) is itself a violation
    assert check_wgrad_hoisting({"outside_scans": 0, "scans": []}, hoisted)


# --------------------------------------------------------------- AST rules

def lint_src(src):
    return lint_source(textwrap.dedent(src), "pkg/mod.py")


def test_tracer_unsafe_fires_in_jit_reachable():
    fs = lint_src("""
        import jax
        import numpy as np

        def step(x):
            bad = float(x)
            worse = x.item()
            worst = np.asarray(x)
            return bad + worse

        jitted = jax.jit(step)
    """)
    calls = sorted(f.data["call"] for f in fs)
    assert calls == ["float", "item", "np.asarray"]
    assert all(f.rule == "tracer-unsafe" and f.severity == "error"
               for f in fs)
    assert all(f.location == "pkg/mod.py::step" for f in fs)


def test_tracer_unsafe_ignores_host_side_and_static():
    fs = lint_src("""
        import jax

        def host_only(x):
            return float(x)            # never traced -> fine

        def step(x, cfg):
            b, h, w, c = x.shape
            n = float(h * w)           # shape-derived -> static
            k = int(len(x))            # len -> static
            mode = bool(cfg.fused)     # config attr -> static
            return x * n * k * mode

        jitted = jax.jit(step)
    """)
    assert fs == []


def test_nested_and_module_method_reachability():
    fs = lint_src("""
        import jax
        import flax.linen as nn

        class Net(nn.Module):
            def __call__(self, x):
                return float(x)        # module methods are traced

        def outer(x):
            def inner(y):
                return float(y)        # nested in jit-reachable
            return inner(x)

        jax.grad(outer)
    """)
    locs = sorted(f.location for f in fs)
    assert locs == ["pkg/mod.py::Net.__call__", "pkg/mod.py::outer.inner"]


def test_wall_clock_fires():
    fs = lint_src("""
        import time
        import jax

        def step(x):
            t0 = time.perf_counter()
            return x * time.time() + t0

        jax.jit(step)
    """)
    assert len(fs) == 2
    assert all(f.rule == "wall-clock" and f.severity == "error" for f in fs)


def test_import_time_jnp_fires():
    fs = lint_src("""
        import jax.numpy as jnp

        TABLE = jnp.arange(16)         # device work at import

        def fine():
            return jnp.arange(16)      # inside a function: fine
    """)
    assert [f.rule for f in fs] == ["import-time-jnp"]
    assert fs[0].severity == "error"


def test_cli_drift_fires_on_seeded_fixture(tmp_path):
    fixture = tmp_path / "cli.py"
    fixture.write_text(textwrap.dedent("""
        from raft_stereo_tpu.config import RAFTStereoConfig

        def add_model_args(parser):
            parser.add_argument("--corr_levels", type=int)
            parser.add_argument("--dropped_flag", type=int)

        def model_config(args):
            return RAFTStereoConfig(corr_levels=args.corr_levels,
                                    bogus_field=1)
    """))
    fs = check_cli_config_drift(str(fixture), "cli.py")
    errors = {(f.data.get("keyword") or f.data.get("dest"))
              for f in fs if f.severity == "error"}
    assert errors == {"bogus_field", "dropped_flag"}


def test_cli_drift_clean_on_real_cli():
    fs = check_cli_config_drift(
        os.path.join(REPO, "raft_stereo_tpu", "cli.py"),
        "raft_stereo_tpu/cli.py")
    assert [f for f in fs if f.severity == "error"] == []


# ------------------------------------------------- baseline + report + gate

def test_baseline_round_trip(tmp_path):
    findings = [
        Finding("tracer-unsafe", "error", "pkg/a.py::f", "bad"),
        Finding("host-sync", "error", "train_step/x", "worse"),
    ]
    path = str(tmp_path / ".graftlint.json")
    write_baseline(path, baseline_from_findings(findings))
    loaded = load_baseline(path)
    assert {(e["rule"], e["location"]) for e in loaded} \
        == {f.key for f in findings}

    fresh = [Finding("tracer-unsafe", "error", "pkg/a.py::f", "bad"),
             Finding("dtype-drift", "warning", "pkg/b.py::g", "meh")]
    applied, stale = apply_baseline(fresh, loaded)
    assert applied[0].suppressed and not applied[1].suppressed
    # the host-sync entry matched nothing -> reported stale, not fatal
    assert [e["rule"] for e in stale] == ["host-sync"]
    assert gate(applied) == 0          # the only error is suppressed
    assert gate(fresh := [Finding("x", "error", "l", "m")]) == 1
    assert severity_counts(applied)["error"] == 1
    report = make_report(applied, ["tracer-unsafe"], ["ast"], stale)
    assert report["unsuppressed"]["error"] == 0
    assert report["suppressed_total"] == 1


def test_runner_gates_on_seeded_violation(tmp_path, capsys):
    from raft_stereo_tpu.analysis.runner import main as lint_main

    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "bad.py").write_text(textwrap.dedent("""
        import jax

        def step(x):
            return float(x)

        jax.jit(step)
    """))
    baseline = str(tmp_path / ".graftlint.json")
    rc = lint_main(["--ast", "--package-root", str(pkg),
                    "--baseline", baseline])
    assert rc == 1

    # --update-baseline accepts the violation; the rerun is green and the
    # lint event + JSON report record the suppression
    assert lint_main(["--ast", "--package-root", str(pkg),
                      "--baseline", baseline, "--update-baseline"]) == 0
    run_dir = str(tmp_path / "run")
    report_path = str(tmp_path / "report.json")
    rc = lint_main(["--ast", "--package-root", str(pkg),
                    "--baseline", baseline, "--run_dir", run_dir,
                    "--json", report_path])
    assert rc == 0
    capsys.readouterr()

    report = json.load(open(report_path))
    assert report["suppressed_total"] == 1
    assert report["unsuppressed"]["error"] == 0

    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import check_events
    assert check_events.check(run_dir) == []
    events = [json.loads(l) for l in
              open(os.path.join(run_dir, "events.jsonl"))]
    lint_events = [e for e in events if e["event"] == "lint"]
    assert lint_events and lint_events[0]["schema"] >= 4
    assert lint_events[0]["errors"] == 0


# ----------------------------------------------------------- clean tree

def test_head_passes_cli_lint(capsys):
    """The acceptance criterion: `cli lint` (graph + ast engines over the
    real package — canonical graph targets lowered at the tiny shape) runs
    green on HEAD: zero unsuppressed error-severity findings.

    ``--no-compile`` keeps the tier-1 budget: it skips only the donated
    AOT compile of the train step (the donation rule itself is pinned
    above on compiled fixtures, and scripts/rehearse_round.py's `lint`
    leg runs the full compile path every round — green run on record in
    runs/rehearsal.log). ``--graph --ast`` keeps the SPMD engine out for
    the same reason — conftest's 8 virtual devices would let it trace the
    three full-model sharded programs here (~12 s); that clean-tree
    guarantee lives in test_spmd_lint's slow-marked
    test_head_passes_spmd_rules_jaxpr_only and in the rehearsal legs."""
    from raft_stereo_tpu.analysis.runner import main as lint_main

    rc = lint_main(["--no-compile", "--graph", "--ast"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "0 error(s)" in out
