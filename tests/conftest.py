"""Test harness: force an 8-device virtual CPU platform before JAX import.

Multi-chip sharding is validated on a host-platform device mesh
(``--xla_force_host_platform_device_count=8``) because tests run without TPU
hardware; the same code paths compile for a real TPU slice.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Ride the repo's persistent compile cache (bench.py's .jax_cache): the
# suite compiles the same canonical programs every run, and on a 1-core
# host the cold XLA-CPU compiles alone overrun the tier-1 time budget.
# Keys are HLO hashes, so a stale entry can't mask a real change; tests
# that assert on `compile` telemetry use hand-written events or the
# cache-independent first-dispatch-latency source.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".jax_cache"))

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

# The environment's TPU plugin (axon) force-registers itself via sitecustomize
# and rewrites jax_platforms after import; pin the test session to the 8-device
# virtual CPU platform regardless.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

REFERENCE_DIR = "/root/reference"


_SESSION_EXIT_STATUS = [None]


def pytest_sessionfinish(session, exitstatus):
    _SESSION_EXIT_STATUS[0] = int(exitstatus)


@pytest.hookimpl(trylast=True)
def pytest_unconfigure(config):
    """Exit with the session's status via os._exit, skipping interpreter
    teardown: after a full-suite run the exit-time cleanup of the imported
    accelerator plugin / torch stack has been observed to SIGSEGV (rc=139)
    AFTER every test passed, which turns a green suite into a red return
    code for any caller that checks rc. By unconfigure time the terminal
    summary is already printed; nothing in this suite relies on atexit."""
    if _SESSION_EXIT_STATUS[0] is None:
        return
    if "coverage" in sys.modules:
        # coverage.py saves its data file via an atexit handler that
        # os._exit would skip; under coverage, risk the teardown instead.
        return
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(_SESSION_EXIT_STATUS[0])


@pytest.fixture(scope="module", autouse=True)
def _clear_jax_caches_between_modules():
    """Free compiled executables after each test module.

    A full-suite run accumulates hundreds of XLA-CPU executables in one
    process; at that load the LLVM JIT has been observed to SIGSEGV inside
    ``backend_compile_and_load`` on a late heavy compile (the same test
    passes in a 4-file run and in isolation). Dropping the jit caches per
    module bounds the accumulation; tests recompile what they reuse."""
    yield
    jax.clear_caches()


def reference_available() -> bool:
    return os.path.isdir(os.path.join(REFERENCE_DIR, "core"))


requires_reference = pytest.mark.skipif(
    not reference_available(),
    reason="PyTorch reference checkout not available",
)


@pytest.fixture(scope="session")
def torch_reference():
    """Import the PyTorch reference as an oracle (numerical parity tests only)."""
    if not reference_available():
        pytest.skip("reference not available")
    if REFERENCE_DIR not in sys.path:
        sys.path.insert(0, REFERENCE_DIR)
    import core.corr  # noqa: F401
    import core.raft_stereo  # noqa: F401
    import core  # noqa: F401
    return core
