"""Device-side observatory: compiled-artifact introspection (obs/xla.py),
the schema-v2 xla events, the run-regression gate (obs/compare.py), the
buffer-assignment parser, the serial-floor decomposition helpers, and the
parity null-floor gate."""

import json
import sys
from pathlib import Path

import pytest

from raft_stereo_tpu.obs import (SCHEMA_VERSION, Telemetry, append_json_log,
                                 make_record, read_events, validate_events,
                                 validate_record)
from raft_stereo_tpu.obs.compare import compare_runs
from raft_stereo_tpu.obs.compare import main as compare_main
from raft_stereo_tpu.obs.xla import (compact_xla_summary, cost_analysis_dict,
                                     introspect_compiled,
                                     memory_analysis_dict,
                                     parse_buffer_assignment,
                                     summarize_buffer_assignment,
                                     volume_class_summary)

REPO = Path(__file__).resolve().parents[1]


@pytest.fixture(scope="module")
def tiny_compiled():
    import jax
    import jax.numpy as jnp

    def f(x, y):
        return jnp.dot(x, y) + x.sum()

    x = jnp.ones((64, 64))
    return jax.jit(f).lower(x, x).compile()


# --- extraction -------------------------------------------------------------

def test_memory_analysis_extraction(tiny_compiled):
    mem = memory_analysis_dict(tiny_compiled)
    assert mem is not None
    # two (64,64) fp32 args in, one out
    assert mem["argument_bytes"] == 2 * 64 * 64 * 4
    assert mem["output_bytes"] == 64 * 64 * 4
    assert mem["temp_bytes"] > 0
    assert mem["peak_bytes"] == (mem["argument_bytes"] + mem["output_bytes"]
                                 + mem["temp_bytes"]
                                 + mem.get("generated_code_bytes", 0)
                                 - mem.get("alias_bytes", 0))


def test_cost_analysis_extraction(tiny_compiled):
    cost = cost_analysis_dict(tiny_compiled)
    assert cost is not None
    # 64x64x64 matmul alone is 2*64^3 = 524288 flops
    assert cost["flops"] >= 2 * 64 ** 3
    assert cost["bytes_accessed"] > 0
    assert cost["flops_per_byte"] == pytest.approx(
        cost["flops"] / cost["bytes_accessed"], rel=1e-3)


def test_introspection_never_raises_on_junk():
    class Broken:
        def memory_analysis(self):
            raise RuntimeError("backend moved")

        def cost_analysis(self):
            raise RuntimeError("backend moved")

    assert memory_analysis_dict(Broken()) is None
    assert cost_analysis_dict(Broken()) is None
    assert introspect_compiled(Broken()) == {"memory": None, "cost": None}
    assert compact_xla_summary({"memory": None, "cost": None}) is None


# --- event emission + schema ------------------------------------------------

def test_introspect_emits_schema_clean_events(tmp_path, tiny_compiled):
    run = tmp_path / "run"
    tel = Telemetry(str(run))
    tel.run_start()
    analysis = introspect_compiled(tiny_compiled, tel, source="unit",
                                   extra={"batch": 3})
    tel.emit("run_end", steps=0, ok=True)
    tel.close()
    assert analysis["memory"] is not None and analysis["cost"] is not None

    events = read_events(str(run / "events.jsonl"))
    assert validate_events(events) == []
    mem = next(e for e in events if e["event"] == "xla_memory")
    cost = next(e for e in events if e["event"] == "xla_cost")
    assert mem["source"] == "unit" and mem["batch"] == 3
    assert mem["peak_bytes"] == analysis["memory"]["peak_bytes"]
    assert cost["flops"] == analysis["cost"]["flops"]

    # the scripts/ lint accepts the new events
    sys.path.insert(0, str(REPO / "scripts"))
    import check_events
    assert check_events.main([str(run)]) == 0


def test_schema_v1_back_compat():
    # a v1 record of a v1 event still lints clean after the v2 bump ...
    v1 = make_record("step", step=1, data_wait_s=0.0, dispatch_s=0.1,
                     fetch_s=0.0)
    v1["schema"] = 1
    assert validate_record(v1) == []
    # ... but a v2-only event may not claim v1, and unknown versions fail
    bad = make_record("xla_memory", source="x", peak_bytes=1)
    bad["schema"] = 1
    assert any("introduced in schema" in e for e in validate_record(bad))
    future = dict(v1, schema=SCHEMA_VERSION + 1)
    assert validate_record(future)
    # current-version xla events with required fields are clean
    assert validate_record(
        make_record("xla_memory", source="x", peak_bytes=1)) == []
    assert validate_record(make_record("xla_cost", source="x",
                                       flops=1.0)) == []
    assert validate_record(make_record("xla_cost", source="x"))  # no flops


# --- summarizer -------------------------------------------------------------

def test_summarizer_reports_headroom_and_flops_per_byte(tmp_path):
    from raft_stereo_tpu.obs import format_summary, summarize_run
    run = tmp_path / "run"
    path = str(run / "events.jsonl")
    append_json_log(path, make_record("run_start", t=0.0, run="x"),
                    stream=None)
    gib = 1024 ** 3
    append_json_log(path, make_record(
        "xla_memory", t=1.0, source="bench_b8", peak_bytes=12 * gib,
        temp_bytes=9 * gib, argument_bytes=2 * gib,
        capacity_bytes=16 * gib, headroom_bytes=4 * gib), stream=None)
    append_json_log(path, make_record(
        "xla_cost", t=1.0, source="bench_b8", flops=3.2e12,
        bytes_accessed=4.0e11, flops_per_byte=8.0), stream=None)
    report = summarize_run(str(run))
    xl = report["events"]["xla"]
    assert xl["peak_bytes"] == 12 * gib
    assert xl["headroom_bytes"] == 4 * gib
    assert xl["flops_per_byte"] == 8.0
    text = format_summary(report)
    assert "headroom 4.00 GiB" in text
    assert "8.0 flops/byte" in text
    assert "peak 12.00 GiB of 16.0 GiB" in text


# --- buffer-assignment parsing ----------------------------------------------

_BA_TEXT = """\
BufferAssignment:
allocation 0: size 16384, parameter 0, shape |f32[64,64]| at ShapeIndex {}, output shape is |f32[64,64]|, maybe-live-out:
 value: <7 Arg_0.1 @0> (size=16384,offset=0): f32[64,64]{1,0}
 value: <13 broadcast_add_fusion @0> (size=16384,offset=0): f32[64,64]{1,0}
allocation 1: size 16384, parameter 1, shape |f32[64,64]| at ShapeIndex {}:
 value: <8 Arg_1.2 @0> (size=16384,offset=0): f32[64,64]{1,0}
allocation 2: size 4, constant:
 value: <10 constant.3 @0> (size=4,offset=0): f32[]
allocation 6: size 16452, preallocated-temp:
 value: <9 dot.4 @0> (size=16384,offset=0): f32[64,64]{1,0}
 value: <11 reduce-window @0> (size=16,offset=16384): f32[2,2]{1,0}
 value: <12 reduce.9 @0> (size=4,offset=16448): f32[]

Total bytes used: 49236 (48.1KiB)

Used values:
<0 Arg_0.6 @0>
 value: <999 should-not-be-parsed @0> (size=999,offset=0): f32[9]
"""


def test_parse_buffer_assignment_names_buffers():
    parsed = parse_buffer_assignment(_BA_TEXT)
    assert parsed["total_bytes"] == 49236
    assert [a["index"] for a in parsed["allocations"]] == [0, 1, 2, 6]
    kinds = {a["index"]: a["kind"] for a in parsed["allocations"]}
    assert kinds[0] == "parameter" and kinds[6] == "temp"
    assert parsed["allocations"][0]["maybe_live_out"] is True
    # the "Used values" tail is not parsed as allocations
    assert all(v["size"] != 999
               for a in parsed["allocations"] for v in a["values"])

    summary = summarize_buffer_assignment(_BA_TEXT, top=3)
    assert summary["temp_bytes"] == 16452
    dom = summary["dominant_temp"]
    assert dom["allocation"] == 6
    assert dom["top_values"][0]["instruction"] == "dot.4"
    assert dom["top_values"][0]["shape"].startswith("f32[64,64]")


_VOLUME_BA_TEXT = """\
BufferAssignment:
allocation 0: size 153600, preallocated-temp:
 value: <1 fusion.1 @0> (size=153600,offset=0): f32[2,24,40,40]{3,2,1,0}
 value: <2 reduce-window.2 @0> (size=76800,offset=0): f32[2,24,40,20]{3,2,1,0}
allocation 1: size 76800, preallocated-temp:
 value: <3 multiply_pad_fusion.4 @0> (size=76800,offset=0): f32[2,24,40,10]{3,2,1,0}
allocation 2: size 12800, preallocated-temp:
 value: <4 fused_block.5 @0> (size=12800,offset=0): f32[8,40,10]{2,1,0}

Total bytes used: 243200 (237.5KiB)
"""


def test_volume_class_names_quadratic_levels_only():
    # The class is the O(H*W^2) residency: the all-pairs volume and its
    # WIDE pooled descendants.  Two shapes that are NOT in the class share
    # dims with it: the (2r+2)-lane tap stacks an on-the-fly lookup builds
    # (trailing 10 collides with pool level 10 -> excluded by the width
    # floor) and bounded per-block slabs (lead rows < H1).
    got = volume_class_summary(_VOLUME_BA_TEXT, w1=40, h1=24)
    assert got["pool_widths"] == [40, 20]
    assert got["count"] == 2
    assert got["bytes"] == 153600 + 76800
    assert all("40,10" not in v["shape"] for v in got["largest"])
    # lowering the floor re-admits level 2 and catches the tap stack too:
    # exactly the collision the default floor exists to avoid.
    loose = volume_class_summary(_VOLUME_BA_TEXT, w1=40, h1=24, min_width=8)
    assert loose["count"] == 3


# --- the regression gate ----------------------------------------------------

def _write_run_events(run_dir, throughput=9.6, dispatch=0.8, peak=9e9,
                      compile_s=120.0):
    path = str(Path(run_dir) / "events.jsonl")
    append_json_log(path, make_record("run_start", t=0.0, run="r"),
                    stream=None)
    append_json_log(path, make_record("compile", t=1.0,
                                      duration_s=compile_s, source="aot"),
                    stream=None)
    append_json_log(path, make_record(
        "xla_memory", t=1.0, source="bench", peak_bytes=peak), stream=None)
    for i in range(6):
        append_json_log(path, make_record(
            "step", t=2.0 + i, step=i + 1, data_wait_s=0.0,
            dispatch_s=dispatch, fetch_s=0.01, batch_size=8), stream=None)
    append_json_log(path, make_record(
        "throughput", t=9.0, pairs_per_sec=throughput, steps=6),
        stream=None)
    append_json_log(path, make_record("run_end", t=9.5, steps=6, ok=True),
                    stream=None)


def test_compare_identical_runs_pass(tmp_path, capsys):
    a, b = tmp_path / "a", tmp_path / "b"
    _write_run_events(a)
    _write_run_events(b)
    assert compare_main([str(a), str(b)]) == 0
    out = capsys.readouterr().out
    assert "no metric moved past its threshold" in out


def test_compare_flags_throughput_regression(tmp_path, capsys):
    a, b = tmp_path / "a", tmp_path / "b"
    _write_run_events(a, throughput=9.64)
    _write_run_events(b, throughput=9.0)   # -6.6% > the 3% gate
    rc = compare_main([str(a), str(b), "--json",
                       str(tmp_path / "cmp.json")])
    assert rc == 1
    assert "throughput_pairs_per_sec" in capsys.readouterr().out
    report = json.loads((tmp_path / "cmp.json").read_text())
    assert report["regressions"] == ["throughput_pairs_per_sec"]
    # the r5 wobble (9.639 -> 9.577, -0.6%) stays inside the noise gate
    assert compare_runs(str(a), str(a))["ok"]
    _write_run_events(tmp_path / "c", throughput=9.577)
    _write_run_events(tmp_path / "d", throughput=9.639)
    assert compare_runs(str(tmp_path / "d"), str(tmp_path / "c"))["ok"]


def test_compare_flags_memory_and_compile_regressions(tmp_path):
    a = tmp_path / "a"
    _write_run_events(a, peak=9e9, compile_s=100.0)
    worse_mem = tmp_path / "m"
    _write_run_events(worse_mem, peak=11e9)          # +22% > 5%
    report = compare_runs(str(a), str(worse_mem))
    assert "peak_memory_bytes" in report["regressions"]
    worse_compile = tmp_path / "c"
    _write_run_events(worse_compile, compile_s=220.0)  # +120% > 50%
    report = compare_runs(str(a), str(worse_compile))
    assert "compile_total_s" in report["regressions"]
    # improvement in the good direction never regresses
    better = tmp_path / "g"
    _write_run_events(better, throughput=12.0, peak=5e9, compile_s=10.0)
    assert compare_runs(str(a), str(better))["ok"]


def test_compare_skips_one_sided_metrics_and_rejects_empty(tmp_path):
    a, b = tmp_path / "a", tmp_path / "b"
    _write_run_events(a)
    # candidate without throughput/memory events: those skip, phases compare
    path = str(b / "events.jsonl")
    append_json_log(path, make_record("run_start", t=0.0, run="r"),
                    stream=None)
    for i in range(3):
        append_json_log(path, make_record(
            "step", t=1.0 + i, step=i + 1, data_wait_s=0.0, dispatch_s=0.8,
            fetch_s=0.01), stream=None)
    report = compare_runs(str(a), str(b))
    assert report["ok"]
    assert "throughput_pairs_per_sec" in report["skipped"]
    assert "peak_memory_bytes" in report["skipped"]
    # no events at all on either side is an ERROR (exit 2), not a pass
    assert compare_main([str(a), str(tmp_path / "missing")]) == 2
    assert compare_main([str(tmp_path / "missing"), str(a)]) == 2


def test_bench_run_dir_rotation(tmp_path, monkeypatch):
    """The chain's telemetry rotation: current -> previous, so the compare
    gate always has last chain's log as its baseline."""
    import bench
    monkeypatch.delenv("BENCH_RUN_DIR", raising=False)
    monkeypatch.setenv("BENCH_RUN_ROOT", str(tmp_path))
    current = tmp_path / "current"
    # first chain: nothing to rotate, env points children at current
    assert bench._rotate_bench_run_dir() == str(current)
    current.mkdir(parents=True)
    (current / "events.jsonl").write_text('{"a": 1}\n')
    # second chain: the prior log becomes the baseline
    monkeypatch.delenv("BENCH_RUN_DIR", raising=False)
    assert bench._rotate_bench_run_dir() == str(current)
    assert (tmp_path / "previous" / "events.jsonl").exists()
    assert not current.exists()
    # an externally-set BENCH_RUN_DIR is respected untouched
    monkeypatch.setenv("BENCH_RUN_DIR", "/elsewhere")
    assert bench._rotate_bench_run_dir() == "/elsewhere"


def test_rehearsal_compare_leg(tmp_path):
    sys.path.insert(0, str(REPO / "scripts"))
    import rehearse_round
    a, b = tmp_path / "prev", tmp_path / "cur"
    _write_run_events(a)
    _write_run_events(b)
    rec = rehearse_round.compare_leg(str(a), str(b))
    assert rec["ok"] and not rec.get("skipped")
    _write_run_events(tmp_path / "bad", throughput=5.0)
    rec = rehearse_round.compare_leg(str(a), str(tmp_path / "bad"))
    assert not rec["ok"]
    # missing baseline skips green (a first round has nothing to diff)
    rec = rehearse_round.compare_leg(str(tmp_path / "nope"), str(b))
    assert rec["ok"] and rec["skipped"]


# --- serial-floor decomposition helpers -------------------------------------

def test_decompose_serial_floor_recovers_linear_model():
    from raft_stereo_tpu.utils.profiling import (decompose_serial_floor,
                                                 fit_linear)
    # ground truth: fixed 0.45 s, 0.02 s/iter rolled, 0.015 s/iter unrolled
    rolled = {i: 0.45 + 0.02 * i for i in (2, 4, 8, 16)}
    unrolled = {i: 0.44 + 0.015 * i for i in (2, 4, 8)}
    d = decompose_serial_floor(rolled, unrolled)
    assert d["fixed_s"] == pytest.approx(0.45, abs=1e-6)
    assert d["per_iter_s"] == pytest.approx(0.02, abs=1e-6)
    assert d["per_iter_compute_s"] == pytest.approx(0.015, abs=1e-6)
    assert d["per_iter_loop_overhead_s"] == pytest.approx(0.005, abs=1e-6)
    with pytest.raises(ValueError):
        fit_linear([3.0], [1.0])


def test_model_iter_metrics_aux_outputs():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from raft_stereo_tpu.config import RAFTStereoConfig
    from raft_stereo_tpu.models import init_model

    cfg = RAFTStereoConfig(hidden_dims=(32, 32, 32))
    model, variables = init_model(jax.random.PRNGKey(0), cfg, (1, 64, 96, 3))
    x = jnp.linspace(0, 255, 1 * 64 * 96 * 3).reshape(1, 64, 96, 3)
    lo, up = model.apply(variables, x, x, iters=3, test_mode=True)
    lo2, up2, norms = model.apply(variables, x, x, iters=3, test_mode=True,
                                  iter_metrics=True)
    assert norms.shape == (3,)
    assert np.all(np.isfinite(np.asarray(norms)))
    # the aux output does not perturb the prediction
    assert np.allclose(np.asarray(up), np.asarray(up2))
    # train mode has no inference scan to instrument — loud, not silent
    with pytest.raises(ValueError, match="test_mode"):
        model.apply(variables, x, x, iters=2, iter_metrics=True)


# --- parity null-floor gate -------------------------------------------------

def test_parity_floor_gate_rules():
    sys.path.insert(0, str(REPO / "scripts"))
    from parity_dynamics import floor_gate

    null = {"last_window_loss_rel": 0.0335,
            "final_epe": {"rel_dev": 0.0801}}
    # the r5 measured values: 1.3% loss / 7.65% EPE vs 3.35% / 8.01% floor
    g = floor_gate(0.01296, 0.0765, null)
    assert g["pass"] and g["rule"] == "null_floor"
    assert g["checks"]["loss"]["ok"] and g["checks"]["epe"]["ok"]
    # either axis exceeding its floor fails
    assert not floor_gate(0.05, 0.0765, null)["pass"]
    assert not floor_gate(0.01296, 0.09, null)["pass"]
    # no null run -> fixed-tolerance fallback on the loss axis
    g = floor_gate(0.019, None, None, tolerance=0.02)
    assert g["pass"] and g["rule"] == "tolerance"
    assert not floor_gate(0.021, None, None, tolerance=0.02)["pass"]
