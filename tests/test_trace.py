"""Span tracing (obs/trace.py) and its consumers: the flight recorder
(obs/telemetry.py), ``cli timeline`` (obs/timeline.py) and ``cli doctor``
(obs/doctor.py).

What is pinned here, per the r13 acceptance bar:

* span nesting, cross-thread propagation and the retroactive ``record``
  API produce referentially-intact v7 ``span`` records that tile their
  root (coverage == 1.0);
* the ring/flush machinery batches writes and never drops a span on
  close; the ring is bounded;
* the flight recorder dumps the event + span rings on injected stall,
  anomaly and crash, as ``flightrec-*.jsonl`` side files plus a
  ``flightrec`` record on the bus — rate-limited per reason;
* the timeline export is well-formed Chrome trace JSON, and a device
  capture merges onto the host clock anchored at the earliest dispatch
  span;
* doctor names distinct bottlenecks (QUEUE_SATURATED / DATA_STARVED /
  COMPILE_STORM / STALLED) on seeded logs, with evidence lines;
* schema v7 is additive and linted (span referential integrity);
* tracing off is bitwise-free: two same-seed tiny trains, trace on vs
  off, emit identical step-loss streams, and the off run has no spans.
"""

import gzip
import json
import os
import threading
import time

import pytest

from raft_stereo_tpu.obs import (NULL_TRACER, Telemetry, Tracer, check_path,
                                 read_events, tracer_for, validate_record)
from raft_stereo_tpu.obs.events import append_json_log, make_record
from raft_stereo_tpu.obs.trace import SpanContext
from raft_stereo_tpu.obs.validate import check_span_integrity


def _spans(run_dir):
    path = os.path.join(run_dir, "events.jsonl")
    if not os.path.exists(path):  # nothing flushed yet
        return []
    return [r for r in read_events(path) if r.get("event") == "span"]


# ------------------------------------------------------- span mechanics

def test_span_nesting_and_trace_grouping(tmp_path):
    tel = Telemetry(str(tmp_path / "run"), stall_deadline_s=None)
    tr = Tracer(tel, flush_every=1)
    with tr.span("step", step=1):
        with tr.span("data_wait"):
            pass
        with tr.span("dispatch") as d:
            assert tr.current() == d.context
    tel.close()
    spans = {s["name"]: s for s in _spans(str(tmp_path / "run"))}
    assert set(spans) == {"step", "data_wait", "dispatch"}
    step = spans["step"]
    assert "parent_id" not in step
    for child in ("data_wait", "dispatch"):
        assert spans[child]["parent_id"] == step["span_id"]
        assert spans[child]["trace_id"] == step["trace_id"]
        assert spans[child]["start_s"] >= step["start_s"]
    assert step["step"] == 1                        # attrs ride along
    assert step["thread"] == threading.current_thread().name
    assert check_path(str(tmp_path / "run")) == []


def test_cross_thread_propagation(tmp_path):
    tel = Telemetry(str(tmp_path / "run"), stall_deadline_s=None)
    tr = Tracer(tel, flush_every=1)
    with tr.span("request"):
        ctx = tr.current()                          # propagation token

        def worker():
            assert tr.current() is None             # thread-local stack
            with tr.span("dispatch", parent=ctx):
                pass
        t = threading.Thread(target=worker, name="scheduler")
        t.start()
        t.join()
    tel.close()
    spans = {s["name"]: s for s in _spans(str(tmp_path / "run"))}
    assert spans["dispatch"]["parent_id"] == spans["request"]["span_id"]
    assert spans["dispatch"]["trace_id"] == spans["request"]["trace_id"]
    assert spans["dispatch"]["thread"] == "scheduler"
    assert spans["request"]["thread"] != "scheduler"


def test_retroactive_record_tiles_root_exactly(tmp_path):
    from raft_stereo_tpu.obs.timeline import span_coverage
    tel = Telemetry(str(tmp_path / "run"), stall_deadline_s=None)
    tr = Tracer(tel, flush_every=1)
    t0 = time.perf_counter()
    t1, t2, t3 = t0 + 0.010, t0 + 0.090, t0 + 0.100
    root = tr.record("step", t0, t3, step=1)
    assert isinstance(root, SpanContext)
    tr.record("data_wait", t0, t1, parent=root)
    tr.record("dispatch", t1, t2, parent=root)
    tr.record("fetch", t2, t3, parent=root)
    tel.close()
    spans = _spans(str(tmp_path / "run"))
    cov = span_coverage(spans)
    assert cov["roots"] == 1 and cov["min"] == 1.0
    # the stamps survive the clock mapping: children sum to the root
    by_name = {s["name"]: s for s in spans}
    assert by_name["step"]["dur_s"] == pytest.approx(0.100, abs=1e-5)
    assert by_name["dispatch"]["dur_s"] == pytest.approx(0.080, abs=1e-5)


def test_flush_batching_order_and_close_salvage(tmp_path):
    tel = Telemetry(str(tmp_path / "run"), stall_deadline_s=None)
    tr = Tracer(tel, flush_every=4)
    for i in range(3):
        with tr.span(f"a{i}"):
            pass
    assert _spans(str(tmp_path / "run")) == []      # buffered, not written
    with tr.span("a3"):
        pass                                        # 4th span -> batch flush
    flushed = [s["name"] for s in _spans(str(tmp_path / "run"))]
    assert flushed == ["a0", "a1", "a2", "a3"]      # end order preserved
    open_span = tr.start("dangling")
    with tr.span("a4"):
        pass
    tr.close()                                      # ends + flushes the rest
    names = [s["name"] for s in _spans(str(tmp_path / "run"))]
    assert names == ["a0", "a1", "a2", "a3", "a4", "dangling"]
    assert open_span.end_pc is not None
    assert check_path(str(tmp_path / "run")) == []  # integrity after salvage
    tel.close()


def test_ring_is_bounded_and_snapshot_marks_open(tmp_path):
    tr = Tracer(None, ring=16, flush_every=1000)
    for i in range(40):
        with tr.span(f"s{i}"):
            pass
    open_span = tr.start("inflight")
    snap = tr.snapshot()
    assert len(snap) == 17                          # 16 ring + 1 open
    assert [s for s in snap if s.get("open")][0]["name"] == "inflight"
    assert snap[0]["name"] == "s24"                 # oldest evicted
    open_span.end()


def test_null_tracer_is_inert_and_tracer_for_dispatch(tmp_path):
    with NULL_TRACER.span("x") as s:
        assert s is None
    assert NULL_TRACER.record("x", 0.0, 1.0) is None
    assert NULL_TRACER.current() is None
    assert NULL_TRACER.snapshot() == []
    assert not NULL_TRACER.enabled
    with pytest.raises(RuntimeError):
        NULL_TRACER.start("x")
    assert tracer_for(None) is NULL_TRACER
    assert tracer_for(object, enabled=False) is NULL_TRACER
    tel = Telemetry(str(tmp_path / "run"), stall_deadline_s=None)
    tr = tracer_for(tel)
    assert isinstance(tr, Tracer) and tel.tracer is tr
    assert tracer_for(tel) is tr                    # reuses the attached one
    tel.close()


# ----------------------------------------------------- flight recorder

def _flight_files(run_dir):
    return sorted(f for f in os.listdir(run_dir)
                  if f.startswith("flightrec-"))


def test_flight_recorder_dumps_on_anomaly_and_rate_limits(tmp_path):
    run = str(tmp_path / "run")
    tel = Telemetry(run, stall_deadline_s=None, flightrec_min_interval_s=60)
    tr = Tracer(tel, flush_every=1)
    with tr.span("step", step=7):
        tel.emit("anomaly", kind="nonfinite_grad", step=7)
    files = _flight_files(run)
    assert len(files) == 1
    lines = [json.loads(l) for l in
             open(os.path.join(run, files[0]))]
    header, body = lines[0], lines[1:]
    assert header["kind"] == "flightrec" and header["reason"] == "anomaly"
    kinds = {l["kind"] for l in body}
    assert kinds == {"event", "span"}
    anomaly = next(l["record"] for l in body if l["kind"] == "event"
                   and l["record"]["event"] == "anomaly")
    # the record's own kind field survives intact (nested, not flattened)
    assert anomaly["step"] == 7 and anomaly["kind"] == "nonfinite_grad"
    # the still-open root made it into the dump, marked open
    open_spans = [l["record"] for l in body
                  if l["kind"] == "span" and l["record"].get("open")]
    assert [s["name"] for s in open_spans] == ["step"]
    # second anomaly within the interval: rate-limited, no new file
    tel.emit("anomaly", kind="nonfinite_grad", step=8)
    assert _flight_files(run) == files
    tel.close()
    # the bus carries exactly one flightrec pointer, and the log lints
    events = read_events(os.path.join(run, "events.jsonl"))
    frecs = [e for e in events if e["event"] == "flightrec"]
    assert len(frecs) == 1 and frecs[0]["path"].endswith(files[0])
    assert check_path(run) == []


def test_flight_recorder_dumps_on_crash(tmp_path):
    run = str(tmp_path / "run")
    tel = Telemetry(run, stall_deadline_s=None)
    tel.emit("step", step=1, data_wait_s=0.0, dispatch_s=0.1, fetch_s=0.0)
    tel.error(RuntimeError("boom"))
    tel.close()
    files = _flight_files(run)
    assert len(files) == 1
    header = json.loads(open(os.path.join(run, files[0])).readline())
    assert header["reason"] == "crash"
    events = read_events(os.path.join(run, "events.jsonl"))
    kinds = [e["event"] for e in events]
    assert "error" in kinds and "flightrec" in kinds


def test_flight_recorder_dumps_on_watchdog_stall(tmp_path):
    run = str(tmp_path / "run")
    tel = Telemetry(run, stall_deadline_s=0.2, first_step_grace=1.0,
                    watch_interval_s=0.05, flightrec_min_interval_s=0.0)
    tel.heartbeat()                                 # arm the full deadline
    deadline = time.monotonic() + 10.0
    while not _flight_files(run) and time.monotonic() < deadline:
        time.sleep(0.05)
    tel.close()
    files = _flight_files(run)
    assert files, "watchdog never dumped"
    header = json.loads(open(os.path.join(run, files[0])).readline())
    assert header["reason"] == "stall"
    events = read_events(os.path.join(run, "events.jsonl"))
    stalls = [e for e in events if e["event"] == "stall"]
    assert stalls and stalls[0]["seconds_since_step"] >= 0.2


# ------------------------------------------------------------- timeline

def test_timeline_json_well_formed_and_device_clock_merge(tmp_path):
    from raft_stereo_tpu.obs.timeline import (_DEVICE_PID_BASE, HOST_PID,
                                              build_timeline)
    run = str(tmp_path / "run")
    tel = Telemetry(run, stall_deadline_s=None)
    tr = Tracer(tel, flush_every=1)
    t0 = time.perf_counter()
    root = tr.record("step", t0, t0 + 0.1, step=1)
    tr.record("dispatch", t0 + 0.01, t0 + 0.09, parent=root)
    tel.emit("compile", duration_s=1.5, source="test")   # instant marker
    tel.close()
    dispatch_start = next(s for s in _spans(run)
                          if s["name"] == "dispatch")["start_s"]
    # a fake jax.profiler capture with an opaque device timebase
    cap = tmp_path / "run" / "plugins" / "profile" / "20260805"
    cap.mkdir(parents=True)
    dev_events = [
        {"ph": "M", "pid": 9, "name": "process_name",
         "args": {"name": "/device:TPU:0 (fake)"}},
        {"ph": "M", "pid": 9, "tid": 2, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        {"ph": "X", "pid": 9, "tid": 2, "name": "fusion.1",
         "ts": 5_000_000.0, "dur": 80_000.0,
         "args": {"hlo_category": "fusion"}},
        {"ph": "X", "pid": 9, "tid": 2, "name": "copy.2",
         "ts": 5_080_000.0, "dur": 10_000.0,
         "args": {"hlo_category": "copy"}},
    ]
    with gzip.open(cap / "host.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": dev_events}, f)
    summary = build_timeline(run)
    assert summary["spans"] == 2 and summary["device_events"] == 4
    assert summary["markers"] >= 1
    assert summary["coverage"]["roots"] == 1
    doc = json.load(open(summary["path"]))
    assert set(doc) == {"traceEvents", "displayTimeUnit"}
    evs = doc["traceEvents"]
    host_x = [e for e in evs if e["ph"] == "X" and e["pid"] == HOST_PID]
    assert {e["name"] for e in host_x} == {"step", "dispatch"}
    # device events remapped out of the host pid range...
    dev_x = [e for e in evs if e["ph"] == "X"
             and e["pid"] == _DEVICE_PID_BASE + 9]
    assert len(dev_x) == 2
    # ...and shifted so the earliest device op starts at the earliest
    # host dispatch span (the one shared correlation anchor)
    assert min(e["ts"] for e in dev_x) == pytest.approx(
        dispatch_start * 1e6, abs=2.0)
    # relative device timing preserved under the shift
    ts = sorted(e["ts"] for e in dev_x)
    assert ts[1] - ts[0] == pytest.approx(80_000.0, abs=1e-3)


def test_timeline_without_device_capture_is_host_only(tmp_path):
    from raft_stereo_tpu.obs.timeline import build_timeline, main
    run = str(tmp_path / "run")
    tel = Telemetry(run, stall_deadline_s=None)
    tr = Tracer(tel, flush_every=1)
    t0 = time.perf_counter()
    tr.record("request", t0, t0 + 0.05, id="r1")
    tel.close()
    summary = build_timeline(run)
    assert summary["device_events"] == 0 and summary["spans"] == 1
    assert main([run]) == 0
    assert main([str(tmp_path / "nonexistent")]) == 1


# --------------------------------------------------------------- doctor

def _write_log(path, records):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    for rec in records:
        append_json_log(path, rec, stream=None)


def test_doctor_names_queue_saturation(tmp_path):
    from raft_stereo_tpu.obs.doctor import diagnose
    log = str(tmp_path / "serve" / "events.jsonl")
    recs = [make_record("run_start", t=0.0, run="serve")]
    for i in range(8):
        recs.append(make_record("request", t=0.5 + i * 0.5, id=f"r{i}",
                                status="ok", latency_s=1.0,
                                queue_wait_s=0.8))
    recs.append(make_record("queue", t=4.0, depth=60, rejected=5))
    _write_log(log, recs)
    report = diagnose(str(tmp_path / "serve"))
    (v,) = report["verdicts"]
    assert v["phase"] == "serve" and v["verdict"] == "QUEUE_SATURATED"
    joined = " ".join(v["evidence"])
    assert "queue_wait" in joined and "80%" in joined
    assert "5 submits shed" in joined


def test_doctor_names_data_starvation(tmp_path):
    from raft_stereo_tpu.obs.doctor import diagnose
    log = str(tmp_path / "train" / "events.jsonl")
    recs = [make_record("run_start", t=0.0, run="train")]
    for i in range(6):
        recs.append(make_record("step", t=1.0 + i, step=i + 1, loss=1.0,
                                data_wait_s=0.7, dispatch_s=0.2,
                                fetch_s=0.1))
        recs.append(make_record("loader", t=1.0 + i, queue_depth=0))
    _write_log(log, recs)
    (v,) = diagnose(str(tmp_path / "train"))["verdicts"]
    assert v["phase"] == "train" and v["verdict"] == "DATA_STARVED"
    joined = " ".join(v["evidence"])
    assert "data_wait" in joined and "decode workers" in joined


def test_doctor_names_compile_storm_and_stall_trumps(tmp_path):
    from raft_stereo_tpu.obs.doctor import diagnose
    storm = str(tmp_path / "storm" / "events.jsonl")
    recs = [make_record("run_start", t=0.0, run="storm")]
    for i in range(4):
        recs.append(make_record("compile", t=1.0 + i * 2, duration_s=1.8,
                                source="backend_compile"))
        recs.append(make_record("step", t=2.0 + i * 2, step=i + 1,
                                loss=1.0, data_wait_s=0.01,
                                dispatch_s=0.05, fetch_s=0.01))
    recs.append(make_record("run_end", t=10.0, steps=4))
    _write_log(storm, recs)
    (v,) = diagnose(str(tmp_path / "storm"))["verdicts"]
    assert v["verdict"] == "COMPILE_STORM"
    assert "4 compilations" in v["evidence"][0]
    # a stall record trumps rate analysis entirely
    stalled = str(tmp_path / "stalled" / "events.jsonl")
    _write_log(stalled, recs[:-1] + [
        make_record("stall", t=9.0, seconds_since_step=400.0,
                    deadline_s=300.0, steps=4),
        make_record("run_end", t=10.0, steps=4)])
    (v,) = diagnose(str(tmp_path / "stalled"))["verdicts"]
    assert v["verdict"] == "STALLED"
    assert "400.0s" in v["evidence"][0]


def test_doctor_unknown_on_empty_and_balanced_on_even(tmp_path):
    from raft_stereo_tpu.obs.doctor import diagnose, main
    log = str(tmp_path / "empty" / "events.jsonl")
    _write_log(log, [make_record("run_start", t=0.0, run="empty")])
    (v,) = diagnose(str(tmp_path / "empty"))["verdicts"]
    assert v["verdict"] == "UNKNOWN"
    even = str(tmp_path / "even" / "events.jsonl")
    recs = [make_record("run_start", t=0.0, run="even")]
    # steps[0] is dropped by the analyzer (compile leg); the body is built
    # so the MEDIAN wait (0.35) and median device share (0.55 of a 0.95
    # median total) each sit under their verdict thresholds — with uniform
    # steps the two fractions sum to 1 and one rule always fires
    phases = [(0.1, 0.1, 0.1),                       # dropped first step
              (0.5, 0.3, 0.1), (0.2, 0.6, 0.3), (0.35, 0.4, 0.15),
              (0.4, 0.35, 0.2), (0.3, 0.5, 0.2)]
    for i, (w, d, f) in enumerate(phases):
        recs.append(make_record("step", t=1.0 + i, step=i + 1, loss=1.0,
                                data_wait_s=w, dispatch_s=d, fetch_s=f))
    _write_log(even, recs)
    (v,) = diagnose(str(tmp_path / "even"))["verdicts"]
    assert v["verdict"] == "BALANCED"
    assert main([str(tmp_path / "even"), "--json"]) == 0
    assert main([str(tmp_path / "missing")]) == 1


# ----------------------------------------------------------- schema v7

def test_v7_records_validate_and_v6_stamp_is_drift():
    span = make_record("span", t=1.0, name="step", span_id="s1",
                       trace_id="t1", start_s=0.5, dur_s=0.5)
    assert validate_record(span) == []
    frec = make_record("flightrec", t=1.0, reason="stall", path="x.jsonl")
    assert validate_record(frec) == []
    stale = dict(span, schema=6)
    assert any("introduced in schema 7" in e
               for e in validate_record(stale))
    missing = {k: v for k, v in span.items() if k != "trace_id"}
    assert any("trace_id" in e for e in validate_record(missing))


def test_span_referential_integrity_lint(tmp_path):
    base = dict(name="x", start_s=0.0, dur_s=0.1)
    good = [make_record("span", t=1.0, span_id="s1", trace_id="t1", **base),
            make_record("span", t=1.0, span_id="s2", trace_id="t1",
                        parent_id="s1", **base)]
    assert check_span_integrity(good) == []
    orphan = good + [make_record("span", t=1.0, span_id="s3",
                                 trace_id="t1", parent_id="s9", **base)]
    assert any("parent_id" in e and "s9" in e
               for e in check_span_integrity(orphan))
    dup = good + [make_record("span", t=1.0, span_id="s1",
                              trace_id="t1", **base)]
    assert any("duplicate span_id" in e for e in check_span_integrity(dup))
    blank = [make_record("span", t=1.0, span_id="s1", trace_id="", **base)]
    assert any("trace_id" in e for e in check_span_integrity(blank))
    # check_path carries the integrity errors with file context
    bad = str(tmp_path / "bad" / "events.jsonl")
    _write_log(bad, [make_record("run_start", t=0.0, run="bad")] + orphan)
    assert any("s9" in e for e in check_path(bad))


def test_old_schema_artifacts_still_lint_clean():
    """v1..v6 rehearsal/drill artifacts in the repo predate spans and must
    keep linting clean under the v7 validator."""
    import glob
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    olds = [p for p in glob.glob(os.path.join(repo, "runs", "**",
                                              "events.jsonl"),
                                 recursive=True)]
    for path in olds:
        assert check_path(path) == [], path


# ------------------------------------------- zero overhead when disabled

def _tiny_train(tmp_path, name, trace):
    from raft_stereo_tpu.config import RAFTStereoConfig, TrainConfig
    from raft_stereo_tpu.training.trainer import train
    from test_trainer import _make_sceneflow_tree
    data = tmp_path / name
    data.mkdir()
    _make_sceneflow_tree(data)
    model_cfg = RAFTStereoConfig(hidden_dims=(32, 32, 32))
    cfg = TrainConfig(
        name=name, batch_size=2, num_steps=2, image_size=(48, 64),
        train_iters=1, valid_iters=1, data_root=str(data),
        ckpt_dir=str(data / "ckpts"), validation_frequency=5,
        num_workers=2, data_parallel=2, seq_parallel=1, lr=1e-4,
        run_dir=str(data / "runs"), trace=trace)
    train(model_cfg, cfg)
    return read_events(str(data / "runs" / name / "events.jsonl"))


@pytest.mark.slow
def test_tracing_off_is_bitwise_free_and_on_covers_steps(tmp_path):
    """The acceptance pin: same-seed runs with tracing on vs off emit
    identical step-loss streams (the NULL_TRACER path adds nothing to the
    numerics or the event payloads), and the traced run's spans tile >=90%
    of every step.

    Slow-marked (two end-to-end trains, ~40s on one core) alongside
    test_train_loop_end_to_end; scripts/trace_drill.py banks the same
    coverage evidence on real runs. The fast surrogate below pins the
    disabled path at the bus level in tier-1."""
    from raft_stereo_tpu.obs.timeline import span_coverage
    ev_on = _tiny_train(tmp_path, "traced", trace=True)
    ev_off = _tiny_train(tmp_path, "plain", trace=False)

    def step_stream(events):
        return [(e["step"], e["loss"], e["batch_size"])
                for e in events if e["event"] == "step"]

    assert step_stream(ev_on) == step_stream(ev_off)
    assert [e for e in ev_off if e["event"] == "span"] == []
    spans = [e for e in ev_on if e["event"] == "span"]
    names = {s["name"] for s in spans}
    assert {"step", "data_wait", "dispatch", "fetch"} <= names
    assert "loader/produce" in names                 # producer-thread spans
    cov = span_coverage(spans)
    assert cov["roots"] == 2 and cov["min"] >= 0.9
    # spans flushed before run_end (the trainer closes the tracer first)
    assert [e["event"] for e in ev_on][-1] == "run_end"


def test_disabled_tracer_leaves_the_bus_untouched(tmp_path):
    """Fast tier-1 surrogate for the slow end-to-end pin above: with
    tracing disabled the trainer-style tracer calls go through
    NULL_TRACER, and the event stream on disk is identical (modulo wall
    clock) to one produced with no tracer in the loop at all."""
    def run(dirname, with_null_tracer):
        tel = Telemetry(str(tmp_path / dirname), run_name="surrogate",
                        stall_deadline_s=None)
        tracer = tracer_for(tel, enabled=False) if with_null_tracer \
            else None
        for i in range(3):
            if tracer is not None:
                with tracer.span("step", step=i) as s:
                    assert s is None
                    with tracer.span("data_wait"):
                        pass
                assert tracer.record("fetch", 0.0, 1.0) is None
            tel.emit("step", step=i, loss=1.5, batch_size=2,
                     data_wait_s=0.01, dispatch_s=0.02, fetch_s=0.005)
            tel.heartbeat()
        tel.close()
        return read_events(str(tmp_path / dirname / "events.jsonl"))

    plain = run("plain", with_null_tracer=False)
    nulled = run("nulled", with_null_tracer=True)

    def scrub(events):
        return [{k: v for k, v in e.items() if k not in ("t", "ts")}
                for e in events]

    assert scrub(nulled) == scrub(plain)
    assert [e for e in nulled if e["event"] == "span"] == []
