import numpy as np
import jax
import jax.numpy as jnp
import pytest

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.models import create_model, init_model


SMALL = (1, 32, 64, 3)


def _images(rng, shape=SMALL):
    img1 = rng.uniform(0, 255, size=shape).astype(np.float32)
    img2 = rng.uniform(0, 255, size=shape).astype(np.float32)
    return jnp.asarray(img1), jnp.asarray(img2)


@pytest.fixture(scope="module")
def default_model():
    cfg = RAFTStereoConfig()
    model, variables = init_model(jax.random.PRNGKey(0), cfg, SMALL)
    return cfg, model, variables


class TestForward:
    def test_train_mode_shapes_and_finiteness(self, default_model):
        cfg, model, variables = default_model
        img1, img2 = _images(np.random.default_rng(0))
        preds = model.apply(variables, img1, img2, iters=4)
        assert preds.shape == (4, 1, 32, 64, 1)
        assert bool(jnp.isfinite(preds).all())

    def test_test_mode_matches_last_train_prediction(self, default_model):
        """test_mode only skips intermediate upsampling — the final prediction
        must be identical to train mode's last (raft_stereo.py:126-139)."""
        cfg, model, variables = default_model
        img1, img2 = _images(np.random.default_rng(1))
        preds = model.apply(variables, img1, img2, iters=3)
        low, up = model.apply(variables, img1, img2, iters=3, test_mode=True)
        # rtol: test_mode runs the final iteration OUTSIDE the scan (the
        # mask-head skip restructure) — different fusion boundaries than the
        # train scan give ulp-level drift that compounds to ~6e-5 relative
        # over the iterations; an iteration-count bug would show up at O(1).
        np.testing.assert_allclose(np.asarray(preds[-1]), np.asarray(up),
                                   rtol=2e-4, atol=1e-4)
        assert low.shape == (1, 8, 16, 2)

    def test_iterations_refine(self, default_model):
        """More iterations must change the prediction (the GRU is doing work)."""
        cfg, model, variables = default_model
        img1, img2 = _images(np.random.default_rng(2))
        _, up1 = model.apply(variables, img1, img2, iters=1, test_mode=True)
        _, up8 = model.apply(variables, img1, img2, iters=8, test_mode=True)
        assert float(jnp.abs(up8 - up1).max()) > 1e-4

    def test_flow_init_shifts_start(self, default_model):
        cfg, model, variables = default_model
        img1, img2 = _images(np.random.default_rng(3))
        low0, _ = model.apply(variables, img1, img2, iters=1, test_mode=True)
        finit = jnp.concatenate([jnp.full((1, 8, 16, 1), -3.0),
                                 jnp.zeros((1, 8, 16, 1))], axis=-1)
        low1, _ = model.apply(variables, img1, img2, iters=1, test_mode=True,
                              flow_init=finit)
        # starting point moved by -3 along x
        assert float(jnp.abs((low1 - low0)[..., 0].mean() + 3.0)) < 1.0

    def test_epipolar_constraint_y_flow_zero(self, default_model):
        cfg, model, variables = default_model
        img1, img2 = _images(np.random.default_rng(4))
        low, _ = model.apply(variables, img1, img2, iters=4, test_mode=True)
        np.testing.assert_allclose(np.asarray(low[..., 1]), 0.0, atol=1e-6)

    def test_reg_and_alt_agree_end_to_end(self):
        rng = np.random.default_rng(5)
        img1, img2 = _images(rng)
        outs = {}
        for impl in ("reg", "alt"):
            cfg = RAFTStereoConfig(corr_implementation=impl)
            model, variables = init_model(jax.random.PRNGKey(0), cfg, SMALL)
            _, outs[impl] = model.apply(variables, img1, img2, iters=4,
                                        test_mode=True)
        # fp differences amplify through the recurrence; allow small slack
        np.testing.assert_allclose(np.asarray(outs["reg"]),
                                   np.asarray(outs["alt"]), rtol=5e-3,
                                   atol=5e-3)

    def test_gradients_flow(self, default_model):
        cfg, model, variables = default_model
        img1, img2 = _images(np.random.default_rng(6))

        def loss_fn(params):
            preds = model.apply(
                {"params": params, "batch_stats": variables["batch_stats"]},
                img1, img2, iters=2)
            return jnp.abs(preds).mean()

        grads = jax.grad(loss_fn)(variables["params"])
        flat = jax.tree.leaves(grads)
        assert all(bool(jnp.isfinite(g).all()) for g in flat)
        # the GRU convs must receive gradient through the scan
        gru_grads = grads["refinement"]["update_block"]["gru08"]
        assert any(float(jnp.abs(g).max()) > 0
                   for g in jax.tree.leaves(gru_grads))


class TestVariants:
    @pytest.mark.parametrize("n_gru_layers", [1, 2, 3])
    def test_gru_layer_counts(self, n_gru_layers):
        cfg = RAFTStereoConfig(n_gru_layers=n_gru_layers)
        model, variables = init_model(jax.random.PRNGKey(0), cfg, SMALL)
        img1, img2 = _images(np.random.default_rng(7))
        _, up = model.apply(variables, img1, img2, iters=2, test_mode=True)
        assert up.shape == (1, 32, 64, 1)

    def test_realtime_configuration(self):
        """shared_backbone + n_downsample 3 + 2 GRU layers + slow_fast_gru
        (README.md:105), with the pure-JAX corr impl standing in for pallas."""
        cfg = RAFTStereoConfig(shared_backbone=True, n_downsample=3,
                               n_gru_layers=2, slow_fast_gru=True,
                               corr_implementation="reg")
        model, variables = init_model(jax.random.PRNGKey(0), cfg, SMALL)
        img1, img2 = _images(np.random.default_rng(8))
        low, up = model.apply(variables, img1, img2, iters=7, test_mode=True)
        assert low.shape == (1, 4, 8, 2)  # 1/8 resolution
        assert up.shape == (1, 32, 64, 1)

    def test_mixed_precision_bf16(self):
        cfg = RAFTStereoConfig(mixed_precision=True)
        model, variables = init_model(jax.random.PRNGKey(0), cfg, SMALL)
        img1, img2 = _images(np.random.default_rng(9))
        _, up = model.apply(variables, img1, img2, iters=2, test_mode=True)
        assert up.dtype == jnp.float32  # upsampling path stays fp32
        assert bool(jnp.isfinite(up).all())
        # params themselves stay fp32 (policy casts activations only)
        assert all(x.dtype == jnp.float32
                   for x in jax.tree.leaves(variables["params"]))

    def test_slow_fast_gru_changes_result(self):
        img1, img2 = _images(np.random.default_rng(10))
        outs = {}
        for sf in (False, True):
            cfg = RAFTStereoConfig(slow_fast_gru=sf)
            model, variables = init_model(jax.random.PRNGKey(0), cfg, SMALL)
            _, outs[sf] = model.apply(variables, img1, img2, iters=2,
                                      test_mode=True)
        assert float(jnp.abs(outs[True] - outs[False]).max()) > 1e-5

    def test_jit_forward(self, default_model):
        cfg, model, variables = default_model
        img1, img2 = _images(np.random.default_rng(11))

        @jax.jit
        def fwd(variables, i1, i2):
            return model.apply(variables, i1, i2, iters=2, test_mode=True)

        low, up = fwd(variables, img1, img2)
        assert up.shape == (1, 32, 64, 1)
