"""Observability layer (raft_stereo_tpu/obs): schema round-trip, the shared
JSONL sink, the stall watchdog, the run summarizer and the schema lint."""

import json
import os
import sys
import time
from pathlib import Path

import pytest

from raft_stereo_tpu.obs import (SCHEMA_VERSION, Telemetry, append_json_log,
                                 format_summary, make_record, read_events,
                                 summarize_run, validate_events,
                                 validate_record)

REPO = Path(__file__).resolve().parents[1]


def _write_run(run_dir, steps=6, stall_deadline_s=None, **tel_kw):
    """A synthetic but schema-complete run: one of every record family."""
    tel = Telemetry(str(run_dir), run_name="synth",
                    stall_deadline_s=stall_deadline_s, **tel_kw)
    tel.run_start(config={"batch_size": 2})
    tel.emit("compile", duration_s=1.25, source="first_step_latency")
    for i in range(steps):
        tel.step(i + 1, data_wait_s=0.01 * (i + 1), dispatch_s=0.05,
                 fetch_s=0.002, batch_size=2, loss=3.0 - 0.1 * i)
    tel.loader_gauge({"queue_depth": 3, "put_wait_s": 0.1,
                      "batches_produced": steps, "epoch": 0})
    tel.pipeline(in_flight=2, window=3, microbatch=1)
    tel.checkpoint(steps, str(run_dir / "ckpt"))
    tel.validation({"things-epe": 1.5}, dataset="things")
    tel.window_throughput()
    tel.emit("run_end", steps=steps, ok=True)
    tel.close()
    return tel


# --- schema -----------------------------------------------------------------

def test_events_schema_roundtrip(tmp_path):
    _write_run(tmp_path / "run")
    events = read_events(str(tmp_path / "run" / "events.jsonl"))
    assert validate_events(events) == []
    kinds = {e["event"] for e in events}
    assert {"run_start", "step", "compile", "checkpoint", "validation",
            "loader", "pipeline", "throughput", "memory", "run_end"} <= kinds
    assert all(e["schema"] == SCHEMA_VERSION for e in events)
    # the monotonic axis is present and non-decreasing
    ts = [e["t"] for e in events]
    assert ts == sorted(ts)


def test_validate_record_catches_drift():
    good = make_record("step", step=1, data_wait_s=0.0, dispatch_s=0.1,
                       fetch_s=0.0)
    assert validate_record(good) == []
    assert validate_record({**good, "schema": SCHEMA_VERSION + 1})
    assert validate_record({k: v for k, v in good.items()
                            if k != "dispatch_s"})
    assert validate_record({**good, "event": "not-an-event"})
    assert validate_record("not a dict")
    # the streaming-eval gauge: in_flight is required at this schema version
    assert validate_record(make_record("pipeline", in_flight=2)) == []
    assert validate_record(make_record("pipeline", window=3))


def test_append_json_log_bare_filename(tmp_path, monkeypatch):
    # regression: os.path.dirname("bare.jsonl") == "" used to crash makedirs
    monkeypatch.chdir(tmp_path)
    append_json_log("bare.jsonl", {"n": 1}, stream=None)
    append_json_log("bare.jsonl", {"n": 2}, stream=None)
    recs = read_events(str(tmp_path / "bare.jsonl"))
    assert [r["n"] for r in recs] == [1, 2]
    assert all("ts" in r for r in recs)


# --- watchdog ---------------------------------------------------------------

def _stalls(run_dir):
    return [e for e in read_events(str(run_dir / "events.jsonl"))
            if e["event"] == "stall"]


def test_watchdog_fires_on_frozen_step(tmp_path):
    run = tmp_path / "frozen"
    tel = Telemetry(str(run), stall_deadline_s=0.2, first_step_grace=1.0,
                    watch_interval_s=0.05)
    tel.step(1, data_wait_s=0.0, dispatch_s=0.0, fetch_s=0.0)
    deadline = time.monotonic() + 10.0
    while not _stalls(run) and time.monotonic() < deadline:
        time.sleep(0.05)  # the "step" is frozen: no further heartbeats
    tel.close()
    stalls = _stalls(run)
    assert stalls, "watchdog never fired on a frozen step"
    assert stalls[0]["seconds_since_step"] >= 0.2
    assert stalls[0]["deadline_s"] == 0.2
    # one record per episode, not one per poll
    assert len(stalls) == 1


def test_watchdog_silent_on_healthy_run(tmp_path):
    run = tmp_path / "healthy"
    tel = Telemetry(str(run), stall_deadline_s=2.0, first_step_grace=1.0,
                    watch_interval_s=0.05)
    for i in range(12):
        tel.step(i + 1, data_wait_s=0.0, dispatch_s=0.0, fetch_s=0.0)
        time.sleep(0.05)
    tel.close()
    assert _stalls(run) == []


def test_watchdog_rearms_after_recovery(tmp_path):
    run = tmp_path / "recover"
    tel = Telemetry(str(run), stall_deadline_s=0.15, first_step_grace=1.0,
                    watch_interval_s=0.03)
    tel.step(1, data_wait_s=0.0, dispatch_s=0.0, fetch_s=0.0)
    deadline = time.monotonic() + 10.0
    while len(_stalls(run)) < 1 and time.monotonic() < deadline:
        time.sleep(0.03)
    tel.step(2, data_wait_s=0.0, dispatch_s=0.0, fetch_s=0.0)  # recovery
    while len(_stalls(run)) < 2 and time.monotonic() < deadline:
        time.sleep(0.03)
    tel.close()
    assert len(_stalls(run)) == 2  # a second episode after re-arming


# --- summarizer -------------------------------------------------------------

def test_summarize_run_merges_events_and_trace(tmp_path):
    run = tmp_path / "run"
    _write_run(run)

    # a real (CPU) profiler capture under the run dir — no TPU required
    import jax
    import jax.numpy as jnp
    from raft_stereo_tpu.utils.profiling import trace

    @jax.jit
    def f(x):
        return jnp.sum(x @ x.T)

    x = jnp.ones((128, 128))
    float(f(x))
    with trace(str(run / "trace")):
        float(f(x))

    report = summarize_run(str(run))
    ev = report["events"]
    assert ev["steps"] == 6
    assert ev["phases"]["dispatch_s"]["total"] == pytest.approx(0.3, rel=0.05)
    assert ev["phases"]["data_wait_s"]["p50"] > 0
    assert ev["compiles"]["count"] >= 1
    assert ev["validations"] == [{"things-epe": 1.5}]
    assert ev["run_end"]["ok"] is True
    assert report["trace"] is not None and "error" not in report["trace"]
    assert report["schema_errors"] == []

    text = format_summary(report)
    assert "per-step phases" in text
    assert "dispatch_s" in text
    assert "throughput trend" in text
    assert "total device-op time" in text  # the merged trace half


def test_summarize_reports_pipeline_overlap(tmp_path):
    """Synthetic pipelined run: 0.03 s of phase work per step landing every
    0.01 s of wall clock -> overlap efficiency 3.0x, plus the in-flight
    gauge section."""
    run = tmp_path / "run"
    path = str(run / "events.jsonl")
    append_json_log(path, make_record("run_start", t=0.0, run="pipe"),
                    stream=None)
    for i in range(5):
        append_json_log(path, make_record(
            "step", t=0.01 * (i + 1), step=i + 1, data_wait_s=0.005,
            dispatch_s=0.02, fetch_s=0.005, batch_size=1, in_flight=2),
            stream=None)
    append_json_log(path, make_record("pipeline", t=0.06, in_flight=2,
                                      window=3, microbatch=2), stream=None)
    report = summarize_run(str(run))
    ov = report["events"]["pipeline_overlap"]
    assert ov["efficiency"] == pytest.approx(3.0, rel=0.01)
    assert ov["wall_s"] == pytest.approx(0.04)
    pg = report["events"]["pipeline"]
    assert pg["in_flight_max"] == 2 and pg["window"] == 3
    text = format_summary(report)
    assert "pipeline overlap: 3.0x" in text
    assert "pipeline gauges: 1" in text


def test_cli_telemetry_renders_synthetic_run(tmp_path, capsys):
    _write_run(tmp_path / "run")
    from raft_stereo_tpu.cli import main
    assert main(["telemetry", str(tmp_path / "run")]) == 0
    out = capsys.readouterr().out
    assert "per-step phases" in out
    assert "validation: {'things-epe': 1.5}" in out
    assert "stalls: none" in out


def test_summarize_run_without_artifacts(tmp_path):
    report = summarize_run(str(tmp_path))
    assert report["events"] is None and report["trace"] is None
    text = format_summary(report)
    assert "events: none" in text and "trace: none" in text


# --- schema lint (scripts/check_events.py) ----------------------------------

def _check_events():
    sys.path.insert(0, str(REPO / "scripts"))
    import check_events
    return check_events


def test_check_events_accepts_conforming_log(tmp_path):
    _write_run(tmp_path / "run")
    ce = _check_events()
    assert ce.main([str(tmp_path / "run")]) == 0
    assert ce.main([str(tmp_path / "run" / "events.jsonl")]) == 0


def test_check_events_rejects_drift(tmp_path):
    run = tmp_path / "run"
    _write_run(run)
    path = run / "events.jsonl"
    ce = _check_events()
    # a record from a future schema version must fail the lint
    with open(path, "a") as f:
        f.write(json.dumps({"schema": SCHEMA_VERSION + 1,
                            "ts": "2026-01-01T00:00:00",
                            "event": "step", "step": 1, "data_wait_s": 0,
                            "dispatch_s": 0, "fetch_s": 0}) + "\n")
    assert ce.main([str(run)]) == 1
    assert ce.main([str(tmp_path / "missing")]) == 1


# --- bench.py rides the same sink -------------------------------------------

def test_bench_chain_logs_attempts_through_sink(tmp_path):
    import bench
    chain = [dict(kw={"tag": "a"}, when="always", note="primary"),
             dict(kw={"tag": "b"}, when="unbanked", note="fallback")]

    def runner(kw, timeout_s=None):
        return ({"metric": "m", "value": 5.0} if kw["tag"] == "a" else None)

    log = tmp_path / "bench" / "attempts.jsonl"
    best = bench.run_chain(chain, runner, log_path=str(log))
    assert best["value"] == 5.0
    recs = read_events(str(log))
    assert [r["status"] for r in recs] == ["ok", "skipped"]
    assert recs[0]["result"]["value"] == 5.0
    assert all("ts" in r for r in recs)
