"""Evaluation layer: StereoPredictor bucketing + validators on synthetic data."""

import numpy as np
import pytest
from PIL import Image

import jax

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.data import frame_utils
from raft_stereo_tpu.eval import validate_eth3d, validate_middlebury
from raft_stereo_tpu.inference import StereoPredictor, bucket_size
from raft_stereo_tpu.models import init_model


@pytest.fixture(scope="module")
def predictor():
    cfg = RAFTStereoConfig()
    model, variables = init_model(jax.random.PRNGKey(0), cfg, (1, 64, 96, 3))
    return StereoPredictor(cfg, variables, valid_iters=2)


def test_bucket_size():
    assert bucket_size(41, 32) == 64
    assert bucket_size(64, 32) == 64
    assert bucket_size(65, 32, bucket=128) == 128
    assert bucket_size(129, 32, bucket=128) == 256


def test_predictor_shapes_and_caching(predictor):
    rng = np.random.default_rng(0)
    out = predictor(rng.uniform(0, 255, (1, 47, 90, 3)),
                    rng.uniform(0, 255, (1, 47, 90, 3)))
    assert out.shape == (1, 47, 90, 1)
    assert np.isfinite(out).all()
    assert len(predictor._compiled) == 1
    # 40x88 pads to the same 64x96 -> no new compile
    predictor(rng.uniform(0, 255, (1, 40, 88, 3)),
              rng.uniform(0, 255, (1, 40, 88, 3)))
    assert len(predictor._compiled) == 1
    # a genuinely different padded shape -> new entry
    predictor(rng.uniform(0, 255, (1, 100, 120, 3)),
              rng.uniform(0, 255, (1, 100, 120, 3)))
    assert len(predictor._compiled) == 2

    bucketed = StereoPredictor(predictor.cfg, predictor.variables,
                               valid_iters=2, bucket=128)
    bucketed(rng.uniform(0, 255, (1, 47, 90, 3)),
             rng.uniform(0, 255, (1, 47, 90, 3)))
    bucketed(rng.uniform(0, 255, (1, 100, 120, 3)),
             rng.uniform(0, 255, (1, 100, 120, 3)))
    assert len(bucketed._compiled) == 1  # both land in the 128x128 bucket


def test_compute_disparity_sign_and_grayscale(predictor):
    rng = np.random.default_rng(1)
    left = rng.uniform(0, 255, (47, 90)).astype(np.uint8)  # grayscale path
    disp = predictor.compute_disparity(left, left)
    assert disp.shape == (47, 90)
    assert np.isfinite(disp).all()


def _write_eth3d_tree(root, n=2, h=48, w=96):
    rng = np.random.default_rng(7)
    for i in range(n):
        scene = root / "ETH3D" / "two_view_training" / f"scene_{i}"
        gt = root / "ETH3D" / "two_view_training_gt" / f"scene_{i}"
        scene.mkdir(parents=True)
        gt.mkdir(parents=True)
        for name in ("im0.png", "im1.png"):
            Image.fromarray(rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
                            ).save(scene / name)
        frame_utils.write_pfm(str(gt / "disp0GT.pfm"),
                              rng.uniform(0, 8, (h, w)).astype(np.float32))
        Image.fromarray((rng.uniform(size=(h, w)) > 0.2).astype(np.uint8)
                        * 255).save(gt / "mask0nocc.png")


def test_validate_eth3d_synthetic(tmp_path, predictor):
    _write_eth3d_tree(tmp_path)
    result = validate_eth3d(predictor, root=str(tmp_path), iters=2)
    assert set(result) == {"eth3d-epe", "eth3d-d1"}
    assert np.isfinite(result["eth3d-epe"])
    assert 0.0 <= result["eth3d-d1"] <= 100.0


def _write_middlebury_tree(root, h=48, w=96):
    rng = np.random.default_rng(9)
    base = root / "Middlebury" / "MiddEval3"
    (base / "trainingF" / "SceneA").mkdir(parents=True)
    (base / "official_train.txt").write_text("SceneA\n")
    scene = base / "trainingF" / "SceneA"
    for name in ("im0.png", "im1.png"):
        Image.fromarray(rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
                        ).save(scene / name)
    frame_utils.write_pfm(str(scene / "disp0GT.pfm"),
                          rng.uniform(0, 8, (h, w)).astype(np.float32))
    Image.fromarray(np.full((h, w), 255, np.uint8)).save(scene / "mask0nocc.png")


def test_validate_middlebury_synthetic(tmp_path, predictor):
    _write_middlebury_tree(tmp_path)
    result = validate_middlebury(predictor, root=str(tmp_path), iters=2)
    assert set(result) == {"middleburyF-epe", "middleburyF-d1"}
    assert np.isfinite(result["middleburyF-epe"])
