"""End-to-end fused_motion path: full model fwd + train-step equivalence.

Uses an image width large enough that the pyramid's coarsest level exceeds
the kernel's minimum window (W2_3 = W/32 > 2r+2), so ``fused_motion=True``
actually engages the Pallas kernel (asserted); the unfused model with the
same parameters is the oracle.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.models import create_model, init_model
from raft_stereo_tpu.ops.pallas.motion_kernels import fused_motion_applicable
from raft_stereo_tpu.training.state import TrainState, make_train_step

H, W = 32, 352  # 1/4-res grid 8x88; pyramid W2s (88, 44, 22, 11)
ITERS = 2


def make_images(seed=0, batch=1):
    rng = np.random.default_rng(seed)
    i1 = jnp.asarray(rng.uniform(0, 255, (batch, H, W, 3)), jnp.float32)
    i2 = jnp.asarray(rng.uniform(0, 255, (batch, H, W, 3)), jnp.float32)
    return i1, i2


def test_fused_engages_at_this_shape():
    lv = tuple(jnp.zeros((1, H // 4, W // 4, (W // 4) >> i), jnp.float32)
               for i in range(4))
    assert fused_motion_applicable(lv, 4)


@pytest.mark.parametrize("mixed", [False, True])
def test_model_forward_fused_vs_unfused(mixed):
    cfg_off = RAFTStereoConfig(mixed_precision=mixed, fused_motion=False)
    cfg_on = RAFTStereoConfig(mixed_precision=mixed, fused_motion=True)
    model_off, variables = init_model(jax.random.PRNGKey(0), cfg_off,
                                      (1, H, W, 3))
    model_on = create_model(cfg_on)
    i1, i2 = make_images()
    out_off = model_off.apply(variables, i1, i2, iters=ITERS)
    out_on = model_on.apply(variables, i1, i2, iters=ITERS)
    a = np.asarray(out_off, np.float32)
    b = np.asarray(out_on, np.float32)
    # bf16 GRU iteration compounds rounding differences between the fused
    # kernel and the XLA graph; 0.5px (<0.3% relative) on a ~170px disparity
    # scale is inside bf16 noise (fp32 agreement is the exactness check)
    tol = 0.5 if mixed else 2e-3
    np.testing.assert_allclose(b, a, atol=tol,
                               err_msg="fused vs unfused predictions")


def test_train_step_fused_vs_unfused():
    i1, i2 = make_images(3)
    rng = np.random.default_rng(4)
    batch = {
        "image1": i1, "image2": i2,
        "flow": -jnp.asarray(rng.uniform(0, 8, (1, H, W, 1)), jnp.float32),
        "valid": jnp.ones((1, H, W), jnp.float32),
    }
    import optax

    outs = {}
    for name, fused in (("off", False), ("on", True)):
        cfg = RAFTStereoConfig(fused_motion=fused)
        model, variables = init_model(jax.random.PRNGKey(0), cfg,
                                      (1, H, W, 3))
        # SGD(1.0): the parameter delta IS the (negated) gradient, so this
        # compares raw gradients — Adam's per-element normalization would
        # amplify fp noise on near-zero-gradient params (e.g. conv biases
        # ahead of instance norm, which are shift-invariant) into O(1)
        # update differences that say nothing about correctness.
        tx = optax.sgd(1.0)
        state = TrainState.create(variables, tx)
        step = make_train_step(model, tx, ITERS)
        new_state, metrics = step(state, batch)
        grads = jax.tree.map(lambda old, new: np.asarray(old - new,
                                                         np.float32),
                             state.params, new_state.params)
        outs[name] = (grads, metrics)

    m_off, m_on = outs["off"][1], outs["on"][1]
    np.testing.assert_allclose(float(m_on["loss"]), float(m_off["loss"]),
                               rtol=1e-4)
    np.testing.assert_allclose(float(m_on["epe"]), float(m_off["epe"]),
                               rtol=1e-4)

    flat_off = jax.tree_util.tree_leaves_with_path(outs["off"][0])
    flat_on = jax.tree_util.tree_leaves_with_path(outs["on"][0])
    gscale = max(np.abs(a).max() for _, a in flat_off) + 1e-6
    for (path_a, a), (_, b) in zip(flat_off, flat_on):
        np.testing.assert_allclose(
            b / gscale, a / gscale, atol=1e-3,
            err_msg=f"gradient {jax.tree_util.keystr(path_a)}")
