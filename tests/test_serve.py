"""The serving subsystem (raft_stereo_tpu/serve):

* batching units: collect_group policy + BoundedQueue semantics;
* served-vs-direct bitwise parity per raw shape and per batch size
  (the scheduler pads exactly like StereoPredictor, so a request's
  result must not depend on who served it);
* per-request fault isolation: a NaN-poisoned request retires as an
  error while its BATCHMATE in the same dispatch stays bitwise-correct;
  a dispatch-level exception fails exactly that batch with a captured
  traceback and the scheduler keeps serving;
* flow_init warm starts: a video session's second frame rides the
  first frame's low-res flow (bitwise vs driving the executable cache
  by hand);
* hot reload: weights swap at a batch boundary without dropping queued
  work, without recompiles, and a structure mismatch is rejected;
* graceful drain: every admitted request completes, later submits are
  rejected-not-lost;
* PendingPrediction error capture (inference.py): a device error
  surfaces as a caught-and-cached per-request failure, not a
  half-fetched handle;
* schema v6: request/queue/slo records validate, v5-stamped v6 events
  flag drift, checked-in v1-v5 artifacts still lint clean;
* cli-drift rule v3: the serve/loadtest parser surfaces fire on a
  seeded orphan flag.
"""

import glob as globmod
import json
import time
from pathlib import Path

import numpy as np
import pytest

import jax

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.inference import (PAD_DIVIS, PendingPrediction,
                                       StereoPredictor, bucket_size)
from raft_stereo_tpu.models import init_model
from raft_stereo_tpu.obs import Telemetry, read_events
from raft_stereo_tpu.obs.events import validate_record
from raft_stereo_tpu.obs.validate import check_path
from raft_stereo_tpu.ops.geometry import InputPadder
from raft_stereo_tpu.serve import (BoundedQueue, BucketKey, QueueClosed,
                                   ServeConfig, ServerDraining, SLOTracker,
                                   StereoServer, collect_group)
from raft_stereo_tpu.serve.server import ServeResult

REPO = Path(__file__).resolve().parents[1]

H, W = 48, 96
ITERS = 2


# ------------------------------------------------- batching policy units

def _driver(items):
    """(pull, push_back, log) over a mutable list."""
    pushed = []

    def pull():
        return items.pop(0) if items else None

    return pull, pushed.append, pushed


def test_collect_group_greedy_same_key():
    items = ["a1", "a2", "b1", "a3"]
    pull, push, pushed = _driver(items)
    group = collect_group("a0", pull, push, 10, key=lambda s: s[0])
    assert group == ["a0", "a1", "a2"]
    assert pushed == ["b1"]          # the break starts the next group
    assert items == ["a3"]           # nothing beyond the break consumed


def test_collect_group_limit_and_exhaustion():
    items = ["a1", "a2"]
    pull, push, pushed = _driver(items)
    assert collect_group("a0", pull, push, 2,
                         key=lambda s: s[0]) == ["a0", "a1"]
    assert pushed == []
    pull2, push2, _ = _driver([])
    assert collect_group("x", pull2, push2, 4, key=len) == ["x"]


def test_bounded_queue_fifo_pushfront_close():
    q = BoundedQueue(2)
    assert q.put("a", timeout=0.1) and q.put("b", timeout=0.1)
    assert not q.put("c", timeout=0.05)     # full: timeout, not loss
    assert q.get() == "a"
    q.push_front("a0")                      # head re-insert
    assert q.get() == "a0" and q.get() == "b"
    assert q.get(timeout=0.05) is None
    q.put("tail", timeout=0.1)
    q.close()
    with pytest.raises(QueueClosed):
        q.put("z", timeout=0.1)
    assert q.get() == "tail"                # drain continues after close
    assert q.get() is None                  # closed + empty: exit signal


# ------------------------------------------------------- served parity

@pytest.fixture(scope="module")
def stack():
    cfg = RAFTStereoConfig()
    _, variables = init_model(jax.random.PRNGKey(0), cfg, (1, H, W, 3))
    predictor = StereoPredictor(cfg, variables, valid_iters=ITERS)
    server = StereoServer(
        cfg, variables,
        ServeConfig(max_batch=2, window=2, default_iters=ITERS,
                    linger_s=0.4))
    yield cfg, variables, predictor, server
    server.request_drain()
    server.join(timeout=60)


def _pair(seed, h=H, w=W, poison=False):
    rng = np.random.default_rng(seed)
    left = rng.integers(0, 255, (h, w, 3)).astype(np.float32)
    right = rng.integers(0, 255, (h, w, 3)).astype(np.float32)
    if poison:
        left[0, 0, 0] = np.nan
    return left, right


def test_served_bitwise_equals_predict_per_shape(stack):
    """Two raw shapes padding into the SAME compiled bucket must each
    come back bitwise-equal to the direct predictor."""
    _, _, predictor, server = stack
    for seed, (h, w) in enumerate([(H, W), (40, 80)]):
        left, right = _pair(seed, h, w)
        res = server.submit(left, right).result(timeout=300)
        assert res.ok and res.flow.shape == (h, w, 1)
        direct = predictor(left[None], right[None], ITERS)
        np.testing.assert_array_equal(res.flow, direct[0])
        assert res.disparity.shape == (h, w)
        np.testing.assert_array_equal(res.disparity, -direct[0, ..., 0])


def test_batched_dispatch_bitwise_and_poison_isolation(stack):
    """Concurrent same-shape submits ride ONE dispatch; poisoning one of
    them fails exactly that request while the batchmate's output stays
    bitwise-identical to what it gets next to a CLEAN batchmate — the
    NaN never crosses batch slots. (The b=2 executable's floats differ
    from the b=1 one at ~1e-5 on XLA CPU — batch-size numerics — so the
    direct-predict cross-check is allclose, not bitwise; the per-bucket
    bitwise claim lives in test_served_bitwise_equals_predict_per_shape
    where batch sizes match.)"""
    cfg, _, predictor, server = stack
    clean_l, clean_r = _pair(10)
    bad_l, bad_r = _pair(11, poison=True)
    h_clean = server.submit(clean_l, clean_r)
    h_bad = server.submit(bad_l, bad_r)
    r_clean = h_clean.result(timeout=300)
    r_bad = h_bad.result(timeout=300)
    # the linger window packs the back-to-back submits into one dispatch
    assert r_clean.batch_size == 2 and r_bad.batch_size == 2
    assert r_clean.bucket == r_bad.bucket
    assert not r_bad.ok
    assert r_bad.error_kind == "nonfinite_output"
    assert r_bad.flow is None
    assert r_clean.ok
    # NaN isolation: drive the SAME b=2 executable by hand with the
    # poisoned batchmate swapped for a clean one — slot 0 must not move
    # by a single bit
    bh = bucket_size(H, PAD_DIVIS, 0)
    bw = bucket_size(W, PAD_DIVIS, 0)
    key = BucketKey(bh, bw, 2, ITERS, False)
    padder = InputPadder((1, H, W, 3), divis_by=PAD_DIVIS, target=(bh, bw))
    alt_l, alt_r = _pair(13)
    def batch(mate_l, mate_r):
        ims = [padder.pad(l[None], r[None])
               for l, r in ((clean_l, clean_r), (mate_l, mate_r))]
        im1 = np.concatenate([np.asarray(p[0]) for p in ims])
        im2 = np.concatenate([np.asarray(p[1]) for p in ims])
        return server.cache(key, im1, im2, None)
    # the converge-flavor program carries the curve as a 4th output
    _, up_bad_mate, finite_bad, *_ = (np.asarray(o) for o in
                                      batch(bad_l, bad_r))
    _, up_clean_mate, finite_clean, *_ = (np.asarray(o) for o in
                                          batch(alt_l, alt_r))
    assert list(finite_bad) == [True, False]
    assert list(finite_clean) == [True, True]
    np.testing.assert_array_equal(up_bad_mate[0], up_clean_mate[0])
    # and the served result IS that executable's slot-0 output
    np.testing.assert_array_equal(
        r_clean.flow, np.asarray(padder.unpad(up_bad_mate[0:1]))[0])
    # cross-batch-size sanity vs the direct b=1 predictor
    direct = predictor(clean_l[None], clean_r[None], ITERS)
    np.testing.assert_allclose(r_clean.flow, direct[0],
                               rtol=5e-3, atol=1e-3)
    # the scheduler survived: a fresh request still serves
    left, right = _pair(12)
    assert server.submit(left, right).result(timeout=300).ok


def test_video_stream_warm_start_chains_flow_init(stack):
    """Frame 2 of a video session must ride frame 1's low-res flow:
    bitwise-equal to driving the warm executable by hand, and different
    from a cold (zero-init) pass over the same frame."""
    cfg, _, _, server = stack
    bh = bucket_size(H, PAD_DIVIS, 0)
    bw = bucket_size(W, PAD_DIVIS, 0)
    factor = 2 ** cfg.n_downsample
    l1, r1 = _pair(20)
    l2, r2 = _pair(21)
    res1 = server.submit(l1, r1, stream="cam", warm_start=True) \
        .result(timeout=300)
    res2 = server.submit(l2, r2, stream="cam", warm_start=True) \
        .result(timeout=300)
    assert res1.ok and res2.ok and res1.bucket.endswith("w")
    key = BucketKey(bh, bw, 1, ITERS, True)
    padder = InputPadder((1, H, W, 3), divis_by=PAD_DIVIS, target=(bh, bw))
    zeros = np.zeros((1, bh // factor, bw // factor, 2), np.float32)
    p1 = [np.asarray(x) for x in padder.pad(l1[None], r1[None])]
    p2 = [np.asarray(x) for x in padder.pad(l2[None], r2[None])]
    lr1, up1, *_ = (np.asarray(o) for o in server.cache(key, *p1, zeros))
    np.testing.assert_array_equal(res1.flow,
                                  np.asarray(padder.unpad(up1))[0])
    _, up2_warm, *_ = (np.asarray(o)
                       for o in server.cache(key, *p2, lr1))
    np.testing.assert_array_equal(res2.flow,
                                  np.asarray(padder.unpad(up2_warm))[0])
    _, up2_cold, *_ = (np.asarray(o)
                       for o in server.cache(key, *p2, zeros))
    assert not np.array_equal(up2_warm, up2_cold)


def test_hot_reload_swaps_weights_without_drop_or_recompile(stack):
    """reload() must change served outputs, complete every queued
    request, add no executables, and reject a structure mismatch."""
    _, variables, predictor, server = stack
    left, right = _pair(30)
    before = server.submit(left, right).result(timeout=300)
    assert before.ok
    n_exec = len(server.cache)
    scaled = jax.tree.map(lambda l: l * 0.5, variables)
    handles = [server.submit(*_pair(31 + i)) for i in range(3)]
    server.reload(scaled, note="test-swap")
    handles.append(server.submit(left, right))
    results = [h.result(timeout=300) for h in handles]
    assert all(r.ok for r in results)          # nothing dropped
    after = server.submit(left, right).result(timeout=300)
    assert after.ok
    assert not np.array_equal(after.flow, before.flow)
    # variables are a runtime argument: same executables serve new weights
    assert len(server.cache) == n_exec
    old_vars = predictor.variables
    try:
        predictor.variables = scaled
        direct = predictor(left[None], right[None], ITERS)
    finally:
        predictor.variables = old_vars
    np.testing.assert_array_equal(after.flow, direct[0])
    with pytest.raises(ValueError):
        server.reload({"params": {"bogus": np.zeros(3, np.float32)}})
    server.reload(variables)                   # restore for later tests


def test_drain_completes_admitted_rejects_new(stack):
    """request_drain(): every admitted request retires, later submits
    raise ServerDraining, the scheduler thread exits. Runs LAST — it
    shuts the module server down (the SIGTERM path in cli/load_drill
    is this plus a SignalGuard)."""
    _, _, _, server = stack
    handles = [server.submit(*_pair(40 + i)) for i in range(4)]
    server.request_drain()
    with pytest.raises(ServerDraining):
        server.submit(*_pair(50))
    results = [h.result(timeout=300) for h in handles]
    assert all(r.ok for r in results)
    assert server.join(timeout=120)
    stats = server.stats()
    assert stats["queue_depth"] == 0 and stats["in_flight"] == 0
    assert stats["rejected"] >= 1


# ------------------------------------- scheduler survives device errors

class _ExplodingCache:
    """Stands in for ExecutableCache: the dispatch itself raises."""

    def __call__(self, key, im1, im2, flow_init=None):
        raise RuntimeError("synthetic device failure")


class _FakeCache:
    """Instant fake executable: constant finite outputs."""

    def __len__(self):  # stats()/healthz report the resident-program count
        return 1

    def __call__(self, key, im1, im2, flow_init=None):
        b, h, w, _ = im1.shape
        return (np.zeros((b, h // 4, w // 4, 2), np.float32),
                np.full((b, h, w, 1), 7.0, np.float32),
                np.ones((b,), bool))


def _light_server(tmp_path, cache, telemetry=None, **kw):
    cfg = RAFTStereoConfig()
    _, variables = init_model(jax.random.PRNGKey(0), cfg, (1, H, W, 3))
    server = StereoServer(
        cfg, variables,
        ServeConfig(max_batch=2, window=2, default_iters=ITERS,
                    linger_s=0.2, slo_every=2, **kw),
        telemetry=telemetry, autostart=False)
    server.cache = cache
    return server


def test_dispatch_failure_fails_batch_not_scheduler(tmp_path):
    tel = Telemetry(str(tmp_path / "run"), stall_deadline_s=None)
    server = _light_server(tmp_path, _ExplodingCache(), telemetry=tel)
    server.start()
    handles = [server.submit(*_pair(60 + i)) for i in range(2)]
    results = [h.result(timeout=60) for h in handles]
    assert all(not r.ok for r in results)
    assert all(r.error_kind == "dispatch" for r in results)
    assert all("synthetic device failure" in r.error for r in results)
    assert all("RuntimeError" in r.traceback for r in results)
    # the scheduler thread survived the exploding batch
    server.cache = _FakeCache()
    assert server.submit(*_pair(62)).result(timeout=60).ok
    server.request_drain()
    assert server.join(timeout=60)
    tel.close()
    events = read_events(str(tmp_path / "run" / "events.jsonl"))
    failed = [e for e in events if e.get("event") == "request"
              and e.get("status") == "error"]
    assert failed and all("RuntimeError" in e["traceback"] for e in failed)
    assert check_path(str(tmp_path / "run")) == []


def test_drain_on_unstarted_server_completes_inline(tmp_path):
    server = _light_server(tmp_path, _FakeCache())
    handles = [server.submit(*_pair(70 + i)) for i in range(3)]
    assert server.close(timeout=60)
    assert all(h.result(timeout=5).ok for h in handles)


def test_http_metrics_exposition_after_load(tmp_path):
    """Mini HTTP loadtest: POST a few /v1/predict requests, then assert
    GET /metrics serves Prometheus text with the SLOTracker gauges and
    monotone counters reflecting the load; --no_metrics turns it off."""
    import io
    import urllib.error
    import urllib.request

    from raft_stereo_tpu.serve.http import make_http_server

    # the fake cache never runs the model, and ExecutableCache.__init__
    # only hashes the pytree structure — a stub keeps this test off the
    # ~10s eager init_model path (one-core suite budget)
    stub_vars = {"params": {"w": np.zeros((1,), np.float32)}}
    server = StereoServer(
        RAFTStereoConfig(), stub_vars,
        ServeConfig(max_batch=2, window=2, default_iters=ITERS,
                    linger_s=0.05, slo_every=2),
        autostart=False)
    server.cache = _FakeCache()
    server.start()
    # host_id exercises the schema-v10 fleet labeling: every family gains
    # a host label so N hosts' expositions can be scraped into one view
    httpd = make_http_server(server, "127.0.0.1", 0,   # ephemeral port
                             host_id="metrics-host")
    t = __import__("threading").Thread(target=httpd.serve_forever,
                                       daemon=True)
    t.start()
    base = "http://%s:%d" % httpd.server_address
    try:
        for i in range(3):
            left, right = _pair(80 + i)
            buf = io.BytesIO()
            np.savez_compressed(buf, left=left, right=right)
            req = urllib.request.Request(f"{base}/v1/predict",
                                         data=buf.getvalue(), method="POST")
            with urllib.request.urlopen(req, timeout=30) as resp:
                assert resp.status == 200
        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/plain")
            text = resp.read().decode()
        assert "# TYPE raft_serve_latency_p50_ms gauge" in text
        assert "# TYPE raft_serve_requests_completed_total counter" in text
        values = {line.split()[0]: float(line.split()[1])
                  for line in text.splitlines()
                  if line and not line.startswith("#")}
        hl = '{host="metrics-host"}'
        assert values["raft_serve_requests_admitted_total" + hl] == 3
        assert values["raft_serve_requests_completed_total" + hl] == 3
        assert values["raft_serve_requests_failed_total" + hl] == 0
        assert values["raft_serve_latency_p50_ms" + hl] > 0
        assert values["raft_serve_draining" + hl] == 0
        # per-bucket families carry BOTH labels (bucket first, host after);
        # the fake cache produces no quality window, so exercise the
        # renderer directly with a seeded bucket
        from raft_stereo_tpu.serve.http import prometheus_metrics
        seeded = dict(server.stats(),
                      quality={"48x96b2i2": {"final_residual_p50": 1.0}})
        assert ('raft_serve_final_residual_p50'
                '{bucket="48x96b2i2",host="metrics-host"}'
                in prometheus_metrics(seeded, host_id="metrics-host"))
        # the --no_metrics plumbing: a metrics-off frontend on the same
        # server 404s the exposition (the handler never reaches the
        # scheduler, so no second model init is needed)
        httpd2 = make_http_server(server, "127.0.0.1", 0, metrics=False)
        t2 = __import__("threading").Thread(target=httpd2.serve_forever,
                                            daemon=True)
        t2.start()
        base2 = "http://%s:%d" % httpd2.server_address
        try:
            with pytest.raises(urllib.error.HTTPError) as exc_info:
                urllib.request.urlopen(f"{base2}/metrics", timeout=10)
            assert exc_info.value.code == 404
        finally:
            httpd2.shutdown()
    finally:
        httpd.shutdown()
        server.close(timeout=60)


# --------------------------------------- PendingPrediction error capture

class _ExplodingArray:
    def __array__(self, *a, **kw):
        raise RuntimeError("device said no")


def test_pending_prediction_captures_fetch_error():
    pending = PendingPrediction(_ExplodingArray(), lambda x: x, 0.01)
    with pytest.raises(RuntimeError, match="device said no"):
        pending.result()
    assert isinstance(pending.exception(), RuntimeError)
    assert pending.fetch_s is not None
    assert pending._flow is None              # buffer reference released
    with pytest.raises(RuntimeError, match="device said no"):
        pending.result()                      # idempotent re-raise


def test_pending_prediction_success_path_unchanged():
    arr = np.ones((1, 4, 4, 1), np.float32)
    pending = PendingPrediction(arr, lambda x: x, 0.01)
    np.testing.assert_array_equal(pending.result(), arr)
    assert pending.exception() is None


# ------------------------------------------------------- schema v6 / SLO

def test_v6_records_validate_and_v5_stamp_is_drift():
    ok = {"schema": 6, "ts": "2026-01-01T00:00:00",
          "event": "slo", "p50_ms": 10.0, "p99_ms": 20.0,
          "pairs_per_sec": 3.0, "in_flight": 1}
    assert validate_record(ok) == []
    assert validate_record({**ok, "schema": 5})  # introduced-in-v6 drift
    assert validate_record({"schema": 6, "ts": "t", "event": "request",
                            "id": "r1", "status": "ok"}) == []
    assert validate_record({"schema": 6, "ts": "t", "event": "queue",
                            "depth": 4}) == []
    missing = validate_record({"schema": 6, "ts": "t", "event": "request",
                               "id": "r1"})
    assert any("status" in e for e in missing)


def test_checked_in_artifacts_still_lint_clean_under_v6():
    """The v5 -> v6 bump is additive: every banked events.jsonl from
    earlier rounds must still validate."""
    artifacts = sorted(globmod.glob(str(REPO / "runs" / "*" /
                                        "events.jsonl")))
    assert artifacts, "expected banked run artifacts in runs/"
    for path in artifacts:
        assert check_path(path) == [], path


def test_slo_tracker_emits_valid_rollups(tmp_path):
    tel = Telemetry(str(tmp_path / "slo"), stall_deadline_s=None)
    slo = SLOTracker(tel, window=8, emit_every=2, gauge_every=1)
    for i in range(4):
        slo.admit(queue_depth=i, in_flight=1)
        slo.retire(request_id=f"r{i}", status="ok" if i else "error",
                   latency_s=0.01 * (i + 1), queue_wait_s=0.001,
                   bucket="64x96b1i2", batch_size=1, in_flight=1,
                   error=None if i else "boom",
                   traceback_tail=None if i else "T" * 3000)
    tel.close()
    events = read_events(str(tmp_path / "slo" / "events.jsonl"))
    kinds = [e["event"] for e in events]
    assert kinds.count("queue") == 4 and kinds.count("request") == 4
    rollups = [e for e in events if e["event"] == "slo"]
    assert len(rollups) == 2
    assert rollups[-1]["p99_ms"] >= rollups[-1]["p50_ms"] > 0
    assert rollups[-1]["completed"] == 3 and rollups[-1]["failed"] == 1
    boom = next(e for e in events if e.get("status") == "error")
    assert len(boom["traceback"]) == 2000     # tail-truncated
    assert check_path(str(tmp_path / "slo")) == []
    snap = slo.snapshot(in_flight=0)
    assert snap["window_requests"] == 4


# ------------------------------------------------- cli surfaces + lint

def test_serve_parsers_defaults_and_shapes():
    from raft_stereo_tpu.cli import (_parse_shapes, build_loadtest_parser,
                                     build_serve_parser, serve_config)
    args = build_serve_parser().parse_args([])
    cfg = serve_config(args)
    assert cfg.max_batch == 4 and cfg.window == 2 and cfg.aot
    lt = build_loadtest_parser().parse_args(["--poison_at", "5"])
    assert lt.poison_at == 5 and lt.clients == 8
    assert len(set(lt.shapes)) >= 3
    assert _parse_shapes(["48x96", "128X64"]) == [(48, 96), (128, 64)]


def test_cli_main_knows_serve_and_loadtest(capsys):
    from raft_stereo_tpu.cli import main
    assert main([]) == 2
    usage = capsys.readouterr().err
    assert "serve" in usage and "loadtest" in usage


def test_cli_drift_v3_fires_on_seeded_serve_fixture(tmp_path):
    """Rule v3 coverage: an orphan flag on either serving surface is an
    error."""
    from raft_stereo_tpu.analysis.ast_rules import (
        RULE_VERSIONS, check_entry_surface_drift)

    assert RULE_VERSIONS["cli-drift"] == 10
    pkg = tmp_path / "raft_stereo_tpu"
    (pkg / "serve").mkdir(parents=True)
    (pkg / "cli.py").write_text(
        "def build_serve_parser():\n"
        "    import argparse\n"
        "    p = argparse.ArgumentParser()\n"
        "    p.add_argument('--port')\n"
        "    p.add_argument('--serve_orphan')\n"
        "    return p\n"
        "def build_loadtest_parser():\n"
        "    import argparse\n"
        "    p = argparse.ArgumentParser()\n"
        "    p.add_argument('--clients')\n"
        "    p.add_argument('--loadtest_orphan')\n"
        "    return p\n"
        "def _serve_main():\n"
        "    args = build_serve_parser().parse_args()\n"
        "    print(args.port)\n")
    (pkg / "serve" / "loadtest.py").write_text(
        "def run(args):\n"
        "    return args.clients\n")
    findings = check_entry_surface_drift(str(tmp_path))
    orphans = {f.data.get("dest") for f in findings
               if f.rule == "cli-drift" and f.severity == "error"}
    assert orphans == {"serve_orphan", "loadtest_orphan"}


def test_cli_drift_v4_fires_on_seeded_timeline_doctor_fixture(tmp_path):
    """Rule v4: the timeline/doctor surfaces drift the same way — a flag
    declared in cli.py that neither cli.py nor the obs consumer module
    reads is an orphan; flags the consumer reads stay clean."""
    from raft_stereo_tpu.analysis.ast_rules import (
        check_entry_surface_drift)

    pkg = tmp_path / "raft_stereo_tpu"
    (pkg / "obs").mkdir(parents=True)
    (pkg / "cli.py").write_text(
        "def build_timeline_parser():\n"
        "    import argparse\n"
        "    p = argparse.ArgumentParser()\n"
        "    p.add_argument('--out')\n"
        "    p.add_argument('--timeline_orphan')\n"
        "    return p\n"
        "def build_doctor_parser():\n"
        "    import argparse\n"
        "    p = argparse.ArgumentParser()\n"
        "    p.add_argument('--json')\n"
        "    p.add_argument('--doctor_orphan')\n"
        "    return p\n")
    (pkg / "obs" / "timeline.py").write_text(
        "def main(args):\n"
        "    return args.out\n")
    (pkg / "obs" / "doctor.py").write_text(
        "def main(args):\n"
        "    return getattr(args, 'json')\n")
    findings = check_entry_surface_drift(str(tmp_path))
    errors = [f for f in findings
              if f.rule == "cli-drift" and f.severity == "error"]
    orphans = {f.data.get("dest") for f in errors}
    assert orphans == {"timeline_orphan", "doctor_orphan"}
    surfaces = {f.data.get("surface") for f in errors}
    assert surfaces == {"build_timeline_parser", "build_doctor_parser"}


def test_cli_drift_v4_real_surfaces_are_clean():
    """The shipped timeline/doctor/serve surfaces lint clean — every
    declared flag (incl. --no_metrics / --no_trace plumbing) is read by
    a consumer module."""
    import os

    import raft_stereo_tpu
    from raft_stereo_tpu.analysis.ast_rules import (
        check_cli_config_drift, check_entry_surface_drift)

    root = os.path.dirname(os.path.dirname(raft_stereo_tpu.__file__))
    errors = [f for f in check_entry_surface_drift(root)
              if f.severity == "error"]
    assert errors == []
    cli_path = os.path.join(root, "raft_stereo_tpu", "cli.py")
    errors = [f for f in check_cli_config_drift(cli_path,
                                                "raft_stereo_tpu/cli.py")
              if f.severity == "error"]
    assert errors == []


def test_loadtest_trace_covers_required_mix():
    from raft_stereo_tpu.serve.loadtest import LoadTestConfig
    lt = LoadTestConfig(clients=8, requests_per_client=4, video_streams=1,
                        poison_at=9)
    trace = lt.trace()
    assert len(trace) == 8
    shapes = {spec["shape"] for client in trace for spec in client}
    assert len(shapes) >= 3
    videos = [s for client in trace for s in client if s["video"]]
    assert videos and all(s["stream"] == "video0" for s in videos)
    poisoned = [s for client in trace for s in client if s["poison"]]
    assert len(poisoned) == 1 and poisoned[0]["ordinal"] == 9
