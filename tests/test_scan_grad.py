"""Gradient-equivalence suite for the custom-VJP refinement scan.

The batched-weight-grad backward (ops/scan_grad.py, config.batched_scan_wgrad)
must be pure scheduling: same forward, same gradients as
autodiff-through-``lax.scan``. Contracts pinned here:

* **fp32 residuals**: gradients match autodiff to accumulation-order
  tolerance — the batched contraction sums the iteration axis inside one
  conv reduction instead of ``iters`` ordered adds, so bitwise equality is
  impossible but every leaf agrees to ~1e-4 relative.
* **bf16 residual stacks** (config.residual_dtype): gradients match within
  the documented bf16 tolerance (leaf relative-L2 <= 2e-2); the custom
  path's FORWARD stays exact (only saved copies are rounded), while the
  autodiff path's cast-through rounds the tagged saves in the forward.
* Both contracts hold across save-policy off/on/"corr", the deferred-fused
  and stacked loss paths, remat on/off, and (slow-marked) the shard_map DP
  path. Everything runs under ``JAX_PLATFORMS=cpu``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.models import create_model, init_model
from raft_stereo_tpu.training.loss import loss_mask

SHAPE = (1, 32, 48, 3)


@pytest.fixture(scope="module")
def setup():
    base = RAFTStereoConfig()
    model, variables = init_model(jax.random.PRNGKey(0), base, SHAPE)
    rng = np.random.default_rng(11)
    img1 = jnp.asarray(rng.uniform(0, 255, SHAPE), jnp.float32)
    img2 = jnp.asarray(rng.uniform(0, 255, SHAPE), jnp.float32)
    gt = jnp.asarray(rng.uniform(-8, 0, SHAPE[:3] + (1,)), jnp.float32)
    valid = jnp.ones(SHAPE[:3], jnp.float32)
    return variables, img1, img2, gt, valid


def stacked_loss(model, variables, img1, img2, iters=2):
    rest = {k: v for k, v in variables.items() if k != "params"}

    def f(p):
        out = model.apply({"params": p, **rest}, img1, img2, iters=iters)
        return jnp.mean(jnp.abs(out))
    return f


def fused_loss(model, variables, img1, img2, gt, valid, iters=2):
    rest = {k: v for k, v in variables.items() if k != "params"}
    mask = loss_mask(gt, valid)

    def f(p):
        err, final = model.apply({"params": p, **rest}, img1, img2,
                                 iters=iters, flow_gt=gt, loss_mask=mask)
        return jnp.sum(err) + jnp.mean(jnp.abs(final))
    return f


def assert_grads_close(want, got, rel_l2=5e-4):
    """The fp32 contract: per-leaf relative L2 within accumulation-order
    tolerance. Element-wise bounds would chase reassociation dust (the two
    paths compile different scan bodies, so XLA may reorder fp32 adds), and
    leaves that are structurally-zero gradients — conv biases feeding
    instance norm — are pure float residue with O(1) relative spread; the
    residue floor pins them near zero instead (the test_training.py
    scan_unroll rationale). Measured headroom: worst substantive leaf
    ~3e-5, worst residue ~2e-8 of scale."""
    assert_grads_tolerance(want, got, rel_l2=rel_l2)


def assert_grads_tolerance(want, got, rel_l2=2e-2):
    """Per-leaf blended bound ``diff_L2 <= rel_l2 * |leaf| + rel_l2/200 *
    global_scale``: relative for substantive leaves, with an absolute floor
    so small-norm leaves (a bias whose gradient is mostly cancellation) and
    pure-residue leaves (structurally-zero gradients, O(1) relative spread)
    are judged against the gradient's global scale instead of their own
    noise. ``rel_l2=2e-2`` is the documented bf16-residual contract."""
    want_leaves = [(k, np.asarray(v, np.float64)) for k, v
                   in jax.tree_util.tree_leaves_with_path(want)]
    got_leaves = [np.asarray(v, np.float64)
                  for _, v in jax.tree_util.tree_leaves_with_path(got)]
    scale = max(np.linalg.norm(a) for _, a in want_leaves)
    for (key, a), b in zip(want_leaves, got_leaves):
        diff = np.linalg.norm(b - a)
        na = np.linalg.norm(a)
        bound = rel_l2 * na + rel_l2 / 200.0 * scale
        assert diff < bound, \
            f"{key}: diff {diff:.3e} > {bound:.3e} (|leaf| {na:.3e})"


# ---------------------------------------------------------------- fp32 exact

@pytest.mark.parametrize("policy", [False, True, "corr"])
def test_matches_autodiff_stacked_fp32(setup, policy):
    """Custom VJP == autodiff on the stacked-loss path, across the save
    policy's off / full / corr-only regimes (replay vs recompute bwd)."""
    variables, img1, img2, gt, valid = setup
    ref = create_model(RAFTStereoConfig(refinement_save_policy=policy))
    cus = create_model(RAFTStereoConfig(refinement_save_policy=policy,
                                        batched_scan_wgrad=True))
    f_ref = stacked_loss(ref, variables, img1, img2)
    f_cus = stacked_loss(cus, variables, img1, img2)
    l_ref, g_ref = jax.value_and_grad(f_ref)(variables["params"])
    l_cus, g_cus = jax.value_and_grad(f_cus)(variables["params"])
    np.testing.assert_allclose(float(l_cus), float(l_ref), rtol=1e-6)
    assert_grads_close(g_ref, g_cus)


@pytest.mark.parametrize("deferred", [True, False])
def test_matches_autodiff_fused_fp32(setup, deferred):
    """Custom VJP == autodiff on the fused-loss path, both the post-scan
    tile-layout (deferred) and in-scan variants; per-iteration error sums
    pinned tight."""
    variables, img1, img2, gt, valid = setup
    cfgs = dict(deferred_upsample=deferred, refinement_save_policy=False)
    ref = create_model(RAFTStereoConfig(**cfgs))
    cus = create_model(RAFTStereoConfig(batched_scan_wgrad=True, **cfgs))
    rest = {k: v for k, v in variables.items() if k != "params"}
    mask = loss_mask(gt, valid)
    err_ref, fin_ref = ref.apply(variables, img1, img2, iters=2,
                                 flow_gt=gt, loss_mask=mask)
    err_cus, fin_cus = cus.apply(variables, img1, img2, iters=2,
                                 flow_gt=gt, loss_mask=mask)
    np.testing.assert_allclose(np.asarray(err_cus), np.asarray(err_ref),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(fin_cus), np.asarray(fin_ref),
                               atol=1e-6)
    del rest
    g_ref = jax.grad(fused_loss(ref, variables, img1, img2, gt, valid))(
        variables["params"])
    g_cus = jax.grad(fused_loss(cus, variables, img1, img2, gt, valid))(
        variables["params"])
    assert_grads_close(g_ref, g_cus)


def test_matches_autodiff_no_remat(setup):
    """remat_refinement=False: the autodiff scan saves everything; the
    custom path recomputes — same gradients either way."""
    variables, img1, img2, gt, valid = setup
    ref = create_model(RAFTStereoConfig(remat_refinement=False))
    cus = create_model(RAFTStereoConfig(remat_refinement=False,
                                        batched_scan_wgrad=True))
    g_ref = jax.grad(stacked_loss(ref, variables, img1, img2))(
        variables["params"])
    g_cus = jax.grad(stacked_loss(cus, variables, img1, img2))(
        variables["params"])
    assert_grads_close(g_ref, g_cus)


def test_slow_fast_shared_backbone(setup):
    """The realtime preset's shape: slow_fast pre-iterations re-apply GRU
    levels on SHARED params — the batched wgrads of the pre32/pre16/main
    applications must sum into the same leaves."""
    import dataclasses

    from raft_stereo_tpu.config import realtime_config

    base = dataclasses.replace(realtime_config(), mixed_precision=False,
                               corr_implementation="reg")
    _, variables = init_model(jax.random.PRNGKey(0), base, SHAPE)
    rng = np.random.default_rng(3)
    img1 = jnp.asarray(rng.uniform(0, 255, SHAPE), jnp.float32)
    img2 = jnp.asarray(rng.uniform(0, 255, SHAPE), jnp.float32)
    ref = create_model(base)
    cus = create_model(dataclasses.replace(base, batched_scan_wgrad=True))
    f_ref = stacked_loss(ref, variables, img1, img2)
    f_cus = stacked_loss(cus, variables, img1, img2)
    l_ref, g_ref = jax.value_and_grad(f_ref)(variables["params"])
    l_cus, g_cus = jax.value_and_grad(f_cus)(variables["params"])
    np.testing.assert_allclose(float(l_cus), float(l_ref), rtol=1e-6)
    assert_grads_close(g_ref, g_cus)


# ------------------------------------------------------------ bf16 residuals

def test_bf16_residuals_forward_exact_grads_tolerance(setup):
    """residual_dtype='bfloat16' on the custom path: the FORWARD is exact
    (only saved copies are rounded — unlike the autodiff cast-through) and
    gradients sit within the documented bf16 tolerance of the fp32
    autodiff reference. The bound is per-leaf rel-L2 <= 1e-1 at 3
    random-init iterations: each iteration's backward restarts from a
    bf16-rounded carry/save, and the recurrence compounds those roundings
    (measured worst leaf ~6e-2 here; single-iteration roundings are
    ~1e-3)."""
    variables, img1, img2, gt, valid = setup
    ref = create_model(RAFTStereoConfig(refinement_save_policy=True))
    cus = create_model(RAFTStereoConfig(refinement_save_policy=True,
                                        batched_scan_wgrad=True,
                                        residual_dtype="bfloat16"))
    out_ref = ref.apply(variables, img1, img2, iters=3)
    out_cus = cus.apply(variables, img1, img2, iters=3)
    np.testing.assert_allclose(np.asarray(out_cus), np.asarray(out_ref),
                               atol=1e-6)
    g_ref = jax.grad(stacked_loss(ref, variables, img1, img2))(
        variables["params"])
    g_cus = jax.grad(stacked_loss(cus, variables, img1, img2))(
        variables["params"])
    assert_grads_tolerance(g_ref, g_cus, rel_l2=1e-1)


def test_bf16_residuals_autodiff_cast_through(setup):
    """residual_dtype on the AUTODIFF path narrows the tagged saves via a
    forward cast-through: with the policy engaged, ONE iteration sits
    within the documented per-iteration rounding tolerance (the recurrence
    amplifies roundings iteration-over-iteration at random init, so the
    multi-iteration contract is per-rounding, not end-to-end); with the
    policy off the knob must not touch the graph at all (bitwise-exact
    forward)."""
    variables, img1, img2, gt, valid = setup
    ref = create_model(RAFTStereoConfig(refinement_save_policy=True))
    lean = create_model(RAFTStereoConfig(refinement_save_policy=True,
                                         residual_dtype="bfloat16"))
    out_ref = ref.apply(variables, img1, img2, iters=1)
    out_lean = lean.apply(variables, img1, img2, iters=1)
    # one bf16 rounding on the saved zr/q/corr tensors -> near, not equal
    np.testing.assert_allclose(np.asarray(out_lean), np.asarray(out_ref),
                               atol=0.5)
    assert np.abs(np.asarray(out_lean) - np.asarray(out_ref)).max() > 0
    g_ref = jax.grad(stacked_loss(ref, variables, img1, img2, iters=1))(
        variables["params"])
    g_lean = jax.grad(stacked_loss(lean, variables, img1, img2, iters=1))(
        variables["params"])
    assert_grads_tolerance(g_ref, g_lean, rel_l2=0.15)

    base = create_model(RAFTStereoConfig(refinement_save_policy=False))
    off = create_model(RAFTStereoConfig(refinement_save_policy=False,
                                        residual_dtype="bfloat16"))
    out_base = base.apply(variables, img1, img2, iters=3)
    out_off = off.apply(variables, img1, img2, iters=3)
    np.testing.assert_array_equal(np.asarray(out_off), np.asarray(out_base))

    # Scoping: under the "corr" policy only corr_feats is kept, so only it
    # is rounded — the gate tags must NOT get the cast-through (were they
    # rounded too, the 'corr' and full-policy forwards would coincide).
    corr_lean = create_model(RAFTStereoConfig(refinement_save_policy="corr",
                                              residual_dtype="bfloat16"))
    out_corr = corr_lean.apply(variables, img1, img2, iters=2)
    out_full = lean.apply(variables, img1, img2, iters=2)
    out_exact = ref.apply(variables, img1, img2, iters=2)
    assert np.abs(np.asarray(out_corr) - np.asarray(out_exact)).max() > 0
    assert np.abs(np.asarray(out_corr) - np.asarray(out_full)).max() > 0


def test_policy_estimate_honors_residual_dtype():
    """bf16 residuals halve the save-policy size estimate for fp32-compute
    configs (the 'may re-admit the policy' lever)."""
    from raft_stereo_tpu.models.raft_stereo import (
        refinement_save_policy_fits)

    cfg = RAFTStereoConfig()
    it, h, w = 22, 80, 180
    # fp32 saves: b4 does not fit (test_training.py pins this); bf16
    # residuals re-admit it, matching the bf16-compute estimate.
    assert not refinement_save_policy_fits(cfg, it, 4, h, w, None)
    assert refinement_save_policy_fits(cfg, it, 4, h, w, None,
                                       residual_dtype="bfloat16")
    assert not refinement_save_policy_fits(cfg, it, 8, h, w, None,
                                           residual_dtype="bfloat16")


# ------------------------------------------------------------- integration

def test_train_step_runs_and_updates(setup):
    """make_train_step over the custom backward: finite metrics, params
    move, jit-compatible with donation."""
    from raft_stereo_tpu.config import TrainConfig
    from raft_stereo_tpu.training.optim import fetch_optimizer
    from raft_stereo_tpu.training.state import TrainState, make_train_step

    variables, img1, img2, gt, valid = setup
    cfg = RAFTStereoConfig(batched_scan_wgrad=True,
                           residual_dtype="bfloat16")
    model = create_model(cfg)
    tx = fetch_optimizer(TrainConfig(num_steps=10, batch_size=1))
    # deep-copy: the jitted step donates its state, and the module fixture's
    # variables must survive for later tests
    state = jax.tree.map(jnp.array, TrainState.create(variables, tx))
    batch = {"image1": img1, "image2": img2, "flow": gt, "valid": valid}
    step = jax.jit(make_train_step(model, tx, train_iters=2,
                                   fused_loss=True), donate_argnums=(0,))
    new_state, metrics = step(state, batch)
    assert int(new_state.step) == 1
    assert np.isfinite(float(metrics["loss"]))
    moved = any(bool(jnp.any(a != b)) for a, b in zip(
        jax.tree_util.tree_leaves(
            jax.device_get(new_state.params)),
        jax.tree_util.tree_leaves(variables["params"])))
    assert moved


def test_uninitialized_params_raise(setup):
    """Applying the custom path with variables missing the refinement
    subtree fails loudly, not with a silent shape error downstream."""
    variables, img1, img2, _, _ = setup
    cus = create_model(RAFTStereoConfig(batched_scan_wgrad=True))
    broken = {"params": {k: v for k, v in variables["params"].items()
                         if k != "refinement"},
              **{k: v for k, v in variables.items() if k != "params"}}
    with pytest.raises(Exception, match="refinement"):
        cus.apply(broken, img1, img2, iters=2)


# ------------------------------------------------------- structural evidence

def test_wgrads_hoisted_out_of_backward_scan(setup):
    """The acceptance-criterion structure, pinned at the jaxpr level
    THROUGH the shared graftlint rule (analysis/graph_rules.py
    ``wgrad-in-loop``): the custom path's backward scan body carries FEWER
    convolutions per iteration (3 GRU levels x (zr + q) = 6 weight-grad
    convs leave the loop body) and the outside-scan graph gains the
    batched contractions. Asserting through ``check_wgrad_hoisting`` means
    this test and ``cli lint`` cannot drift apart."""
    from raft_stereo_tpu.analysis.graph_rules import check_wgrad_hoisting
    from raft_stereo_tpu.obs.xla import conv_op_profile

    variables, img1, img2, gt, valid = setup
    profiles = {}
    for name, flag in (("autodiff", False), ("batched", True)):
        m = create_model(RAFTStereoConfig(refinement_save_policy=False,
                                          batched_scan_wgrad=flag))
        jaxpr = jax.make_jaxpr(
            jax.grad(stacked_loss(m, variables, img1, img2)))(
                variables["params"])
        profiles[name] = conv_op_profile(jaxpr)
    findings = check_wgrad_hoisting(profiles["autodiff"],
                                    profiles["batched"])
    assert findings == [], [f.message for f in findings]
    # the rule is live: feeding the autodiff profile as "batched" (nothing
    # hoisted) must fire it
    assert check_wgrad_hoisting(profiles["autodiff"],
                                profiles["autodiff"])


def test_op_counts_event_schema(tmp_path, setup):
    """The op_counts evidence event (schema v3) emits and lints clean."""
    import os
    import sys

    from raft_stereo_tpu.obs import Telemetry
    from raft_stereo_tpu.obs.xla import conv_op_profile, emit_op_counts

    variables, img1, img2, gt, valid = setup
    m = create_model(RAFTStereoConfig(batched_scan_wgrad=True))
    jaxpr = jax.make_jaxpr(
        jax.grad(stacked_loss(m, variables, img1, img2, iters=2)))(
            variables["params"])
    run_dir = str(tmp_path / "run")
    tel = Telemetry(run_dir, stall_deadline_s=None)
    rec = emit_op_counts(conv_op_profile(jaxpr), tel, source="test")
    tel.close()
    assert rec["conv_total"] > 0
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    import check_events
    assert check_events.check(run_dir) == []


# ------------------------------------------------------------------ sharded

@pytest.mark.slow  # full-model multi-device XLA-CPU compile, minutes
def test_shardmap_dp_matches_single_device_custom():
    """The shard_map DP step over the custom backward equals the
    single-device custom step (psum'd grads; custom_vjp composes with
    shard_map + donation)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from raft_stereo_tpu.config import TrainConfig
    from raft_stereo_tpu.parallel.data_parallel import (
        make_shardmap_train_step)
    from raft_stereo_tpu.parallel.mesh import make_mesh, replicated
    from raft_stereo_tpu.training.optim import fetch_optimizer
    from raft_stereo_tpu.training.state import TrainState, make_train_step

    cfg = RAFTStereoConfig(batched_scan_wgrad=True)
    tcfg = TrainConfig(num_steps=10, batch_size=4, lr=1e-4)
    model, variables = init_model(jax.random.PRNGKey(0), cfg, (1, 32, 48, 3))
    tx = fetch_optimizer(tcfg)
    state = TrainState.create(variables, tx)

    rng = np.random.default_rng(1)
    batch = {
        "image1": jnp.asarray(rng.uniform(0, 255, (4, 32, 48, 3)),
                              jnp.float32),
        "image2": jnp.asarray(rng.uniform(0, 255, (4, 32, 48, 3)),
                              jnp.float32),
        "flow": jnp.asarray(rng.uniform(-8, 0, (4, 32, 48, 1)), jnp.float32),
        "valid": jnp.ones((4, 32, 48), jnp.float32),
    }

    single = jax.jit(make_train_step(model, tx, train_iters=1,
                                     fused_loss=True))
    ref_state, ref_metrics = single(jax.tree.map(jnp.array, state), batch)

    mesh = make_mesh(4, 1, devices=jax.devices()[:4])
    with mesh:
        st = jax.device_put(jax.tree.map(jnp.array, state), replicated(mesh))
        sharded_batch = {k: jax.device_put(
            v, NamedSharding(mesh, P("data"))) for k, v in batch.items()}
        dp_step = make_shardmap_train_step(model, tx, 1, mesh,
                                           fused_loss=True)
        dp_state, dp_metrics = dp_step(st, sharded_batch)

    assert float(dp_metrics["loss"]) == pytest.approx(
        float(ref_metrics["loss"]), rel=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(ref_state.params),
                    jax.tree_util.tree_leaves(dp_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-5)
