"""Ring-sharded correlation vs the unsharded oracle, on the 8-device CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_stereo_tpu.ops.corr import corr_lookup, init_corr
from raft_stereo_tpu.ops.geometry import coords_grid
from raft_stereo_tpu.parallel.mesh import make_mesh
from raft_stereo_tpu.parallel.ring_corr import make_ring_lookup


@pytest.mark.parametrize("num_levels,radius", [(4, 4), (2, 3)])
def test_ring_matches_unsharded_alt(num_levels, radius):
    if jax.device_count() < 8:
        pytest.skip("needs 8 virtual devices")
    rng = np.random.default_rng(0)
    b, h, w, d = 2, 4, 64, 32  # 8 blocks of 8 columns
    f1 = jnp.asarray(rng.normal(size=(b, h, w, d)), jnp.float32)
    f2 = jnp.asarray(rng.normal(size=(b, h, w, d)), jnp.float32)
    coords = coords_grid(b, h, w) + jnp.asarray(
        rng.uniform(-6, 6, size=(b, h, w, 2)), jnp.float32)

    state = init_corr("alt", f1, f2, num_levels=num_levels, radius=radius)
    want = corr_lookup(state, coords)

    mesh = make_mesh(1, 8)
    with mesh:
        ring = jax.jit(make_ring_lookup(mesh, radius=radius,
                                        num_levels=num_levels))
        got = ring(f1, f2, coords[..., 0])

    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-4, rtol=1e-4)


def test_ring_rejects_unpoolable_shard():
    if jax.device_count() < 8:
        pytest.skip("needs 8 virtual devices")
    rng = np.random.default_rng(1)
    b, h, w, d = 1, 2, 32, 8  # blocks of 4 < 2^(4-1)
    f1 = jnp.asarray(rng.normal(size=(b, h, w, d)), jnp.float32)
    f2 = jnp.asarray(rng.normal(size=(b, h, w, d)), jnp.float32)
    coords = coords_grid(b, h, w)[..., 0]
    mesh = make_mesh(1, 8)
    with mesh:
        ring = make_ring_lookup(mesh, radius=4, num_levels=4)
        with pytest.raises(ValueError, match="divisible"):
            jax.jit(ring)(f1, f2, coords)


def test_distributed_helpers_single_process():
    """Multi-host helpers degrade correctly to one process."""
    from raft_stereo_tpu.parallel.distributed import (host_local_to_global,
                                                      initialize,
                                                      process_batch_slice)
    from raft_stereo_tpu.parallel.mesh import make_mesh

    initialize(num_processes=1)  # no-op
    assert process_batch_slice(8) == slice(0, 8)
    mesh = make_mesh(4, 2)
    batch = {"image1": np.zeros((4, 8, 16, 3), np.float32),
             "image2": np.zeros((4, 8, 16, 3), np.float32),
             "flow": np.zeros((4, 8, 16, 1), np.float32),
             "valid": np.ones((4, 8, 16), np.float32)}
    placed = host_local_to_global(mesh, batch)
    assert placed["image1"].shape == (4, 8, 16, 3)
    shardings = placed["image1"].sharding
    assert shardings.spec == jax.sharding.PartitionSpec("data", None, "seq", None)
