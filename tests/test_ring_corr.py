"""Ring-sharded correlation vs the unsharded oracle, on the 8-device CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

# Every case traces shard_map collectives through the full lookup (several
# compile-heavy 8-device XLA-CPU sessions — minutes of wall clock), so the
# module runs in the slow tier with the end-to-end train loop.
pytestmark = pytest.mark.slow

from raft_stereo_tpu.ops.corr import corr_lookup, init_corr
from raft_stereo_tpu.ops.geometry import coords_grid
from raft_stereo_tpu.parallel.mesh import make_mesh
from raft_stereo_tpu.parallel.ring_corr import make_ring_lookup


@pytest.mark.parametrize("num_levels,radius", [(4, 4), (2, 3)])
def test_ring_matches_unsharded_alt(num_levels, radius):
    if jax.device_count() < 8:
        pytest.skip("needs 8 virtual devices")
    rng = np.random.default_rng(0)
    b, h, w, d = 2, 4, 64, 32  # 8 blocks of 8 columns
    f1 = jnp.asarray(rng.normal(size=(b, h, w, d)), jnp.float32)
    f2 = jnp.asarray(rng.normal(size=(b, h, w, d)), jnp.float32)
    coords = coords_grid(b, h, w) + jnp.asarray(
        rng.uniform(-6, 6, size=(b, h, w, 2)), jnp.float32)

    state = init_corr("alt", f1, f2, num_levels=num_levels, radius=radius)
    want = corr_lookup(state, coords)

    mesh = make_mesh(1, 8)
    with mesh:
        ring = jax.jit(make_ring_lookup(mesh, radius=radius,
                                        num_levels=num_levels))
        got = ring(f1, f2, coords[..., 0])

    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-4, rtol=1e-4)


def test_ring_rejects_unpoolable_shard():
    if jax.device_count() < 8:
        pytest.skip("needs 8 virtual devices")
    rng = np.random.default_rng(1)
    b, h, w, d = 1, 2, 32, 8  # blocks of 4 < 2^(4-1)
    f1 = jnp.asarray(rng.normal(size=(b, h, w, d)), jnp.float32)
    f2 = jnp.asarray(rng.normal(size=(b, h, w, d)), jnp.float32)
    coords = coords_grid(b, h, w)[..., 0]
    mesh = make_mesh(1, 8)
    with mesh:
        ring = make_ring_lookup(mesh, radius=4, num_levels=4)
        with pytest.raises(ValueError, match="divisible"):
            jax.jit(ring)(f1, f2, coords)


def test_model_ring_end_to_end():
    """``--corr_implementation ring`` drives the FULL model at a
    Middlebury-F-scale width (2048 px -> 512 disparity columns at 1/4 res)
    on the 8-device CPU mesh, and matches the unsharded alt oracle."""
    if jax.device_count() < 8:
        pytest.skip("needs 8 virtual devices")
    from raft_stereo_tpu.config import RAFTStereoConfig
    from raft_stereo_tpu.models import create_model, init_model
    from raft_stereo_tpu.parallel.mesh import batch_sharding

    b, h, w = 1, 32, 2048
    cfg_ring = RAFTStereoConfig(corr_implementation="ring")
    cfg_alt = RAFTStereoConfig(corr_implementation="alt")
    # corr choice does not change the parameter tree: share the variables
    model_ring, variables = init_model(jax.random.PRNGKey(0), cfg_ring,
                                       (1, 32, 64, 3))
    model_alt = create_model(cfg_alt)

    rng = np.random.default_rng(2)
    img1 = jnp.asarray(rng.uniform(0, 255, (b, h, w, 3)), jnp.float32)
    img2 = jnp.asarray(rng.uniform(0, 255, (b, h, w, 3)), jnp.float32)

    want_low, want_up = model_alt.apply(variables, img1, img2, iters=2,
                                        test_mode=True)

    mesh = make_mesh(1, 8)
    with mesh:
        spec = batch_sharding(mesh)
        s1, s2 = jax.device_put(img1, spec), jax.device_put(img2, spec)
        fwd = jax.jit(lambda v, a, c: model_ring.apply(v, a, c, iters=2,
                                                       test_mode=True))
        # The ring must actually engage (not silently fall back to alt):
        # the lowering has to contain the ppermute collective.
        hlo = fwd.lower(variables, s1, s2).as_text()
        assert ("collective-permute" in hlo) or ("collective_permute" in hlo), \
            "ring lookup fell back to unsharded alt (no collective in HLO)"
        got_low, got_up = fwd(variables, s1, s2)

    np.testing.assert_allclose(np.asarray(got_low), np.asarray(want_low),
                               atol=2e-3, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(got_up), np.asarray(want_up),
                               atol=2e-3, rtol=1e-4)


def test_predictor_ring_matches_alt():
    """StereoPredictor with corr_implementation='ring' shards the width over
    all devices (and pads W so per-shard pooling stays local), matching the
    unsharded alt predictor."""
    if jax.device_count() < 8:
        pytest.skip("needs 8 virtual devices")
    from raft_stereo_tpu.config import RAFTStereoConfig
    from raft_stereo_tpu.inference import StereoPredictor
    from raft_stereo_tpu.models import init_model

    cfg_ring = RAFTStereoConfig(corr_implementation="ring")
    cfg_alt = RAFTStereoConfig(corr_implementation="alt")
    _, variables = init_model(jax.random.PRNGKey(1), cfg_ring, (1, 32, 64, 3))

    rng = np.random.default_rng(5)
    left = rng.uniform(0, 255, (1, 32, 500, 3)).astype(np.float32)
    right = rng.uniform(0, 255, (1, 32, 500, 3)).astype(np.float32)

    import math
    pred_ring = StereoPredictor(cfg_ring, variables, valid_iters=2)
    assert pred_ring._mesh is not None
    # lcm(pad_divis, factor * n_devices * 2^(levels-1))
    assert pred_ring._w_divis == math.lcm(32, 4 * jax.device_count() * 8)
    pred_alt = StereoPredictor(cfg_alt, variables, valid_iters=2)

    got = pred_ring(left, right)
    want = pred_alt(left, right)
    assert got.shape == (1, 32, 500, 1)
    np.testing.assert_allclose(got, want, atol=2e-3, rtol=1e-4)


def test_ring_backward_matches_alt():
    """Gradients flow through the ppermute ring identically to alt."""
    if jax.device_count() < 8:
        pytest.skip("needs 8 virtual devices")
    from raft_stereo_tpu.config import RAFTStereoConfig
    from raft_stereo_tpu.models import create_model, init_model
    from raft_stereo_tpu.parallel.mesh import batch_sharding

    b, h, w = 1, 16, 256
    cfg_ring = RAFTStereoConfig(corr_implementation="ring")
    cfg_alt = RAFTStereoConfig(corr_implementation="alt")
    model_ring, variables = init_model(jax.random.PRNGKey(0), cfg_ring,
                                       (1, 16, 64, 3))
    model_alt = create_model(cfg_alt)

    rng = np.random.default_rng(3)
    img1 = jnp.asarray(rng.uniform(0, 255, (b, h, w, 3)), jnp.float32)
    img2 = jnp.asarray(rng.uniform(0, 255, (b, h, w, 3)), jnp.float32)

    def loss(model):
        def f(params):
            preds = model.apply(
                {"params": params, **{k: v for k, v in variables.items()
                                      if k != "params"}},
                img1, img2, iters=1)
            return jnp.mean(jnp.abs(preds))
        return f

    want = jax.grad(loss(model_alt))(variables["params"])
    mesh = make_mesh(1, 8)
    with mesh:
        got = jax.jit(jax.grad(loss(model_ring)))(variables["params"])

    flat_w, _ = jax.tree_util.tree_flatten(want)
    flat_g, _ = jax.tree_util.tree_flatten(got)
    for gw, gg in zip(flat_w, flat_g):
        np.testing.assert_allclose(np.asarray(gg), np.asarray(gw),
                                   atol=1e-4, rtol=1e-3)


def test_distributed_helpers_single_process():
    """Multi-host helpers degrade correctly to one process."""
    from raft_stereo_tpu.parallel.distributed import (host_local_to_global,
                                                      initialize,
                                                      process_batch_slice)
    from raft_stereo_tpu.parallel.mesh import make_mesh

    initialize(num_processes=1)  # no-op
    assert process_batch_slice(8) == slice(0, 8)
    mesh = make_mesh(4, 2)
    batch = {"image1": np.zeros((4, 8, 16, 3), np.float32),
             "image2": np.zeros((4, 8, 16, 3), np.float32),
             "flow": np.zeros((4, 8, 16, 1), np.float32),
             "valid": np.ones((4, 8, 16), np.float32)}
    placed = host_local_to_global(mesh, batch)
    assert placed["image1"].shape == (4, 8, 16, 3)
    shardings = placed["image1"].sharding
    assert shardings.spec == jax.sharding.PartitionSpec("data", None, "seq", None)
