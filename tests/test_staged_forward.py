"""Staged forward (stage='encode' / 'refine') vs the monolithic apply.

The model's ``stage`` parameter exposes the forward as separately-jittable
pieces; 'full' must be exactly refine(encode(x)) — parameters, outputs and
gradients identical up to XLA scheduling. (This pins the API directly; the
split-compilation *training step* that once consumed it was deleted in r5
after its compile-service premise was falsified — see PERF.md.)
"""

import numpy as np

import jax
import jax.numpy as jnp

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.models import init_model

SHAPE = (1, 32, 48, 3)


def _data():
    rng = np.random.default_rng(11)
    return (jnp.asarray(rng.uniform(0, 255, SHAPE), jnp.float32),
            jnp.asarray(rng.uniform(0, 255, SHAPE), jnp.float32))


def test_staged_forward_matches_full():
    model, variables = init_model(jax.random.PRNGKey(0), RAFTStereoConfig(),
                                  SHAPE)
    img1, img2 = _data()
    full = model.apply(variables, img1, img2, iters=2)
    enc = model.apply(variables, img1, img2, stage="encode")
    staged = model.apply(variables, img1, img2, iters=2, stage="refine",
                         enc_outs=enc)
    np.testing.assert_allclose(np.asarray(staged), np.asarray(full),
                               atol=1e-6)


def test_staged_grads_match_full():
    model, variables = init_model(jax.random.PRNGKey(0), RAFTStereoConfig(),
                                  SHAPE)
    img1, img2 = _data()
    rest = {k: v for k, v in variables.items() if k != "params"}

    def loss_full(p):
        out = model.apply({"params": p, **rest}, img1, img2, iters=2)
        return jnp.mean(jnp.abs(out))

    def loss_staged(p):
        v = {"params": p, **rest}
        enc = model.apply(v, img1, img2, stage="encode")
        out = model.apply(v, img1, img2, iters=2, stage="refine",
                          enc_outs=enc)
        return jnp.mean(jnp.abs(out))

    g_full = jax.grad(loss_full)(variables["params"])
    g_staged = jax.grad(loss_staged)(variables["params"])
    for a, b in zip(jax.tree_util.tree_leaves(g_full),
                    jax.tree_util.tree_leaves(g_staged)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=1e-6)
