"""CLI flag-surface parity: every flag the reference's entry points declare
must be accepted by the matching CLI here (the argparse surface IS the
reference's public API — SURVEY §5 config row).

The reference scripts are parsed statically (regex over ``add_argument``
calls) so this works without importing torch-side modules.
"""

import os
import re

import pytest

from raft_stereo_tpu import cli

REFERENCE = "/root/reference"


def _reference_flags(script):
    path = os.path.join(REFERENCE, script)
    if not os.path.isfile(path):
        pytest.skip("reference not available")
    text = open(path).read()
    # Capture every long option in each add_argument call, including flags
    # declared short-option-first ("-l", "--left_imgs"). Option strings are
    # the *leading* quoted arguments of the call, so match the run of quoted
    # tokens right after "add_argument(" — robust to parentheses later in the
    # same call (a paren inside default=/choices= would truncate a naive
    # "[^)]*" span and silently drop flags declared after it).
    flags = set()
    for m in re.finditer(r"add_argument\(", text):
        lead = re.match(r"(?:\s*['\"]-{1,2}[\w-]+['\"]\s*,)*"
                        r"\s*['\"]-{1,2}[\w-]+['\"]",
                        text[m.end():])
        if lead:
            flags.update(re.findall(r"['\"](--[\w-]+)['\"]", lead.group(0)))
    return flags


def _our_flags(build_parser):
    parser = build_parser()
    flags = set()
    for action in parser._actions:
        flags.update(o for o in action.option_strings if o.startswith("--"))
    return flags


@pytest.mark.parametrize("script,builder", [
    ("train_stereo.py", cli.build_train_parser),
    ("evaluate_stereo.py", cli.build_eval_parser),
    ("demo.py", cli.build_demo_parser),
])
def test_reference_flags_accepted(script, builder):
    ref = _reference_flags(script)
    ours = _our_flags(builder)
    missing = sorted(ref - ours)
    assert not missing, (f"{script}: reference flags not accepted here: "
                        f"{missing}")
