"""scripts/rehearse_round.py: the driver-shaped rehearsal harness
(VERDICT r5 #8). The legs themselves shell out to bench.py /
__graft_entry__.py and are exercised on the TPU host; here the leg runner,
budget enforcement and artifact checks are pinned with stub commands."""

import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO / "scripts"))

import rehearse_round  # noqa: E402


def test_run_leg_success_with_artifact_check():
    rec = rehearse_round.run_leg(
        "bench", [sys.executable, "-c",
                  "print('noise'); print('{\"value\": 9.5}')"],
        timeout_s=60, check_stdout=rehearse_round.check_bench_stdout)
    assert rec["ok"] and rec["rc"] == 0 and rec["error"] is None
    assert rec["wall_s"] < 60


def test_run_leg_rc_failure():
    rec = rehearse_round.run_leg(
        "bench", [sys.executable, "-c", "raise SystemExit(3)"], timeout_s=60)
    assert not rec["ok"] and rec["error"] == "rc=3"


def test_run_leg_budget_timeout():
    rec = rehearse_round.run_leg(
        "slow", [sys.executable, "-c", "import time; time.sleep(30)"],
        timeout_s=1)
    assert not rec["ok"]
    assert "timeout" in str(rec["rc"])
    assert rec["wall_s"] < 10


def test_check_bench_stdout_rejects_bad_artifacts():
    check = rehearse_round.check_bench_stdout
    assert check('{"value": 9.58}\n') is None
    assert check("") is not None                       # no output at all
    assert check("all bench attempts failed\n")        # not JSON
    assert check(json.dumps({"metric": "x"}) + "\n")   # no numeric value


def test_check_event_artifacts_lints_event_logs_only(tmp_path):
    good = tmp_path / "run" / "events.jsonl"
    good.parent.mkdir()
    from raft_stereo_tpu.obs import Telemetry
    tel = Telemetry(str(good.parent))
    tel.run_start()
    tel.emit("run_end", steps=0, ok=True)
    tel.close()
    # a dated-JSON attempt log (no schema stamp) must be skipped, not flagged
    attempts = tmp_path / "attempts.jsonl"
    attempts.write_text('{"attempt": 0, "status": "ok"}\n')
    checked, errors = rehearse_round.check_event_artifacts(
        [str(good), str(attempts), str(tmp_path / "missing.jsonl")])
    assert str(good) in checked and str(attempts) in checked
    assert errors == []

    bad = tmp_path / "bad" / "events.jsonl"
    bad.parent.mkdir()
    bad.write_text('{"schema": 999, "ts": "t", "event": "step"}\n')
    _, errors = rehearse_round.check_event_artifacts([str(bad)])
    assert errors
