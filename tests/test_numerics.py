"""The numerics observatory (obs/numerics.py + schema v9):

* the fused tap-stats vector pinned against a NumPy oracle (min/max/
  absmean over finite values, nonfinite counts, bf16 saturation on the
  rail, bf16 underflow on subnormal flush), under jit with the sink armed
  inside the trace, including the duplicate-label ``#2`` suffixing;
* the model-level numerics aux: 8 ordered refinement-scan taps riding
  LAST in the output tuple without perturbing the flow, and the loud
  ValueError when requested off the test_mode path;
* the --no_numerics zero-overhead pin: numerics-off keeps the exact
  prior HLO, a same-seed double eval run emits an identical event stream,
  and a numerics-off train step carries no leaf_grad_norms;
* NaN provenance: a poisoned input attributes to the dataflow-earliest
  tap (corr_feats) at iteration 0 via taps_payload's first_nonfinite;
* the train side: make_train_step(numerics=True) metrics gain one L2
  norm per param leaf whose stacked global norm matches optax's;
* payload construction + the v9 numerics lint's negative cases, and the
  additive schema bump (v1-v8 records validate; a v8-stamped numerics
  record flags drift);
* eval emission on both paths (sequential and streaming: one record per
  dispatch) and serve emission (per-dispatch taps events, per-request
  output ranges, the slo output_range rollup, Prometheus gauges) with
  their off-by-default pins;
* the doctor's NONFINITE_ORIGIN > BF16_SATURATION > GRAD_EXPLOSION >
  NUMERICS_CLEAN verdict ladder on seeded logs;
* cli surfaces: build_numerics_parser defaults, the train/eval/serve
  flag plumbing, `cli numerics` text + --json - modes, and cli-drift
  rule v6 firing on a seeded orphan flag.
"""

import json
from pathlib import Path

import numpy as np
import pytest

import jax

from raft_stereo_tpu.config import RAFTStereoConfig, TrainConfig
from raft_stereo_tpu.eval.stream import StreamConfig, run_frames
from raft_stereo_tpu.inference import StereoPredictor
from raft_stereo_tpu.models import init_model
from raft_stereo_tpu.nn.gru import numerics_taps, record_numerics_tap
from raft_stereo_tpu.obs import Telemetry, read_events
from raft_stereo_tpu.obs import numerics as nm
from raft_stereo_tpu.obs.events import make_record, validate_record
from raft_stereo_tpu.obs.validate import (check_numerics_integrity,
                                          check_path)
from raft_stereo_tpu.training.optim import fetch_optimizer
from raft_stereo_tpu.training.state import TrainState, make_train_step

REPO = Path(__file__).resolve().parents[1]

H, W = 32, 64
ITERS = 3

#: the refinement-scan tap labels, in trace (dataflow) order, for the
#: tiny 3-level model — the provenance tie-break contract
TAP_LABELS = ("corr_feats", "gru32.zr", "gru32.q", "gru16.zr", "gru16.q",
              "gru08.zr", "gru08.q", "delta_flow")


@pytest.fixture(scope="module")
def tiny():
    cfg = RAFTStereoConfig(hidden_dims=(32, 32, 32))
    model, variables = init_model(jax.random.PRNGKey(0), cfg, (1, H, W, 3))
    return cfg, model, variables


@pytest.fixture(scope="module")
def pred_num(tiny):
    cfg, _, variables = tiny
    return StereoPredictor(cfg, variables, valid_iters=ITERS, numerics=True)


@pytest.fixture(scope="module")
def pred_off(tiny):
    cfg, _, variables = tiny
    return StereoPredictor(cfg, variables, valid_iters=ITERS)


def _frame(seed, h=H, w=W):
    rng = np.random.default_rng(seed)
    return {"image1": rng.integers(0, 255, (h, w, 3)).astype(np.float32),
            "image2": rng.integers(0, 255, (h, w, 3)).astype(np.float32)}


class _Data:
    def __init__(self, n=3, h=H, w=W, seed=0):
        self._samples = [_frame(seed + i, h, w) for i in range(n)]

    def __len__(self):
        return len(self._samples)

    def sample(self, i):
        return self._samples[i]


# ------------------------------------------------ tap stats vs the oracle

def test_tap_stats_pin_against_numpy_oracle():
    """The fused (6,) stats vector under jit, sink armed inside the
    trace (sink values are tracers — arming around a jit call would leak
    them)."""
    def fixture(x, y):
        with numerics_taps() as sink:
            record_numerics_tap(x, "a")
            record_numerics_tap(y, "a")      # duplicate label -> "a#2"
            return dict(sink)

    x = np.array([1.0, -2.0, np.nan, 0.5, np.inf, 0.0], np.float32)
    y = np.array([3.4e38, -3.4e38, 1e-41, 4.0], np.float32)
    out = {k: np.asarray(v) for k, v in jax.jit(fixture)(x, y).items()}
    assert sorted(out) == ["00:a", "01:a#2"]

    a = dict(zip(nm.STAT_FIELDS, out["00:a"]))
    assert a["min"] == -2.0 and a["max"] == 1.0
    # absmean: finite |x| summed, divided by the TOTAL element count
    assert a["absmean"] == pytest.approx((1.0 + 2.0 + 0.5) / 6)
    assert a["nonfinite"] == 2
    assert a["sat"] == 1          # inf trips the rail too
    assert a["underflow"] == 0

    b = dict(zip(nm.STAT_FIELDS, out["01:a#2"]))
    assert b["nonfinite"] == 0
    assert b["sat"] == 2          # +/-3.4e38 both at the bf16 rail
    assert b["underflow"] == 1    # 1e-41 flushes to bf16 zero
    assert b["min"] == np.float32(-3.4e38) and b["max"] == np.float32(3.4e38)

    # no armed sink: the tap is the identity and records nothing
    z = np.ones((2,), np.float32)
    assert record_numerics_tap(z, "idle") is z


def test_all_nonfinite_tensor_yields_inf_sentinels():
    def fixture(x):
        with numerics_taps() as sink:
            record_numerics_tap(x, "dead")
            return dict(sink)

    (key, stats), = jax.jit(fixture)(
        np.full((3,), np.nan, np.float32)).items()
    s = dict(zip(nm.STAT_FIELDS, np.asarray(stats)))
    assert key == "00:dead"
    assert np.isinf(s["min"]) and np.isinf(s["max"])     # host -> null
    assert s["nonfinite"] == 3
    # and taps_payload cleans the sentinels to null
    payload = nm.taps_payload("eval:t", {key: np.asarray(stats)[None]})
    series = payload["taps"]["dead"]
    assert series["min"] == [None] and series["max"] == [None]
    assert payload["first_nonfinite"] == {"tap": "dead", "iter": 0,
                                          "count": 3}


# --------------------------------------------------- model-level numerics

def test_model_numerics_aux_rides_last_without_perturbing_flow(tiny):
    cfg, model, variables = tiny
    s = _frame(7)
    im1, im2 = s["image1"][None], s["image2"][None]
    out = model.apply(variables, im1, im2, iters=ITERS, test_mode=True,
                      numerics=True)
    flow_lr, flow_up, taps = out
    labels = [nm.split_label(k)[1] for k in sorted(taps)]
    assert tuple(labels) == TAP_LABELS
    for k, v in taps.items():
        assert np.asarray(v).shape == (ITERS, len(nm.STAT_FIELDS)), k
    # sorted-key flattening preserves trace order via the 2-digit prefix
    orders = [nm.split_label(k)[0] for k in sorted(taps)]
    assert orders == list(range(len(TAP_LABELS)))
    # the aux rides along without perturbing the prediction
    _, up_plain = model.apply(variables, im1, im2, iters=ITERS,
                              test_mode=True)
    np.testing.assert_array_equal(np.asarray(up_plain), np.asarray(flow_up))
    # healthy inputs: no nonfinite anywhere, finite ranges everywhere
    payload = nm.taps_payload(
        "eval:t", {k: np.asarray(v) for k, v in taps.items()})
    assert payload["iters"] == ITERS
    assert payload["first_nonfinite"] is None
    assert payload["underflow_total"] >= 0


def test_numerics_off_test_mode_path_is_loud(tiny):
    _, model, variables = tiny
    s = _frame(3)
    with pytest.raises(ValueError, match="test_mode"):
        model.apply(variables, s["image1"][None], s["image2"][None],
                    iters=2, numerics=True)


def test_no_numerics_keeps_the_exact_prior_hlo(tiny):
    cfg, model, variables = tiny
    spec = jax.ShapeDtypeStruct((1, H, W, 3), np.float32)
    vspec = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype), variables)

    def run_off(v, a, b):
        return model.apply(v, a, b, iters=ITERS, test_mode=True,
                           numerics=False)

    def run_prior(v, a, b):
        return model.apply(v, a, b, iters=ITERS, test_mode=True)

    run_off.__name__ = run_prior.__name__ = "forward"
    text_off = jax.jit(run_off).lower(vspec, spec, spec).as_text()
    text_prior = jax.jit(run_prior).lower(vspec, spec, spec).as_text()
    assert text_off == text_prior


def test_nan_provenance_attributes_earliest_tap(tiny):
    """A NaN-poisoned input shows up at the dataflow-earliest tap
    (corr_feats) of iteration 0 — not at whichever downstream tap
    happens to sort first."""
    cfg, model, variables = tiny
    s = _frame(11)
    im1 = s["image1"][None].copy()
    im1[0, H // 2, W // 2, :] = np.nan
    _, _, taps = model.apply(variables, im1, s["image2"][None],
                             iters=ITERS, test_mode=True, numerics=True)
    payload = nm.taps_payload(
        "eval:things", {k: np.asarray(v) for k, v in taps.items()},
        bucket=f"{H}x{W}", frame=0)
    fn = payload["first_nonfinite"]
    assert fn is not None
    assert fn["tap"] == "corr_feats" and fn["iter"] == 0
    assert nm.alarm(payload) == "nonfinite_tap"
    # the record round-trips schema + referential lint
    rec = make_record("numerics", t=1.0, **payload)
    assert validate_record(rec) == []
    assert check_numerics_integrity([rec]) == []


# -------------------------------------------------------- the train side

def test_train_step_leaf_grad_norms(tiny):
    cfg, model, variables = tiny
    tx = fetch_optimizer(TrainConfig(num_steps=10, batch_size=2))
    state = TrainState.create(variables, tx)
    rng = np.random.default_rng(0)
    batch = {
        "image1": np.asarray(rng.uniform(0, 255, (2, H, W, 3)), np.float32),
        "image2": np.asarray(rng.uniform(0, 255, (2, H, W, 3)), np.float32),
        "flow": np.asarray(rng.uniform(-8, 0, (2, H, W, 1)), np.float32),
        "valid": np.ones((2, H, W), np.float32),
    }
    step = jax.jit(make_train_step(model, tx, train_iters=2, numerics=True))
    _, metrics = step(state, batch)
    norms = np.asarray(metrics["leaf_grad_norms"])
    names = nm.grad_leaf_names(variables["params"])
    assert norms.shape == (len(names),)
    assert np.all(np.isfinite(norms)) and np.all(norms >= 0)
    # the stacked per-leaf vector recomposes optax's global norm
    assert float(np.sqrt(np.sum(norms ** 2))) == pytest.approx(
        float(metrics["grad_norm"]), rel=1e-5)
    # numerics off: the metrics dict stays exactly as before
    step_off = jax.jit(make_train_step(model, tx, train_iters=2))
    _, m_off = step_off(state, batch)
    assert "leaf_grad_norms" not in m_off

    payload = nm.grad_payload(50, names, norms)
    assert payload["kind"] == "grad" and payload["step"] == 50
    assert len(payload["top"]) == nm.TOP_K
    assert nm.alarm(payload) is None      # healthy tiny step
    rec = make_record("numerics", t=1.0, **payload)
    assert validate_record(rec) == []
    assert check_numerics_integrity([rec]) == []


def test_top_leaves_ranks_nonfinite_first():
    names = ["a", "b", "c", "d"]
    norms = [1.0, float("nan"), 50.0, 2.0]
    top = nm.top_leaves(names, norms, k=3)
    assert top == [("b", None), ("c", 50.0), ("d", 2.0)]
    assert nm.alarm(nm.grad_payload(1, names, norms)) \
        == "nonfinite_grad_leaf"
    assert nm.alarm(nm.grad_payload(1, ["a"], [nm.GRAD_ALARM_NORM * 2])) \
        == "grad_explosion"


# ------------------------------------------- lint + schema v9 additivity

def _tap_rec(**kw):
    series = {f: [0.0, 0.0] for f in nm.STAT_FIELDS}
    base = dict(source="eval:t", kind="taps", iters=2,
                taps={"delta_flow": series}, sat_total=0,
                underflow_total=0, first_nonfinite=None)
    base.update(kw)
    return make_record("numerics", t=1.0, **base)


def test_numerics_lint_catches_malformed_records():
    assert check_numerics_integrity([_tap_rec()]) == []
    bad_len = {f: [0.0] for f in nm.STAT_FIELDS}
    assert any("not length iters" in e for e in check_numerics_integrity(
        [_tap_rec(taps={"delta_flow": bad_len})]))
    neg = {f: ([0.0, -1.0] if f == "sat" else [0.0, 0.0])
           for f in nm.STAT_FIELDS}
    assert any("negative sat" in e for e in check_numerics_integrity(
        [_tap_rec(taps={"delta_flow": neg})]))
    assert any("unknown tap" in e for e in check_numerics_integrity(
        [_tap_rec(first_nonfinite={"tap": "ghost", "iter": 0})]))
    assert any("outside" in e for e in check_numerics_integrity(
        [_tap_rec(first_nonfinite={"tap": "delta_flow", "iter": 5})]))
    assert any("not positive" in e for e in check_numerics_integrity(
        [_tap_rec(first_nonfinite={"tap": "delta_flow", "iter": 0})]))
    grad = make_record("numerics", t=1.0, source="train", kind="grad",
                       step=1, leaves=["a", "b"], grad_norm=[1.0])
    assert any("2 leaves vs 1" in e
               for e in check_numerics_integrity([grad]))
    assert any("numbers or null" in e for e in check_numerics_integrity(
        [dict(grad, grad_norm=["nan", 1.0])]))
    assert any("unknown kind" in e for e in check_numerics_integrity(
        [make_record("numerics", t=1.0, source="t", kind="mystery")]))


def test_schema_v9_additive_and_v8_stamp_is_drift():
    good = _tap_rec()
    assert validate_record(good) == []
    stale = dict(good, schema=8)
    assert any("introduced in schema 9" in e for e in validate_record(stale))
    # pre-v9 records validate against their own surface (additive bump)
    for ver, event, payload in [
            (5, "anomaly", dict(kind="nonfinite_grad")),
            (7, "span", dict(name="x", span_id="s1", trace_id="t1",
                             start_s=0.0, dur_s=0.1)),
            (8, "converge", dict(source="eval:t", iters=2, idx=[0, 1],
                                 residual=[1.0, 0.1]))]:
        rec = dict(make_record(event, t=1.0, **payload), schema=ver)
        assert validate_record(rec) == [], (ver, event)
    # the v9 request/slo output-range extras ride along additively
    slo = make_record("slo", t=1.0, p50_ms=1.0, p99_ms=2.0,
                      pairs_per_sec=3.0, in_flight=1,
                      output_range={"32x64": {"output_min_p05": -8.0,
                                              "output_max_p95": 0.1,
                                              "n": 4}})
    assert validate_record(slo) == []


# ----------------------------------------------- eval emission + the pin

def _eval_run(tmp_path, name, ds, predictor, stream):
    tel = Telemetry(str(tmp_path / name), stall_deadline_s=None)
    tel.run_start(config={"mode": "eval"})
    run_frames(predictor, ds, lambda *a: None, iters=ITERS,
               stream=stream, telemetry=tel, source="things")
    tel.emit("run_end", steps=tel.steps, ok=True)
    tel.close()
    return read_events(str(tmp_path / name / "events.jsonl"))


def test_eval_emits_numerics_both_paths(tmp_path, pred_num):
    ds = _Data(n=3)
    assert pred_num.numerics
    seq = _eval_run(tmp_path, "seq", ds, pred_num, stream=False)
    st = _eval_run(tmp_path, "stream", ds, pred_num,
                   stream=StreamConfig(enabled=True, window=2,
                                       microbatch=2))
    # one record per DISPATCH: 3 sequential singles, 2 microbatches
    for name, events, n in (("seq", seq, 3), ("stream", st, 2)):
        recs = [e for e in events if e.get("event") == "numerics"]
        assert len(recs) == n, name
        for r in recs:
            assert r["kind"] == "taps" and r["source"] == "eval:things"
            assert r["bucket"] == f"{H}x{W}" and "frame" in r
            assert tuple(r["taps"]) == TAP_LABELS
            assert r["iters"] == ITERS
            assert r["first_nonfinite"] is None
        assert check_path(str(tmp_path / name)) == []
    # the recorded run replays into the offline report
    doc = nm.build_report("stream", nm.load_records(str(tmp_path /
                                                        "stream")))
    assert doc["tap_events"] == 2 and doc["grad_events"] == 0
    assert [r["tap"] for r in doc["taps"]] == list(TAP_LABELS)
    assert doc["first_nonfinite"] == []


def test_no_numerics_double_run_is_byte_identical(tmp_path, pred_off):
    ds = _Data(n=2)
    ev1 = _eval_run(tmp_path, "off1", ds, pred_off, stream=False)
    ev2 = _eval_run(tmp_path, "off2", ds, pred_off, stream=False)

    def scrub(events):
        # the v10 clock_anchor is monotonic/wall by definition — drop it
        # like the other wall-clock fields
        return [{k: v for k, v in e.items()
                 if k not in ("t", "ts", "run", "path", "data_wait_s",
                              "dispatch_s", "fetch_s")}
                for e in events
                if e.get("event") not in ("compile", "clock_anchor")]

    assert scrub(ev1) == scrub(ev2)
    assert [e for e in ev1 if e.get("event") == "numerics"] == []
    assert pred_off.take_aux() is None


def test_predictor_numerics_aux_fetch(pred_num, pred_off):
    s = _frame(9)
    flow = pred_num(s["image1"][None], s["image2"][None], ITERS)
    assert flow.shape == (1, H, W, 1)
    aux = pred_num.take_aux()
    assert "numerics" in aux
    taps = aux["numerics"]
    assert [nm.split_label(k)[1] for k in sorted(taps)] == list(TAP_LABELS)
    assert pred_num.take_aux() is None          # popped once
    # numerics never perturbs the flow vs the off flavor
    np.testing.assert_array_equal(
        np.asarray(pred_off(s["image1"][None], s["image2"][None], ITERS)),
        np.asarray(flow))


# --------------------------------------- serve: taps events + output range

class _Fake5Cache:
    """Fake converge+numerics flavor: 5 outputs, the taps dict LAST."""

    def __len__(self):
        return 1

    def __call__(self, key, im1, im2, flow_init=None):
        b, h, w, _ = im1.shape
        deltas = np.linspace(1.0, 0.01, key.iters)[:, None].repeat(b, 1)
        stats = np.zeros((key.iters, len(nm.STAT_FIELDS)), np.float32)
        stats[:, 0], stats[:, 1], stats[:, 2] = -8.0, 7.0, 3.0
        taps = {f"{i:02d}:{label}": stats.copy()
                for i, label in enumerate(TAP_LABELS)}
        return (np.zeros((b, h // 4, w // 4, 2), np.float32),
                np.full((b, h, w, 1), 7.0, np.float32),
                np.ones((b,), bool),
                deltas.astype(np.float32),
                taps)


def _serve_run(tmp_path, name, cache, **cfg_kw):
    from raft_stereo_tpu.serve import ServeConfig, StereoServer
    tel = Telemetry(str(tmp_path / name), stall_deadline_s=None)
    tel.run_start(config={"mode": "serve"})
    stub_vars = {"params": {"w": np.zeros((1,), np.float32)}}
    server = StereoServer(
        RAFTStereoConfig(), stub_vars,
        ServeConfig(max_batch=2, window=2, default_iters=4, linger_s=0.0,
                    slo_every=1, **cfg_kw),
        telemetry=tel, autostart=False)
    server.cache = cache
    server.start()
    rng = np.random.default_rng(0)
    results = []
    for i in range(3):
        left = rng.random((H, W, 3)).astype(np.float32)
        right = rng.random((H, W, 3)).astype(np.float32)
        results.append(server.submit(left, right).result(timeout=60))
    server.request_drain()
    assert server.join(timeout=60)
    stats = server.stats()
    tel.emit("run_end", steps=3, ok=True)
    tel.close()
    return results, stats, read_events(str(tmp_path / name /
                                           "events.jsonl"))


def test_serve_numerics_events_and_output_range(tmp_path):
    from raft_stereo_tpu.serve.http import prometheus_metrics
    results, stats, events = _serve_run(tmp_path, "serve", _Fake5Cache(),
                                        numerics=True)
    assert all(r.ok for r in results)
    # converge still rides in slot 3 with the taps LAST
    assert all(r.final_residual == pytest.approx(0.01) for r in results)
    assert all(r.output_min == pytest.approx(7.0)
               and r.output_max == pytest.approx(7.0) for r in results)
    recs = [e for e in events if e.get("event") == "numerics"]
    assert recs and all(r["kind"] == "taps" for r in recs)
    for r in recs:
        assert r["source"].startswith("serve:")
        assert r["bucket"].count("x") == 1 and r["id"].startswith("r")
        assert tuple(r["taps"]) == TAP_LABELS
    reqs = [e for e in events if e.get("event") == "request"]
    assert all(r["output_min"] == pytest.approx(7.0) for r in reqs)
    assert all(r["output_max"] == pytest.approx(7.0) for r in reqs)
    (bucket, rng_), = stats["output_range"].items()
    assert rng_["n"] == 3
    assert rng_["output_min_p05"] == pytest.approx(7.0)
    assert rng_["output_max_p95"] == pytest.approx(7.0)
    assert check_path(str(tmp_path / "serve")) == []
    text = prometheus_metrics(stats)
    assert f'raft_serve_output_min_p05{{bucket="{bucket}"}}' in text
    assert f'raft_serve_output_max_p95{{bucket="{bucket}"}}' in text
    assert f'raft_serve_output_range_window_requests{{bucket="{bucket}"}}' \
        in text


def test_serve_numerics_off_emits_nothing_extra(tmp_path):
    from raft_stereo_tpu.serve.http import prometheus_metrics
    from test_converge import _Fake4Cache
    results, stats, events = _serve_run(tmp_path, "off", _Fake4Cache())
    assert all(r.ok and r.output_min is None and r.output_max is None
               for r in results)
    assert [e for e in events if e.get("event") == "numerics"] == []
    assert "output_range" not in stats
    assert all("output_min" not in e for e in events
               if e.get("event") == "request")
    assert "output_range" not in prometheus_metrics(stats)


def test_serve_numerics_defaults_off():
    from raft_stereo_tpu.serve import ServeConfig
    from raft_stereo_tpu.serve.cache import ExecutableCache
    assert ServeConfig().numerics is False      # serve opts IN
    stub = {"params": {"w": np.zeros((1,), np.float32)}}
    assert ExecutableCache(RAFTStereoConfig(), stub).numerics is False


# --------------------------------------------------- the doctor's ladder

def _numerics_log(tmp_path, name, payloads):
    run = tmp_path / name
    tel = Telemetry(str(run), stall_deadline_s=None)
    tel.run_start(config={})
    for p in payloads:
        tel.emit("numerics", **p)
    tel.emit("run_end", steps=len(payloads), ok=True)
    tel.close()
    return str(run)


def _sat_payload(sat=0.0, nonfinite=0.0, tap="gru08.q"):
    stats = np.zeros((2, len(nm.STAT_FIELDS)))
    stats[1, nm.STAT_FIELDS.index("sat")] = sat
    stats[1, nm.STAT_FIELDS.index("nonfinite")] = nonfinite
    return nm.taps_payload("eval:t", {f"03:{tap}": stats}, frame=0)


def test_doctor_numerics_verdict_ladder(tmp_path):
    from raft_stereo_tpu.obs.doctor import diagnose

    def verdict(run):
        return next(v for v in diagnose(run)["verdicts"]
                    if v["phase"] == "numerics")

    # a NaN origin trumps a saturation record in the same run
    run = _numerics_log(tmp_path, "nan", [
        _sat_payload(sat=5.0),
        _sat_payload(nonfinite=3.0, tap="corr_feats")])
    v = verdict(run)
    assert v["verdict"] == "NONFINITE_ORIGIN"
    assert any("corr_feats" in e for e in v["evidence"])
    assert any("cli numerics" in e for e in v["evidence"])

    # a null grad-norm leaf is also an origin
    names, norms = ["enc/w", "gru/w"], [1.0, float("nan")]
    run = _numerics_log(tmp_path, "grad_nan",
                        [nm.grad_payload(7, names, norms)])
    v = verdict(run)
    assert v["verdict"] == "NONFINITE_ORIGIN"
    assert any("gru/w" in e for e in v["evidence"])

    # saturation outranks a (finite) explosion
    run = _numerics_log(tmp_path, "sat", [
        _sat_payload(sat=5.0),
        nm.grad_payload(7, ["w"], [nm.GRAD_ALARM_NORM * 2])])
    v = verdict(run)
    assert v["verdict"] == "BF16_SATURATION"
    assert any("gru08.q" in e for e in v["evidence"])

    run = _numerics_log(tmp_path, "boom", [
        nm.grad_payload(7, ["w"], [nm.GRAD_ALARM_NORM * 2])])
    assert verdict(run)["verdict"] == "GRAD_EXPLOSION"

    run = _numerics_log(tmp_path, "clean", [_sat_payload()])
    assert verdict(run)["verdict"] == "NUMERICS_CLEAN"

    # no numerics events at all: the phase stays silent (pre-v9 runs)
    run = _numerics_log(tmp_path, "silent", [])
    assert all(v["phase"] != "numerics" for v in diagnose(run)["verdicts"])


# ------------------------------------------------- cli surfaces + drift

def test_build_numerics_parser_defaults():
    from raft_stereo_tpu.cli import build_numerics_parser
    args = build_numerics_parser().parse_args(["runs/x"])
    assert args.run_dir == "runs/x"
    assert args.top == 10 and args.json is None
    args = build_numerics_parser().parse_args(
        ["runs/x", "--top", "3", "--json", "-"])
    assert args.top == 3 and args.json == "-"


def test_train_eval_serve_parsers_carry_numerics_flags():
    from raft_stereo_tpu.cli import (build_eval_parser, build_serve_parser,
                                     build_train_parser, serve_config,
                                     train_config)
    args = build_train_parser().parse_args([])
    cfg = train_config(args)
    assert cfg.numerics is True and cfg.numerics_every == 50
    cfg = train_config(build_train_parser().parse_args(
        ["--no_numerics", "--numerics_every", "7"]))
    assert cfg.numerics is False and cfg.numerics_every == 7
    args = build_eval_parser().parse_args(["--dataset", "things"])
    assert not args.no_numerics
    assert serve_config(build_serve_parser().parse_args([])).numerics \
        is False
    assert serve_config(build_serve_parser().parse_args(
        ["--numerics"])).numerics is True


def test_cli_numerics_main_on_recorded_run(tmp_path, capsys):
    from raft_stereo_tpu.cli import main
    run = _numerics_log(tmp_path, "run", [
        _sat_payload(sat=2.0),
        nm.grad_payload(50, ["enc/w", "gru/w"], [1.0, 0.5])])
    assert main(["numerics", str(run), "--json", "-"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["grad_events"] == 1 and doc["tap_events"] == 1
    assert doc["saturation"][0]["tap"] == "gru08.q"
    assert main(["numerics", str(run)]) == 0
    text = capsys.readouterr().out
    assert "bf16 saturation leaderboard" in text and "gru08.q" in text
    # empty run dir: loud exit 1
    assert main(["numerics", str(tmp_path / "empty")]) == 1
    assert "no numerics records" in capsys.readouterr().err
    # the command is advertised
    assert main([]) == 2


def test_cli_drift_v6_fires_on_seeded_numerics_fixture(tmp_path):
    from raft_stereo_tpu.analysis.ast_rules import (
        RULE_VERSIONS, check_entry_surface_drift)

    assert RULE_VERSIONS["cli-drift"] == 10
    pkg = tmp_path / "raft_stereo_tpu"
    (pkg / "obs").mkdir(parents=True)
    (pkg / "cli.py").write_text(
        "def build_numerics_parser():\n"
        "    import argparse\n"
        "    p = argparse.ArgumentParser()\n"
        "    p.add_argument('run_dir')\n"
        "    p.add_argument('--top')\n"
        "    p.add_argument('--numerics_orphan')\n"
        "    return p\n")
    (pkg / "obs" / "numerics.py").write_text(
        "def main(args):\n"
        "    return (args.run_dir, args.top)\n")
    findings = check_entry_surface_drift(str(tmp_path))
    errors = [f for f in findings
              if f.rule == "cli-drift" and f.severity == "error"]
    assert {f.data.get("dest") for f in errors} == {"numerics_orphan"}
    assert {f.data.get("surface")
            for f in errors} == {"build_numerics_parser"}


# ------------------------------------------------- report helper pins

def test_report_helpers_pins():
    assert nm.split_label("03:gru16.zr") == (3, "gru16.zr")
    assert nm.split_label("bare")[1] == "bare"
    records = [
        dict(nm.grad_payload(0, ["a", "b"], [1.0, 2.0]), event="numerics"),
        dict(nm.grad_payload(100, ["a", "b"], [4.0, float("nan")]),
             event="numerics"),
    ]
    rows = nm.leaf_trend(records)
    assert rows[0]["leaf"] == "b" and rows[0]["nonfinite_steps"] == [100]
    assert rows[1]["leaf"] == "a"
    assert rows[1]["first"] == 1.0 and rows[1]["last"] == 4.0
    assert rows[1]["growth"] == pytest.approx(4.0)
    taps = [dict(_sat_payload(sat=3.0), event="numerics"),
            dict(_sat_payload(sat=1.0, tap="corr_feats"),
                 event="numerics")]
    trend = nm.tap_trend(taps)
    board = nm.saturation_leaderboard(trend)
    assert [r["tap"] for r in board] == ["gru08.q", "corr_feats"]
    nf = nm.first_nonfinite_report(
        [dict(_sat_payload(nonfinite=2.0, tap="corr_feats"),
              event="numerics"),
         dict(nm.grad_payload(9, ["w"], [float("inf")]),
              event="numerics")])
    assert nf[0]["tap"] == "corr_feats" and nf[0]["iter"] == 1
    assert nf[1]["kind"] == "grad" and nf[1]["step"] == 9
