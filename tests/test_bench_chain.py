"""Unit tests for bench.py's attempt-chain gating (no jax, no subprocesses).

The chain's gating policy decides whether the driver round reports a
number at all (r3 reported none); these tests pin its semantics with a
stubbed attempt runner.
"""

import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import bench


def _res(value, **extra):
    return {"metric": "sceneflow_train_throughput", "value": value, **extra}


def _runner(script):
    """script: list of (expected_tag, result_or_None); runner returns results
    in order and records which attempts actually ran."""
    calls = []

    def run(kw, timeout_s=None):
        tag = kw.get("_tag")
        calls.append(tag)
        for t, r in script:
            if t == tag:
                return dict(r) if r is not None else None
        raise AssertionError(f"unexpected attempt {tag}")

    return run, calls


def _chain(*atts):
    return [dict(kw={"_tag": t}, when=w, note=n, timeout_s=None)
            for t, w, n in atts]


def test_first_success_banks_and_skips_fallbacks():
    chain = _chain(("primary", "always", None),
                   ("banker", "unbanked", "banker"),
                   ("fallback", "unbanked", "fb"))
    run, calls = _runner([("primary", _res(10.0))])
    best = bench.run_chain(chain, run)
    assert best["value"] == 10.0
    assert calls == ["primary"]


def test_banker_runs_when_primary_fails():
    chain = _chain(("primary", "always", None),
                   ("banker", "unbanked", "banker"),
                   ("fallback", "unbanked", "fb"))
    run, calls = _runner([("primary", None), ("banker", _res(9.0))])
    best = bench.run_chain(chain, run)
    assert best["value"] == 9.0
    assert best["note"] == "banker"
    assert calls == ["primary", "banker"]


def test_below_par_control_runs_until_par():
    # banked 7.4 < par: the pinned-OFF control must still run and, being
    # faster, become the reported best (the kernel-regression insurance).
    chain = _chain(("primary", "always", None),
                   ("banker", "unbanked", "banker"),
                   ("control", "below_par", "unfused control"))
    run, calls = _runner([("primary", None), ("banker", _res(7.4)),
                          ("control", _res(9.3))])
    best = bench.run_chain(chain, run)
    assert best["value"] == 9.3
    assert calls == ["primary", "banker", "control"]


def test_below_par_control_skipped_at_par():
    at_par = bench._PAR_PAIRS_PER_SEC + 0.05
    chain = _chain(("primary", "always", None),
                   ("control", "below_par", "unfused control"))
    run, calls = _runner([("primary", _res(at_par))])
    best = bench.run_chain(chain, run)
    assert best["value"] == at_par
    assert calls == ["primary"]


def test_experiments_run_after_banked_and_best_wins():
    chain = _chain(("banker", "always", None),
                   ("exp", "always", "experiment"),
                   ("fallback", "unbanked", "fb"))
    run, calls = _runner([("banker", _res(9.4)), ("exp", _res(11.0))])
    best = bench.run_chain(chain, run)
    assert best["value"] == 11.0
    assert best["note"] == "experiment"
    assert calls == ["banker", "exp"]


def test_slower_experiment_does_not_displace_best():
    chain = _chain(("banker", "always", None),
                   ("exp", "always", "experiment"))
    run, calls = _runner([("banker", _res(9.4)), ("exp", _res(5.0))])
    best = bench.run_chain(chain, run)
    assert best["value"] == 9.4


def test_all_fail_returns_none():
    chain = _chain(("primary", "always", None),
                   ("banker", "unbanked", "banker"))
    run, calls = _runner([("primary", None), ("banker", None)])
    assert bench.run_chain(chain, run) is None


def test_deadline_stops_chain_but_keeps_best():
    chain = _chain(("banker", "always", None),
                   ("exp", "always", "experiment"))
    # Deadline expired before the chain starts: nothing may run. (An
    # explicit t_start/deadline_s pair — NOT t_start=0.0, which only means
    # "expired" when the host's monotonic clock exceeds _DEADLINE_S.)
    run, calls = _runner([])
    best = bench.run_chain(chain, run,
                           t_start=time.monotonic() - 10.0, deadline_s=1.0)
    assert best is None and calls == []
    # Deadline trips mid-chain, after the banker banked a result: the
    # remaining attempts are skipped but the banked best is still returned.
    inner, calls_mid = _runner([("banker", _res(9.4))])

    def slow_run(kw, timeout_s=None):
        result = inner(kw, timeout_s)
        time.sleep(0.05)
        return result

    best_mid = bench.run_chain(chain, slow_run, deadline_s=0.02)
    assert best_mid["value"] == 9.4
    assert calls_mid == ["banker"]
    # with a sane deadline everything runs
    run2, calls2 = _runner([("banker", _res(9.4)), ("exp", None)])
    best2 = bench.run_chain(chain, run2)
    assert best2["value"] == 9.4
    assert calls2 == ["banker", "exp"]


def test_real_chain_shape():
    """The production TPU chain: primary first with a tight timeout, the
    below-par-gated banker second (it must run even when a slow primary
    banked a number), the always-run scan-backward A/B third (r8 — banks
    whichever refinement backward is faster, with the banker as the
    pinned-off control), the always-run fused-corr A/B fourth (r18 —
    same control), then unbanked fallbacks only."""
    chain = bench._attempt_chain(True)
    assert chain[0]["when"] == "always" and chain[0]["timeout_s"]
    assert chain[1]["when"] == "below_par"
    assert chain[1]["kw"]["remat_encoders"] == "blocks_hires"
    # the scan custom-VJP A/B: always runs, banker schedule, lean stacks
    assert chain[2]["when"] == "always"
    assert chain[2]["kw"]["batched_scan_wgrad"] is True
    assert chain[2]["kw"]["residual_dtype"] == "bfloat16"
    assert chain[2]["kw"]["remat_encoders"] == "blocks_hires"
    # the control (banker) must run BEFORE the A/B so a custom-path
    # regression can never leave the round without the autodiff number
    assert not chain[1]["kw"].get("batched_scan_wgrad")
    # the fused-corr A/B (r18): always runs, banker schedule, memoryless
    # lookup — the banker row above is its reg control
    assert chain[3]["when"] == "always"
    assert chain[3]["kw"]["corr_implementation"] == "fused"
    assert chain[3]["kw"]["remat_encoders"] == "blocks_hires"
    assert not chain[3]["kw"].get("batched_scan_wgrad")
    # the proven full blocks-remat config backs up the banker, below-par
    # gated too (it must get its shot if the banker banks low or fails)
    assert chain[4]["when"] == "below_par"
    assert chain[4]["kw"]["remat_encoders"] == "blocks"
    # the r4-measured best schedule is on the primary, bankers, and A/Bs
    for att in chain[:5]:
        assert att["kw"]["remat_loss_tail"] is False
        assert att["kw"]["fold_enc_saves"] is False
        assert att["kw"]["upsample_tile_budget"] > 10 ** 9
    assert all(a["when"] == "unbanked" for a in chain[5:])
    # the split-step attempt is gone (helper-rejected at b8 in r3 AND r4)
    assert not any(a["kw"].get("split_step") for a in chain)
    # every attempt is the SceneFlow recipe family
    for a in chain:
        assert a["kw"]["train_iters"] == 22
