"""Data layer tests: codecs round-trip, augmentor invariants, datasets, loader."""

import json
import os

import numpy as np
import pytest

from raft_stereo_tpu.data import frame_utils
from raft_stereo_tpu.data.augment import (
    FlowAugmentor,
    PhotometricAugment,
    SparseFlowAugmentor,
)
from raft_stereo_tpu.data.datasets import SceneFlow, StereoDataset
from raft_stereo_tpu.data.loader import Loader


# ------------------------------------------------------------------- codecs

def test_pfm_roundtrip(tmp_path):
    arr = np.random.default_rng(0).normal(size=(13, 17)).astype(np.float32)
    path = str(tmp_path / "x.pfm")
    frame_utils.write_pfm(path, arr)
    out = frame_utils.read_pfm(path)
    np.testing.assert_array_equal(out, arr)


def test_pfm_rejects_garbage(tmp_path):
    path = str(tmp_path / "bad.pfm")
    with open(path, "wb") as f:
        f.write(b"JUNK\n1 1\n-1\n\x00\x00\x00\x00")
    with pytest.raises(ValueError):
        frame_utils.read_pfm(path)


def test_flo_roundtrip(tmp_path):
    flow = np.random.default_rng(1).normal(size=(7, 9, 2)).astype(np.float32)
    path = str(tmp_path / "x.flo")
    frame_utils.write_flo(path, flow)
    np.testing.assert_array_equal(frame_utils.read_flo(path), flow)


def test_kitti_disp_roundtrip(tmp_path):
    import cv2

    disp = np.zeros((5, 6), np.float32)
    disp[2, 3] = 42.5
    path = str(tmp_path / "d.png")
    cv2.imwrite(path, (disp * 256).astype(np.uint16))
    out, valid = frame_utils.read_disp_kitti(path)
    assert out[2, 3] == pytest.approx(42.5)
    assert valid[2, 3] and not valid[0, 0]


def test_kitti_flow_roundtrip(tmp_path):
    flow = np.random.default_rng(2).uniform(-64, 64, (5, 6, 2)).astype(np.float32)
    flow = np.round(flow * 64) / 64  # representable at 1/64 px
    path = str(tmp_path / "f.png")
    frame_utils.write_flow_kitti(path, flow)
    out, valid = frame_utils.read_flow_kitti(path)
    np.testing.assert_allclose(out, flow, atol=1 / 64)
    assert valid.all()


def test_sintel_disp_decode(tmp_path):
    # d = R*4 + G/64 + B/16384
    rgb = np.zeros((4, 5, 3), np.uint8)
    rgb[1, 2] = (10, 32, 0)  # 40 + 0.5
    (tmp_path / "disparities").mkdir()
    (tmp_path / "occlusions").mkdir()
    from PIL import Image

    Image.fromarray(rgb).save(tmp_path / "disparities" / "frame_0.png")
    occ = np.zeros((4, 5), np.uint8)
    occ[0, 0] = 255
    Image.fromarray(occ).save(tmp_path / "occlusions" / "frame_0.png")
    disp, valid = frame_utils.read_disp_sintel(
        str(tmp_path / "disparities" / "frame_0.png"))
    assert disp[1, 2] == pytest.approx(40.5)
    assert valid[1, 2]
    assert not valid[0, 0]  # occluded


def test_falling_things_decode(tmp_path):
    from PIL import Image

    depth = np.full((3, 4), 3000, np.uint16)
    Image.fromarray(depth).save(tmp_path / "left.depth.png")
    with open(tmp_path / "_camera_settings.json", "w") as f:
        json.dump({"camera_settings":
                   [{"intrinsic_settings": {"fx": 768.0}}]}, f)
    disp, valid = frame_utils.read_disp_falling_things(
        str(tmp_path / "left.depth.png"))
    assert disp[0, 0] == pytest.approx(768.0 * 600 / 3000)
    assert valid.all()


def test_tartanair_decode(tmp_path):
    depth = np.full((3, 4), 16.0, np.float32)
    np.save(tmp_path / "left_depth.npy", depth)
    disp, valid = frame_utils.read_disp_tartanair(
        str(tmp_path / "left_depth.npy"))
    assert disp[0, 0] == pytest.approx(5.0)


def test_middlebury_decode(tmp_path):
    disp = np.random.default_rng(3).uniform(1, 50, (6, 8)).astype(np.float32)
    frame_utils.write_pfm(str(tmp_path / "disp0GT.pfm"), disp)
    from PIL import Image

    mask = np.full((6, 8), 255, np.uint8)
    mask[0, 0] = 128
    Image.fromarray(mask).save(tmp_path / "mask0nocc.png")
    out, valid = frame_utils.read_disp_middlebury(str(tmp_path / "disp0GT.pfm"))
    np.testing.assert_allclose(out, disp, rtol=1e-6)
    assert not valid[0, 0] and valid[1, 1]


# ------------------------------------------------------------------- augment

def test_photometric_preserves_shape_dtype():
    rng = np.random.default_rng(0)
    img = rng.integers(0, 255, (40, 50, 3), dtype=np.uint8)
    out = PhotometricAugment()(img, rng)
    assert out.shape == img.shape and out.dtype == np.uint8


def test_flow_augmentor_static_output_shape():
    rng = np.random.default_rng(0)
    img1 = rng.integers(0, 255, (200, 300, 3), dtype=np.uint8)
    img2 = rng.integers(0, 255, (200, 300, 3), dtype=np.uint8)
    flow = rng.normal(size=(200, 300, 2)).astype(np.float32)
    aug = FlowAugmentor(crop_size=(96, 128), yjitter=True)
    for _ in range(5):
        o1, o2, of = aug(img1, img2, flow, rng)
        assert o1.shape == (96, 128, 3)
        assert o2.shape == (96, 128, 3)
        assert of.shape == (96, 128, 2)


def test_flow_augmentor_deterministic():
    img1 = np.random.default_rng(7).integers(
        0, 255, (150, 200, 3), dtype=np.uint8)
    img2 = img1.copy()
    flow = np.ones((150, 200, 2), np.float32)
    aug = FlowAugmentor(crop_size=(64, 96))
    a = aug(img1, img2, flow, np.random.default_rng(42))
    b = aug(img1, img2, flow, np.random.default_rng(42))
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_flow_augmentor_scales_flow_values():
    rng = np.random.default_rng(1)
    img = rng.integers(0, 255, (100, 150, 3), dtype=np.uint8)
    flow = np.full((100, 150, 2), 4.0, np.float32)
    aug = FlowAugmentor(crop_size=(64, 96), min_scale=1.0, max_scale=1.0)
    aug.stretch_prob = 0.0
    _, _, of = aug(img, img.copy(), flow, rng)
    np.testing.assert_allclose(of[..., 0], 8.0, rtol=1e-5)  # 2**1 scale


def test_sparse_augmentor_shapes_and_valid():
    rng = np.random.default_rng(0)
    img1 = rng.integers(0, 255, (200, 300, 3), dtype=np.uint8)
    img2 = rng.integers(0, 255, (200, 300, 3), dtype=np.uint8)
    flow = rng.normal(size=(200, 300, 2)).astype(np.float32)
    valid = (rng.random((200, 300)) > 0.5).astype(np.float32)
    aug = SparseFlowAugmentor(crop_size=(96, 128))
    o1, o2, of, ov = aug(img1, img2, flow, valid, rng)
    assert o1.shape == (96, 128, 3) and of.shape == (96, 128, 2)
    assert ov.shape == (96, 128)
    assert set(np.unique(ov)).issubset({0, 1})


def test_sparse_resize_scatters_scaled_values():
    flow = np.zeros((10, 12, 2), np.float32)
    valid = np.zeros((10, 12), np.float32)
    flow[5, 6] = (-3.0, 0.0)
    valid[5, 6] = 1
    out_flow, out_valid = SparseFlowAugmentor.resize_sparse_flow_map(
        flow, valid, fx=2.0, fy=2.0)
    assert out_flow.shape == (20, 24, 2)
    assert out_valid[10, 12] == 1
    np.testing.assert_allclose(out_flow[10, 12], (-6.0, 0.0))
    assert out_valid.sum() == 1


# ------------------------------------------------------------------- datasets

def _make_sceneflow_tree(root, n=3, h=96, w=128):
    """Synthetic FlyingThings3D layout with matching PFM disparities."""
    from PIL import Image

    rng = np.random.default_rng(0)
    base = root / "FlyingThings3D"
    for i in range(n):
        for side in ("left", "right"):
            d = base / "frames_cleanpass" / "TRAIN" / "A" / f"{i:04d}" / side
            d.mkdir(parents=True, exist_ok=True)
            img = rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
            Image.fromarray(img).save(d / "0006.png")
        dd = base / "disparity" / "TRAIN" / "A" / f"{i:04d}" / "left"
        dd.mkdir(parents=True, exist_ok=True)
        disp = rng.uniform(1, 30, (h, w)).astype(np.float32)
        frame_utils.write_pfm(str(dd / "0006.pfm"), disp)
    return root


def test_sceneflow_dataset_sample(tmp_path):
    _make_sceneflow_tree(tmp_path)
    ds = SceneFlow(aug_params={"crop_size": (64, 96)}, root=str(tmp_path))
    assert len(ds) == 3
    s = ds.sample(0, np.random.default_rng(0))
    assert s["image1"].shape == (64, 96, 3)
    assert s["flow"].shape == (64, 96, 1)
    assert s["valid"].shape == (64, 96)
    assert (s["flow"] <= 0).all()  # flow = -disparity


def test_dataset_mul_add_composition(tmp_path):
    _make_sceneflow_tree(tmp_path)
    a = SceneFlow(aug_params=None, root=str(tmp_path))
    combined = (a * 2) + (a * 3)
    assert len(combined) == 15
    s = combined.sample(14, np.random.default_rng(0))
    assert s["image1"].shape[-1] == 3


def test_dataset_unaugmented_valid_mask(tmp_path):
    _make_sceneflow_tree(tmp_path)
    ds = SceneFlow(aug_params=None, root=str(tmp_path))
    s = ds.sample(1, np.random.default_rng(0))
    assert s["valid"].all()  # all synthetic disparities < 512


# ------------------------------------------------------------------- loader

def test_loader_batches_and_determinism(tmp_path):
    _make_sceneflow_tree(tmp_path, n=5)
    ds = SceneFlow(aug_params={"crop_size": (32, 48)}, root=str(tmp_path))
    loader_a = Loader(ds, batch_size=2, seed=3, num_workers=2)
    batches_a = list(loader_a)
    assert len(batches_a) == 2  # drop_last: 5 // 2
    for b in batches_a:
        assert b["image1"].shape == (2, 32, 48, 3)
        assert b["flow"].shape == (2, 32, 48, 1)

    loader_b = Loader(ds, batch_size=2, seed=3, num_workers=4)
    batches_b = list(loader_b)
    # determinism must not depend on worker count
    for ba, bb in zip(batches_a, batches_b):
        for k in ba:
            np.testing.assert_array_equal(ba[k], bb[k])


def test_loader_mid_epoch_resume_exact(tmp_path):
    """start_batch resumes inside an epoch EXACTLY: the skipped-ahead stream
    equals the uninterrupted run's tail, and the following epoch is
    untouched (trainer resume path, training/trainer.py)."""
    _make_sceneflow_tree(tmp_path, n=6)
    ds = SceneFlow(aug_params={"crop_size": (32, 48)}, root=str(tmp_path))
    continuous = Loader(ds, batch_size=2, seed=7, num_workers=2)
    epoch0 = list(continuous)           # 3 batches
    epoch1 = list(continuous)

    resumed = Loader(ds, batch_size=2, seed=7, num_workers=2)
    resumed.epoch = 0
    resumed.start_batch = 2             # as if restored at global step 2
    tail = list(resumed)
    assert len(tail) == 1
    for k in epoch0[2]:
        np.testing.assert_array_equal(tail[0][k], epoch0[2][k])
    # start_batch is consume-once: the next epoch is complete and identical
    nxt = list(resumed)
    assert len(nxt) == 3
    for ba, bb in zip(nxt, epoch1):
        for k in ba:
            np.testing.assert_array_equal(ba[k], bb[k])


def test_loader_epochs_differ(tmp_path):
    _make_sceneflow_tree(tmp_path, n=4)
    ds = SceneFlow(aug_params={"crop_size": (32, 48)}, root=str(tmp_path))
    loader = Loader(ds, batch_size=4, seed=0, num_workers=2)
    e0 = next(iter(loader))
    e1 = next(iter(loader))
    assert not np.array_equal(e0["image1"], e1["image1"])


def test_sparse_flip_keeps_valid_aligned():
    """'v' flip must move the sparse valid mask together with the flow (a fix
    over the reference, which leaves valid unflipped)."""
    from raft_stereo_tpu.data.augment import SparseFlowAugmentor

    aug = SparseFlowAugmentor(crop_size=(32, 48), do_flip="v")
    aug.spatial_aug_prob = -1.0  # disable resize
    aug.v_flip_prob = 1.1        # force the flip
    rng = np.random.default_rng(0)
    h, w = 40, 56
    img = rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
    flow = np.zeros((h, w, 2), np.float32)
    valid = np.zeros((h, w), np.float32)
    flow[5, 7] = (-3.0, 0.0)
    valid[5, 7] = 1.0
    _, _, flow_a, valid_a = aug(img, img, flow, valid, rng)
    # wherever valid survived the crop, flow must carry the flipped value
    ys, xs = np.nonzero(valid_a)
    for y, x in zip(ys, xs):
        assert flow_a[y, x, 0] == -3.0
        assert flow_a[y, x, 1] == 0.0


def test_extras_utilities():
    """Dead-code-parity utilities (SURVEY components 6/8) work."""
    from raft_stereo_tpu.utils.extras import (forward_interpolate, gauss_blur,
                                              transfer_color)
    rng = np.random.default_rng(0)
    a = rng.integers(0, 255, (16, 20, 3), dtype=np.uint8)
    b = rng.integers(0, 255, (16, 20, 3), dtype=np.uint8)
    out = transfer_color(a, b)
    assert out.shape == a.shape and np.isfinite(out).all()

    flow = rng.normal(scale=2.0, size=(8, 10, 2)).astype(np.float32)
    warped = forward_interpolate(flow)
    assert warped.shape == flow.shape and np.isfinite(warped).all()
    chw = forward_interpolate(flow.transpose(2, 0, 1))
    assert chw.shape == (2, 8, 10)

    img = rng.normal(size=(12, 14, 3)).astype(np.float32)
    assert gauss_blur(img).shape == img.shape


def test_visualize_geometry():
    """disparity->depth->cloud round trip (SURVEY component 12)."""
    from raft_stereo_tpu.visualize import (CameraIntrinsics, depth_to_cloud,
                                           disparity_to_depth)
    cam = CameraIntrinsics(fx=100.0, fy=100.0, cx=10.0, cy=8.0, baseline=0.12)
    disp = np.full((16, 20), 6.0, np.float32)
    depth = disparity_to_depth(disp, cam)
    np.testing.assert_allclose(depth, 100.0 * 0.12 / 6.0)
    pts, cols = depth_to_cloud(depth, cam,
                               color=np.zeros((16, 20, 3), np.uint8))
    assert pts.shape[1] == 3 and len(pts) == len(cols) == 16 * 20
    # pixel at (cx, cy) projects to the optical axis
    pose = np.eye(4); pose[:3, 3] = [1.0, 2.0, 3.0]
    pts_w, _ = depth_to_cloud(depth, cam, pose=pose)
    np.testing.assert_allclose(pts_w.mean(0) - pts.mean(0), [1.0, 2.0, 3.0],
                               atol=1e-5)
