"""Two-process ``jax.distributed`` test of the multi-host layer.

Spawns two REAL processes (each with 2 virtual CPU devices, gloo CPU
collectives) that join one jax.distributed job and drive
``parallel/distributed.py`` end-to-end: global mesh over 4 devices,
per-process batch slicing, ``host_local_to_global`` assembly, and a
cross-process ``psum`` through ``shard_map`` — the same collective layout a
multi-host TPU job uses over DCN (SURVEY §5 comm-backend row).
"""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import sys
import jax
jax.config.update('jax_platforms', 'cpu')
jax.config.update('jax_cpu_collectives_implementation', 'gloo')
pid = int(sys.argv[1])
port = sys.argv[2]
jax.distributed.initialize(coordinator_address=f'127.0.0.1:{port}',
                           num_processes=2, process_id=pid)
sys.path.insert(0, sys.argv[3])

import numpy as np
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from raft_stereo_tpu.parallel.distributed import (global_mesh,
                                                  host_local_to_global,
                                                  process_batch_slice)
from raft_stereo_tpu.parallel.mesh import DATA_AXIS

assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 4, jax.devices()

mesh = global_mesh(4, 1)

# Each process loads ONLY its slice of a deterministic global batch.
gb = 8
h, w = 4, 8
full = {
    "image1": np.arange(gb * h * w * 3, dtype=np.float32).reshape(gb, h, w, 3),
    "image2": np.arange(gb * h * w * 3, dtype=np.float32).reshape(gb, h, w, 3) + 1,
    "flow": np.arange(gb * h * w, dtype=np.float32).reshape(gb, h, w, 1),
    "valid": np.ones((gb, h, w), np.float32),
}
sl = process_batch_slice(gb)
assert sl == slice(pid * 4, pid * 4 + 4), sl
local = {k: v[sl] for k, v in full.items()}

placed = host_local_to_global(mesh, local)
for k, v in placed.items():
    assert v.shape == full[k].shape, (k, v.shape)

# 1) content check: replicate each array and compare against the full batch
# (the replication itself is a cross-process all-gather).
for k in ("image1", "flow"):
    gathered = jax.jit(lambda x: x,
                       out_shardings=NamedSharding(mesh, P()))(placed[k])
    np.testing.assert_array_equal(np.asarray(gathered), full[k])

# 2) collective check: explicit psum over the data axis through shard_map,
# crossing the process boundary.
from jax import shard_map
def local_sum(x):
    return jax.lax.psum(jnp.sum(x), DATA_AXIS)
total = shard_map(local_sum, mesh=mesh,
                  in_specs=P(DATA_AXIS),
                  out_specs=P(),
                  check_vma=False)(placed["image1"])
np.testing.assert_allclose(np.asarray(total), full["image1"].sum(), rtol=1e-6)

print(f"proc {pid} DIST-OK", flush=True)
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_host_local_to_global(tmp_path):
    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)

    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(i), str(port), REPO],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out}"
        assert f"proc {i} DIST-OK" in out, f"proc {i} output:\n{out}"
