import numpy as np
import jax.numpy as jnp
import pytest

from raft_stereo_tpu.ops.sampler import gather_window_2d, linear_sample_1d, window_taps


class TestLinearSample1D:
    def test_exact_integer_coords(self):
        v = jnp.asarray([[10.0, 20.0, 30.0, 40.0]])
        x = jnp.asarray([[0.0, 1.0, 3.0]])
        out = linear_sample_1d(v, x)
        np.testing.assert_allclose(out, [[10.0, 20.0, 40.0]])

    def test_fractional_interp(self):
        v = jnp.asarray([[0.0, 10.0, 20.0]])
        x = jnp.asarray([[0.5, 1.25]])
        out = linear_sample_1d(v, x)
        np.testing.assert_allclose(out, [[5.0, 12.5]], rtol=1e-6)

    def test_zero_outside_range(self):
        """grid_sample(padding_mode='zeros') semantics: OOB taps read 0."""
        v = jnp.asarray([[10.0, 20.0]])
        x = jnp.asarray([[-1.0, -0.5, 1.5, 2.0, 5.0]])
        out = linear_sample_1d(v, x)
        # -0.5: 0.5*v[-1](=0) + 0.5*v[0] = 5 ; 1.5: 0.5*v[1] + 0.5*v[2](=0) = 10
        np.testing.assert_allclose(out, [[0.0, 5.0, 10.0, 0.0, 0.0]], rtol=1e-6)

    def test_edge_coordinate_no_bleed(self):
        """x == W-1 must return v[W-1] exactly (weight-0 OOB neighbor)."""
        v = jnp.asarray([[1.0, 2.0, 7.0]])
        out = linear_sample_1d(v, jnp.asarray([[2.0]]))
        np.testing.assert_allclose(out, [[7.0]])

    def test_matches_torch_grid_sample(self):
        """Oracle check against grid_sample(align_corners=True, zeros padding),
        the exact operator behind the reference's bilinear_sampler
        (core/utils/utils.py:59-74) on the (N,1,1,W) collapsed corr volume."""
        torch = pytest.importorskip("torch")
        rng = np.random.default_rng(0)
        n, w, k = 6, 37, 9
        vals = rng.standard_normal((n, w)).astype(np.float32)
        # include in-range, boundary and out-of-range coordinates
        x = rng.uniform(-3.0, w + 2.0, size=(n, k)).astype(np.float32)

        img = torch.from_numpy(vals).view(n, 1, 1, w)
        xg = 2 * torch.from_numpy(x) / (w - 1) - 1
        grid = torch.stack([xg, torch.zeros_like(xg)], dim=-1).view(n, k, 1, 2)
        want = torch.nn.functional.grid_sample(
            img, grid, align_corners=True).view(n, k).numpy()

        got = np.asarray(linear_sample_1d(jnp.asarray(vals), jnp.asarray(x)))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


class TestWindowTaps:
    def test_ascending_offsets(self):
        taps = window_taps(jnp.asarray([5.0]), radius=2)
        np.testing.assert_allclose(taps, [[3.0, 4.0, 5.0, 6.0, 7.0]])


class TestGatherWindow2D:
    def test_matches_manual(self):
        rng = np.random.default_rng(1)
        b, h, w, d = 2, 3, 11, 4
        vals = rng.standard_normal((b, h, w, d)).astype(np.float32)
        x = rng.uniform(-1.0, w, size=(b, h, 5, 3)).astype(np.float32)
        got = np.asarray(gather_window_2d(jnp.asarray(vals), jnp.asarray(x)))
        # manual per-element
        for bi in range(b):
            for hi in range(h):
                flat_x = x[bi, hi].reshape(-1)
                want = np.asarray(
                    linear_sample_1d(jnp.asarray(vals[bi, hi].T),  # (D, W)
                                     jnp.broadcast_to(flat_x, (d, flat_x.size)))
                ).T.reshape(5, 3, d)
                np.testing.assert_allclose(got[bi, hi], want, rtol=1e-5, atol=1e-6)
