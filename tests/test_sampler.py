import numpy as np
import jax
import jax.numpy as jnp
import pytest

from raft_stereo_tpu.ops.sampler import gather_window_2d, linear_sample_1d, window_taps


class TestLinearSample1D:
    def test_exact_integer_coords(self):
        v = jnp.asarray([[10.0, 20.0, 30.0, 40.0]])
        x = jnp.asarray([[0.0, 1.0, 3.0]])
        out = linear_sample_1d(v, x)
        np.testing.assert_allclose(out, [[10.0, 20.0, 40.0]])

    def test_fractional_interp(self):
        v = jnp.asarray([[0.0, 10.0, 20.0]])
        x = jnp.asarray([[0.5, 1.25]])
        out = linear_sample_1d(v, x)
        np.testing.assert_allclose(out, [[5.0, 12.5]], rtol=1e-6)

    def test_zero_outside_range(self):
        """grid_sample(padding_mode='zeros') semantics: OOB taps read 0."""
        v = jnp.asarray([[10.0, 20.0]])
        x = jnp.asarray([[-1.0, -0.5, 1.5, 2.0, 5.0]])
        out = linear_sample_1d(v, x)
        # -0.5: 0.5*v[-1](=0) + 0.5*v[0] = 5 ; 1.5: 0.5*v[1] + 0.5*v[2](=0) = 10
        np.testing.assert_allclose(out, [[0.0, 5.0, 10.0, 0.0, 0.0]], rtol=1e-6)

    def test_edge_coordinate_no_bleed(self):
        """x == W-1 must return v[W-1] exactly (weight-0 OOB neighbor)."""
        v = jnp.asarray([[1.0, 2.0, 7.0]])
        out = linear_sample_1d(v, jnp.asarray([[2.0]]))
        np.testing.assert_allclose(out, [[7.0]])

    def test_matches_torch_grid_sample(self):
        """Oracle check against grid_sample(align_corners=True, zeros padding),
        the exact operator behind the reference's bilinear_sampler
        (core/utils/utils.py:59-74) on the (N,1,1,W) collapsed corr volume."""
        torch = pytest.importorskip("torch")
        rng = np.random.default_rng(0)
        n, w, k = 6, 37, 9
        vals = rng.standard_normal((n, w)).astype(np.float32)
        # include in-range, boundary and out-of-range coordinates
        x = rng.uniform(-3.0, w + 2.0, size=(n, k)).astype(np.float32)

        img = torch.from_numpy(vals).view(n, 1, 1, w)
        xg = 2 * torch.from_numpy(x) / (w - 1) - 1
        grid = torch.stack([xg, torch.zeros_like(xg)], dim=-1).view(n, k, 1, 2)
        want = torch.nn.functional.grid_sample(
            img, grid, align_corners=True).view(n, k).numpy()

        got = np.asarray(linear_sample_1d(jnp.asarray(vals), jnp.asarray(x)))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


class TestWindowTaps:
    def test_ascending_offsets(self):
        taps = window_taps(jnp.asarray([5.0]), radius=2)
        np.testing.assert_allclose(taps, [[3.0, 4.0, 5.0, 6.0, 7.0]])


class TestGatherWindow2D:
    def test_matches_manual(self):
        rng = np.random.default_rng(1)
        b, h, w, d = 2, 3, 11, 4
        vals = rng.standard_normal((b, h, w, d)).astype(np.float32)
        x = rng.uniform(-1.0, w, size=(b, h, 5, 3)).astype(np.float32)
        got = np.asarray(gather_window_2d(jnp.asarray(vals), jnp.asarray(x)))
        # manual per-element
        for bi in range(b):
            for hi in range(h):
                flat_x = x[bi, hi].reshape(-1)
                want = np.asarray(
                    linear_sample_1d(jnp.asarray(vals[bi, hi].T),  # (D, W)
                                     jnp.broadcast_to(flat_x, (d, flat_x.size)))
                ).T.reshape(5, 3, d)
                np.testing.assert_allclose(got[bi, hi], want, rtol=1e-5, atol=1e-6)


class TestWindowedLinearSample:
    def test_matches_general_sampler(self):
        """windowed_linear_sample == linear_sample_1d on window taps (the
        gather-free TPU path vs the reference-semantics oracle)."""
        from raft_stereo_tpu.ops.sampler import (linear_sample_1d, window_taps,
                                                 windowed_linear_sample)
        rng = np.random.default_rng(0)
        vol = jnp.asarray(rng.normal(size=(2, 3, 7, 24)), jnp.float32)
        # centers spanning in-range, fractional, far out-of-range both sides
        centers = jnp.asarray(
            rng.uniform(-8, 32, size=(2, 3, 7)), jnp.float32)
        for r in (1, 4):
            want = linear_sample_1d(vol, window_taps(centers, r))
            got = windowed_linear_sample(vol, centers, r)
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       atol=1e-5, rtol=1e-5)

    def test_integer_centers_exact(self):
        from raft_stereo_tpu.ops.sampler import windowed_linear_sample
        vol = jnp.arange(10, dtype=jnp.float32)[None]
        out = windowed_linear_sample(vol, jnp.asarray([3.0]), 1)
        np.testing.assert_allclose(np.asarray(out)[0], [2.0, 3.0, 4.0])

    def test_gradients_match_autodiff_oracle(self):
        """Autodiff of the masked-reduce path == autodiff of the gather-based
        oracle (both values- and center-gradients)."""
        from raft_stereo_tpu.ops.sampler import (linear_sample_1d, window_taps,
                                                 windowed_linear_sample)
        rng = np.random.default_rng(3)
        vol = jnp.asarray(rng.normal(size=(2, 4, 6, 20)), jnp.float32)
        centers = jnp.asarray(rng.uniform(-3, 22, size=(2, 4, 6)), jnp.float32)
        ct = jnp.asarray(rng.normal(size=(2, 4, 6, 9)), jnp.float32)

        def fast(v, c):
            return jnp.sum(windowed_linear_sample(v, c, 4) * ct)

        def oracle(v, c):
            return jnp.sum(linear_sample_1d(v, window_taps(c, 4)) * ct)

        gv_f, gc_f = jax.grad(fast, argnums=(0, 1))(vol, centers)
        gv_o, gc_o = jax.grad(oracle, argnums=(0, 1))(vol, centers)
        np.testing.assert_allclose(np.asarray(gv_f), np.asarray(gv_o),
                                   atol=1e-5, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(gc_f), np.asarray(gc_o),
                                   atol=1e-4, rtol=1e-4)
