"""Streaming eval pipeline (eval/stream.py + inference.predict_async):

* streaming-vs-sequential metric equivalence on all four validators
  (synthetic dataset trees, CPU, real forwards) at the oracle tolerance;
* async/sync predictor output parity;
* an injected-latency fake-device proof that the pipeline overlaps: >=2x
  end-to-end throughput at in-flight window >= 2;
* telemetry: per-frame step records (with in_flight) on every validator,
  the pipeline gauge, and schema conformance via scripts/check_events.py;
* the empty-valid-mask guard (skip-and-warn instead of NaN).
"""

import logging
import sys
import time
from pathlib import Path

import numpy as np
import pytest
from PIL import Image

import jax

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.data import frame_utils
from raft_stereo_tpu.eval import validate
from raft_stereo_tpu.eval.stream import (FrameTiming, StreamConfig,
                                         run_frames)
from raft_stereo_tpu.inference import StereoPredictor
from raft_stereo_tpu.models import init_model
from raft_stereo_tpu.obs import Telemetry, read_events

REPO = Path(__file__).resolve().parents[1]

H, W = 48, 96


# ---------------------------------------------------------- synthetic trees

def _save_png(path, arr):
    path.parent.mkdir(parents=True, exist_ok=True)
    Image.fromarray(arr).save(path)


def _images(rng, path_l, path_r, h=H, w=W):
    _save_png(path_l, rng.integers(0, 255, (h, w, 3), dtype=np.uint8))
    _save_png(path_r, rng.integers(0, 255, (h, w, 3), dtype=np.uint8))


def _write_eth3d(ds, rng, n=2, bad_frames=()):
    for i in range(n):
        scene = ds / "ETH3D" / "two_view_training" / f"scene_{i}"
        gt = ds / "ETH3D" / "two_view_training_gt" / f"scene_{i}"
        _images(rng, scene / "im0.png", scene / "im1.png")
        disp = rng.uniform(0, 8, (H, W)).astype(np.float32)
        if i in bad_frames:
            disp[:] = 600.0  # >= 512: every pixel fails the validity cut
        gt.mkdir(parents=True, exist_ok=True)
        frame_utils.write_pfm(str(gt / "disp0GT.pfm"), disp)
        _save_png(gt / "mask0nocc.png",
                  (rng.uniform(size=(H, W)) > 0.3).astype(np.uint8) * 255)


def _write_kitti(ds, rng, n=2):
    import cv2
    kroot = ds / "KITTI" / "training"
    for i in range(n):
        _images(rng, kroot / "image_2" / f"00000{i}_10.png",
                kroot / "image_3" / f"00000{i}_10.png")
        disp = rng.uniform(0.5, 40, (H, W))
        disp[rng.uniform(size=(H, W)) < 0.2] = 0.0  # sparse: invalid
        (kroot / "disp_occ_0").mkdir(parents=True, exist_ok=True)
        cv2.imwrite(str(kroot / "disp_occ_0" / f"00000{i}_10.png"),
                    (disp * 256.0).astype(np.uint16))


def _write_things(ds, rng, n=3):
    froot = ds / "FlyingThings3D"
    for i in range(n):
        left = froot / "frames_finalpass" / "TEST" / "A" / f"{i:04d}" / "left"
        right = froot / "frames_finalpass" / "TEST" / "A" / f"{i:04d}" / "right"
        _images(rng, left / "0006.png", right / "0006.png")
        disp = rng.uniform(0, 8, (H, W)).astype(np.float32)
        dpath = froot / "disparity" / "TEST" / "A" / f"{i:04d}" / "left"
        dpath.mkdir(parents=True, exist_ok=True)
        frame_utils.write_pfm(str(dpath / "0006.pfm"), disp)


def _write_middlebury(ds, rng):
    mb = ds / "Middlebury" / "MiddEval3"
    scene = mb / "trainingF" / "SceneA"
    _images(rng, scene / "im0.png", scene / "im1.png")
    disp = rng.uniform(0, 8, (H, W)).astype(np.float32)
    frame_utils.write_pfm(str(scene / "disp0GT.pfm"), disp)
    _save_png(scene / "mask0nocc.png",
              (rng.uniform(size=(H, W)) > 0.3).astype(np.uint8) * 255)
    (mb / "official_train.txt").write_text("SceneA\n")


@pytest.fixture(scope="module")
def tree(tmp_path_factory):
    root = tmp_path_factory.mktemp("stream_eval")
    ds = root / "datasets"
    rng = np.random.default_rng(21)
    _write_eth3d(ds, rng)
    _write_kitti(ds, rng)
    _write_things(ds, rng)
    _write_middlebury(ds, rng)
    return ds


@pytest.fixture(scope="module")
def predictor():
    cfg = RAFTStereoConfig()
    model, variables = init_model(jax.random.PRNGKey(0), cfg, (1, H, W, 3))
    return StereoPredictor(cfg, variables, valid_iters=2)


STREAM = StreamConfig(enabled=True, window=2, microbatch=2,
                      decode_workers=2)

VALIDATOR_CASES = [
    ("eth3d", validate.validate_eth3d, {}),
    ("kitti", validate.validate_kitti, {"warmup_frames": 0}),
    ("things", validate.validate_things, {}),
    ("middlebury", validate.validate_middlebury, {"split": "F"}),
]


# -------------------------------------------- stream == sequential metrics

@pytest.mark.parametrize("name,fn,kw", VALIDATOR_CASES,
                         ids=[c[0] for c in VALIDATOR_CASES])
def test_streaming_matches_sequential(tree, predictor, name, fn, kw):
    """Micro-batched, windowed streaming must aggregate to the sequential
    numbers at the oracle tolerance (metric closures retire in index
    order; frozen-stat normalization makes batching per-sample exact)."""
    seq = fn(predictor, root=str(tree), iters=2, stream=False, **kw)
    strm = fn(predictor, root=str(tree), iters=2, stream=STREAM, **kw)
    for key in seq:
        if key.endswith("fps") or key.endswith("fps-e2e"):
            continue  # wall-clock measurements, not metrics
        np.testing.assert_allclose(strm[key], seq[key], rtol=1e-5, atol=1e-5,
                                   err_msg=f"{name}:{key}")


def test_kitti_fps_keys_by_mode(tree, predictor):
    seq = validate.validate_kitti(predictor, root=str(tree), iters=2,
                                  warmup_frames=0, stream=False)
    strm = validate.validate_kitti(predictor, root=str(tree), iters=2,
                                   warmup_frames=0, stream=STREAM)
    # sequential: device-only FPS via predict_timed, plus e2e
    assert "kitti-fps" in seq and "kitti-fps-e2e" in seq
    # streaming: a per-frame device sync would re-serialize the pipeline;
    # only the pipelined end-to-end number is reported
    assert "kitti-fps" not in strm and "kitti-fps-e2e" in strm


# ------------------------------------------------------- async/sync parity

def test_predict_async_matches_sync(predictor):
    rng = np.random.default_rng(3)
    left = rng.uniform(0, 255, (1, 47, 90, 3)).astype(np.float32)
    right = rng.uniform(0, 255, (1, 47, 90, 3)).astype(np.float32)
    sync = predictor(left, right, iters=2)
    handle = predictor.predict_async(left, right, iters=2)
    out = handle.result()
    assert out.shape == sync.shape == (1, 47, 90, 1)
    # same compiled executable, same inputs -> identical outputs
    np.testing.assert_array_equal(out, sync)
    assert handle.ready()
    assert handle.fetch_s is not None and handle.dispatch_s >= 0.0
    assert handle.result() is out  # idempotent, cached


def test_stream_on_requires_async_predictor():
    class NoAsync:
        pass

    with pytest.raises(ValueError, match="predict_async"):
        run_frames(NoAsync(), [], lambda *a: None, iters=2, stream=True)


# ------------------------------------- injected-latency pipeline speedup

class _FakeFrames:
    """Minimal dataset: n identical tiny frames, instant decode."""

    def __init__(self, n, h=8, w=16):
        self.n = n
        self._s = {
            "image1": np.zeros((h, w, 3), np.uint8),
            "image2": np.zeros((h, w, 3), np.uint8),
            "flow": np.zeros((h, w, 1), np.float32),
            "valid": np.ones((h, w), np.float32),
        }

    def __len__(self):
        return self.n

    def sample(self, i):
        return dict(self._s)


def _sleep_until(t):
    while True:
        dt = t - time.monotonic()
        if dt <= 0:
            return
        time.sleep(dt)


class _FakeLatencyPredictor:
    """Single-queue fake device with a host round-trip cost.

    Dispatches serialize on the 'device' (each costs ``device_s`` per
    frame); every blocking host sync pays ``rtt_s``. The serial paths pay
    the real serial path's TWO round-trips per frame (H2D/sync fetch + the
    full-map fetch — see StereoPredictor.predict_timed); the async path
    pays one, after device completion, exactly like PendingPrediction.
    """

    def __init__(self, device_s, rtt_s):
        self.device_s, self.rtt_s = device_s, rtt_s
        self._free_at = time.monotonic()

    def _enqueue(self, batch):
        start = max(time.monotonic(), self._free_at)
        self._free_at = done = start + self.device_s * batch
        return done

    def _flow(self, im1):
        return np.zeros(im1.shape[:3] + (1,), np.float32)

    def predict_async(self, im1, im2, iters=None):
        done = self._enqueue(im1.shape[0])
        outer = self

        class Handle:
            dispatch_s = 0.0
            fetch_s = 0.0

            def result(self):
                _sleep_until(done)         # device completion
                time.sleep(outer.rtt_s)    # one D2H round-trip
                return outer._flow(im1)

        return Handle()

    def predict_timed(self, im1, im2, iters=None):
        # the real timed path settles inputs BEFORE dispatching
        # (jax.block_until_ready in StereoPredictor.predict_timed), so the
        # H2D round-trip serializes ahead of device compute
        time.sleep(self.rtt_s)
        done = self._enqueue(im1.shape[0])
        _sleep_until(done)
        time.sleep(self.rtt_s)             # full-map fetch
        return self._flow(im1), self.device_s * im1.shape[0]

    def __call__(self, im1, im2, iters=None):
        return self.predict_timed(im1, im2, iters)[0]


def test_pipeline_speedup_at_window_2plus():
    """Acceptance criterion: >=2x end-to-end eval throughput over the
    serial path at in-flight window >= 2 (deterministic injected latency:
    serial pays device + 2 RTT per frame; the pipeline retires at
    max(device, RTT))."""
    n, device_s, rtt_s = 20, 0.008, 0.012
    ds = _FakeFrames(n)
    seen = []

    def consume(i, sample, flow, timing):
        assert isinstance(timing, FrameTiming)
        seen.append(i)

    serial = run_frames(_FakeLatencyPredictor(device_s, rtt_s), ds, consume,
                        iters=2, stream=False, timed=True)
    assert seen == list(range(n))
    seen.clear()
    stream = run_frames(
        _FakeLatencyPredictor(device_s, rtt_s), ds, consume, iters=2,
        stream=StreamConfig(enabled=True, window=3, microbatch=1))
    assert seen == list(range(n))  # retire order == index order
    assert serial["mode"] == "sequential" and stream["mode"] == "stream"
    speedup = serial["wall_s"] / stream["wall_s"]
    assert speedup >= 2.0, (
        f"pipeline speedup {speedup:.2f}x < 2x "
        f"(serial {serial['wall_s']:.3f}s, stream {stream['wall_s']:.3f}s)")


def test_microbatch_groups_same_shape_frames():
    """With microbatch=4 over uniform shapes, dispatches carry batches > 1
    (the FlyingThings win) and every frame still retires exactly once."""
    ds = _FakeFrames(8)
    sizes = []
    run_frames(_FakeLatencyPredictor(1e-4, 1e-4), ds,
               lambda i, s, f, t: sizes.append(t.batch_size), iters=2,
               stream=StreamConfig(enabled=True, window=2, microbatch=4))
    assert len(sizes) == 8
    assert max(sizes) > 1


# ------------------------------------------------------------- telemetry

def test_streaming_emits_steps_and_pipeline_gauge(tree, predictor, tmp_path):
    run = tmp_path / "run"
    tel = Telemetry(str(run), run_name="stream-eval")
    tel.run_start(config={"dataset": "eth3d"})
    validate.validate_eth3d(predictor, root=str(tree), iters=2,
                            telemetry=tel, stream=STREAM)
    tel.emit("run_end", steps=tel.steps, ok=True)
    tel.close()

    events = read_events(str(run / "events.jsonl"))
    steps = [e for e in events if e["event"] == "step"]
    assert [s["step"] for s in steps] == [1, 2]  # every frame, in order
    for s in steps:
        assert {"data_wait_s", "dispatch_s", "fetch_s", "in_flight",
                "batch_size"} <= set(s)
    gauges = [e for e in events if e["event"] == "pipeline"]
    assert gauges and all("in_flight" in g for g in gauges)
    assert gauges[0]["window"] == STREAM.window

    # the artifact must pass the schema lint (scripts/check_events.py)
    sys.path.insert(0, str(REPO / "scripts"))
    import check_events
    assert check_events.main([str(run)]) == 0


def test_sequential_validators_emit_steps_too(tree, predictor, tmp_path):
    """PR goal: ALL validators emit the per-frame phase split (previously
    only KITTI did), in both modes."""
    run = tmp_path / "run"
    tel = Telemetry(str(run), run_name="seq-eval")
    validate.validate_middlebury(predictor, root=str(tree), iters=2,
                                 telemetry=tel, stream=False)
    tel.close()
    steps = [e for e in read_events(str(run / "events.jsonl"))
             if e["event"] == "step"]
    assert len(steps) == 1 and steps[0]["in_flight"] == 1


# ------------------------------------------------- empty-valid-mask guard

def test_empty_valid_mask_skips_frame_with_warning(tmp_path, predictor,
                                                   caplog):
    ds = tmp_path / "datasets"
    rng = np.random.default_rng(5)
    _write_eth3d(ds, rng, n=2, bad_frames=(1,))
    with caplog.at_level(logging.WARNING,
                         logger="raft_stereo_tpu.eval.validate"):
        result = validate.validate_eth3d(predictor, root=str(ds), iters=2,
                                         stream=False)
    assert np.isfinite(result["eth3d-epe"])  # the NaN frame was skipped
    assert any("validity mask is empty" in r.message for r in caplog.records)
