"""Fused corr-lookup + motion-encoder kernel vs the module composition.

Oracle: the exact XLA path the model takes without the kernel — ``_lookup_reg``
on a reg CorrState followed by ``BasicMotionEncoder`` (nn/gru.py), sharing one
parameter set. The kernels run in interpreter mode on CPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.nn.gru import BasicMotionEncoder
from raft_stereo_tpu.ops.corr import CorrState, _lookup_reg
from raft_stereo_tpu.ops.pallas.motion_kernels import (
    fused_corr_motion,
    fused_motion_applicable,
)

B, H, W = 1, 8, 24
W2S = (96, 48, 24, 12)
RADIUS = 4


def make_inputs(seed=0, vol_dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    levels = tuple(
        jnp.asarray(rng.standard_normal((B, H, W, w2)), vol_dtype)
        for w2 in W2S)
    coords = jnp.asarray(
        rng.uniform(-4.0, W2S[0] + 4.0, (B, H, W)), jnp.float32)
    return levels, coords


def make_params(seed=1):
    rng = np.random.default_rng(seed)

    def t(*shape):
        return jnp.asarray(rng.standard_normal(shape) * 0.1, jnp.float32)

    flax_params = {
        "convc1": {"kernel": t(1, 1, 36, 64), "bias": t(64)},
        "convc2": {"kernel": t(3, 3, 64, 64), "bias": t(64)},
        "convf1": {"kernel": t(7, 7, 2, 64), "bias": t(64)},
        "convf2": {"kernel": t(3, 3, 64, 64), "bias": t(64)},
        "conv": {"kernel": t(3, 3, 128, 126), "bias": t(126)},
    }
    kp = {
        "c1_k": flax_params["convc1"]["kernel"].reshape(36, 64),
        "c1_b": flax_params["convc1"]["bias"],
        "c2_k": flax_params["convc2"]["kernel"],
        "c2_b": flax_params["convc2"]["bias"],
        "f1_k": flax_params["convf1"]["kernel"][:, :, 0, :].reshape(49, 64),
        "f1_b": flax_params["convf1"]["bias"],
        "f2_k": flax_params["convf2"]["kernel"],
        "f2_b": flax_params["convf2"]["bias"],
        "o_k": flax_params["conv"]["kernel"],
        "o_b": flax_params["conv"]["bias"],
    }
    return flax_params, kp


def oracle_motion(levels, coords, flax_params, dt):
    state = CorrState(levels=levels, fmap1=None, impl="reg", radius=RADIUS)
    corr = _lookup_reg(state, coords)
    if dt is not None:
        corr = corr.astype(dt)
    col = jnp.arange(W, dtype=jnp.float32)[None, None, :]
    flow = jnp.stack([coords - col, jnp.zeros_like(coords)], axis=-1)
    if dt is not None:
        flow = flow.astype(dt)
    enc = BasicMotionEncoder(RAFTStereoConfig(), dtype=dt)
    return enc.apply({"params": flax_params}, flow, corr)


def test_applicable():
    levels, _ = make_inputs()
    assert fused_motion_applicable(levels, RADIUS)
    assert not fused_motion_applicable(levels[:3], RADIUS)
    tiny = tuple(v[..., : 2 * RADIUS + 1] for v in levels)
    assert not fused_motion_applicable(tiny, RADIUS)


@pytest.mark.parametrize("dt,tol", [(jnp.float32, 2e-4), (jnp.bfloat16, 5e-2)])
def test_forward_matches_module(dt, tol):
    levels, coords = make_inputs()
    flax_params, kp = make_params()
    want = np.asarray(oracle_motion(levels, coords, flax_params, dt),
                      np.float32)
    got = np.asarray(fused_corr_motion(levels, coords, kp, RADIUS, dt),
                     np.float32)
    assert got.shape == (B, H, W, 128)
    # flow channels exactly
    np.testing.assert_allclose(got[..., 126:], want[..., 126:],
                               atol=1e-5)
    scale = np.abs(want).max() + 1e-6
    np.testing.assert_allclose(got / scale, want / scale, atol=tol)


def test_forward_bf16_volume_storage():
    levels, coords = make_inputs(vol_dtype=jnp.bfloat16)
    flax_params, kp = make_params()
    want = np.asarray(oracle_motion(levels, coords, flax_params,
                                    jnp.bfloat16), np.float32)
    got = np.asarray(
        fused_corr_motion(levels, coords, kp, RADIUS, jnp.bfloat16),
        np.float32)
    scale = np.abs(want).max() + 1e-6
    np.testing.assert_allclose(got / scale, want / scale, atol=5e-2)


def test_multiblock_multibatch_grid():
    """B=2, H=24 -> grid (2, 3): exercises the clamped halo chunks, the
    interior-row weight-grad dedup, and the cross-grid-step accumulator
    revisiting that the single-program shapes above never reach."""
    b2, h2 = 2, 24
    rng = np.random.default_rng(11)
    levels = tuple(
        jnp.asarray(rng.standard_normal((b2, h2, W, w2)), jnp.float32)
        for w2 in W2S)
    coords = jnp.asarray(
        rng.uniform(-4.0, W2S[0] + 4.0, (b2, h2, W)), jnp.float32)
    flax_params, kp = make_params(12)
    probe = jnp.asarray(rng.standard_normal((b2, h2, W, 128)), jnp.float32)

    def oracle(levels, fp):
        state = CorrState(levels=levels, fmap1=None, impl="reg",
                          radius=RADIUS)
        corr = _lookup_reg(state, coords)
        col = jnp.arange(W, dtype=jnp.float32)[None, None, :]
        flow = jnp.stack([coords - col, jnp.zeros_like(coords)], axis=-1)
        enc = BasicMotionEncoder(RAFTStereoConfig(), dtype=None)
        return enc.apply({"params": fp}, flow, corr)

    got = np.asarray(
        fused_corr_motion(levels, coords, kp, RADIUS, jnp.float32),
        np.float32)
    want = np.asarray(oracle(levels, flax_params), np.float32)
    scale = np.abs(want).max() + 1e-6
    np.testing.assert_allclose(got / scale, want / scale, atol=2e-4)

    (dl_k, dkp) = jax.grad(
        lambda l, p: jnp.sum(
            fused_corr_motion(l, coords, p, RADIUS, jnp.float32) * probe),
        argnums=(0, 1))(levels, kp)
    (dl_o, dfp) = jax.grad(
        lambda l, p: jnp.sum(oracle(l, p) * probe),
        argnums=(0, 1))(levels, flax_params)
    for i in range(4):
        a, bb = np.asarray(dl_k[i]), np.asarray(dl_o[i])
        s = np.abs(bb).max() + 1e-6
        np.testing.assert_allclose(a / s, bb / s, atol=2e-4,
                                   err_msg=f"d_volume level {i} (multiblock)")
    pairs = [
        (dkp["c2_k"], dfp["convc2"]["kernel"]),
        (dkp["f1_k"].reshape(7, 7, 64), dfp["convf1"]["kernel"][:, :, 0, :]),
        (dkp["o_k"], dfp["conv"]["kernel"]),
        (dkp["o_b"], dfp["conv"]["bias"]),
    ]
    for nidx, (a, bb) in enumerate(pairs):
        a, bb = np.asarray(a), np.asarray(bb)
        s = np.abs(bb).max() + 1e-6
        np.testing.assert_allclose(a / s, bb / s, atol=2e-4,
                                   err_msg=f"param grad {nidx} (multiblock)")


def test_gradients_match_module():
    levels, coords = make_inputs()
    flax_params, kp = make_params()
    rng = np.random.default_rng(7)
    probe = jnp.asarray(rng.standard_normal((B, H, W, 128)), jnp.float32)

    def loss_kernel(levels, kp):
        return jnp.sum(
            fused_corr_motion(levels, coords, kp, RADIUS, jnp.float32)
            * probe)

    def loss_oracle(levels, fp):
        return jnp.sum(
            oracle_motion(levels, coords, fp, jnp.float32) * probe)

    (dl_k, dkp) = jax.grad(loss_kernel, argnums=(0, 1))(levels, kp)
    (dl_o, dfp) = jax.grad(loss_oracle, argnums=(0, 1))(levels, flax_params)

    for i in range(4):
        a, b = np.asarray(dl_k[i]), np.asarray(dl_o[i])
        scale = np.abs(b).max() + 1e-6
        np.testing.assert_allclose(a / scale, b / scale, atol=2e-4,
                                   err_msg=f"d_volume level {i}")

    pairs = [
        (dkp["c1_k"].reshape(1, 1, 36, 64), dfp["convc1"]["kernel"]),
        (dkp["c1_b"], dfp["convc1"]["bias"]),
        (dkp["c2_k"], dfp["convc2"]["kernel"]),
        (dkp["c2_b"], dfp["convc2"]["bias"]),
        (dkp["f1_k"].reshape(7, 7, 64), dfp["convf1"]["kernel"][:, :, 0, :]),
        (dkp["f1_b"], dfp["convf1"]["bias"]),
        (dkp["f2_k"], dfp["convf2"]["kernel"]),
        (dkp["f2_b"], dfp["convf2"]["bias"]),
        (dkp["o_k"], dfp["conv"]["kernel"]),
        (dkp["o_b"], dfp["conv"]["bias"]),
    ]
    for n, (a, b) in enumerate(pairs):
        a, b = np.asarray(a), np.asarray(b)
        scale = np.abs(b).max() + 1e-6
        np.testing.assert_allclose(a / scale, b / scale, atol=2e-4,
                                   err_msg=f"param grad {n}")
    # the y-column of convf1 must receive zero gradient in the oracle
    # (structurally-zero flow y), matching the kernel's omission of it
    np.testing.assert_allclose(
        np.asarray(dfp["convf1"]["kernel"][:, :, 1, :]), 0.0, atol=1e-6)
