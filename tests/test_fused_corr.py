"""The memoryless fused correlation lookup (r18).

The ``fused`` plugin's contract has three legs, each pinned here:

* PARITY — the W2-blocked Pallas kernel (interpreter mode on CPU) matches
  the ``reg`` materialized-volume oracle through the full registry path,
  across radii, pyramid depths (including degenerate narrow levels that
  route through the pure-JAX reference), out-of-range coords, and forced
  multi-block tilings (block_w < W2, non-dividing);
* GRADIENTS — the hand-written VJP (which re-derives tap gradients without
  a forward-saved volume) matches autodiff through the ``alt`` einsum
  oracle on both feature maps;
* MEMORYLESSNESS where it is testable on CPU — the scan-carried state
  pytree is the O(W) feature pyramid (bytes shrink vs reg's volume
  pyramid once W2 > D), and the serve cache / ring-mesh surfaces compose
  with the new impl.
"""

import dataclasses
import types

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_stereo_tpu.config import CORR_ALIASES, RAFTStereoConfig
from raft_stereo_tpu.ops.corr import corr_lookup, init_corr
from raft_stereo_tpu.ops.geometry import coords_grid
from raft_stereo_tpu.ops.pallas.corr_kernels import (
    _fused_tiles,
    fused_windowed_corr_pallas,
)
from raft_stereo_tpu.ops.sampler import windowed_linear_sample


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(0)
    b, h, w, d = 2, 4, 16, 32
    f1 = jnp.asarray(rng.normal(size=(b, h, w, d)), jnp.float32)
    f2 = jnp.asarray(rng.normal(size=(b, h, w, d)), jnp.float32)
    # deliberately past both edges: taps outside [0, W2) must read as zero
    centers = jnp.asarray(rng.uniform(-4, w + 4, size=(b, h, w)), jnp.float32)
    return f1, f2, centers


def _oracle(f1, f2, centers, radius):
    d = f1.shape[-1]
    vol = jnp.einsum("bhwd,bhvd->bhwv", f1, f2) / jnp.sqrt(jnp.float32(d))
    return windowed_linear_sample(vol, centers, radius)


class TestFusedKernel:
    @pytest.mark.parametrize("radius", [1, 3, 4])
    def test_forward_matches_oracle(self, data, radius):
        f1, f2, centers = data
        want = _oracle(f1, f2, centers, radius)
        got = fused_windowed_corr_pallas(f1, f2, centers, radius)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-4, rtol=1e-4)

    def test_forward_multiblock(self, data):
        """block_w < W2 forces nv > 1 (here a NON-dividing tile, so the
        zero-padded tail block is exercised too) — cross-block window
        accumulation must stay exact."""
        f1, f2, centers = data
        w2 = f2.shape[2]
        k = 2 * 3 + 1
        tiles = _fused_tiles(f1.shape[1], f1.shape[2], w2, f1.shape[3],
                             k, block_w=9)
        assert tiles is not None and tiles[2] > 1 and tiles[3] > w2
        want = _oracle(f1, f2, centers, 3)
        got = fused_windowed_corr_pallas(f1, f2, centers, 3, 9)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-4, rtol=1e-4)

    def test_degenerate_narrow_w2(self, data):
        """W2 <= 2r+2 lanes: the blocked kernel cannot tile, the pure-JAX
        per-tap reference must carry the level with identical semantics."""
        f1, f2, centers = data
        f2n = f2[:, :, :6]
        assert _fused_tiles(f1.shape[1], f1.shape[2], 6, f1.shape[3],
                            2 * 4 + 1, 256) is None
        want = _oracle(f1, f2n, centers, 4)
        got = fused_windowed_corr_pallas(f1, f2n, centers, 4)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-4, rtol=1e-4)

    @pytest.mark.parametrize("block_w", [256, 9])
    def test_backward_matches_oracle(self, data, block_w):
        """The hand VJP (no forward-saved volume) vs autodiff through the
        einsum oracle, on both the single- and multi-block tilings."""
        f1, f2, centers = data
        rng = np.random.default_rng(2)
        ct = jnp.asarray(rng.normal(size=(2, 4, 16, 7)), jnp.float32)

        def fused(a, b):
            return jnp.sum(
                fused_windowed_corr_pallas(a, b, centers, 3, block_w) * ct)

        def oracle(a, b):
            return jnp.sum(_oracle(a, b, centers, 3) * ct)

        g_f = jax.grad(fused, argnums=(0, 1))(f1, f2)
        g_o = jax.grad(oracle, argnums=(0, 1))(f1, f2)
        for a, b in zip(g_f, g_o):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)


class TestRegistryParity:
    @pytest.mark.parametrize("radius,num_levels", [(1, 2), (3, 2), (4, 4)])
    def test_lookup_matches_reg(self, data, radius, num_levels):
        # num_levels=4 pools W2 down to 2 — the deepest levels run the
        # degenerate reference path inside a registry lookup
        f1, f2, _ = data
        b, h, w, _ = f1.shape
        coords = coords_grid(b, h, w) + 1.3
        want = corr_lookup(init_corr("reg", f1, f2, num_levels=num_levels,
                                     radius=radius), coords)
        got = corr_lookup(init_corr("fused", f1, f2, num_levels=num_levels,
                                    radius=radius), coords)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-4, rtol=1e-4)

    def test_lookup_matches_reg_multiblock(self, data):
        f1, f2, _ = data
        b, h, w, _ = f1.shape
        coords = coords_grid(b, h, w) + 1.3
        want = corr_lookup(init_corr("reg", f1, f2, num_levels=2, radius=3),
                           coords)
        got = corr_lookup(init_corr("fused", f1, f2, num_levels=2, radius=3,
                                    block_w=9), coords)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-4, rtol=1e-4)

    def test_grad_matches_alt_autodiff(self, data):
        """End-to-end registry gradients: the fused custom VJP vs plain
        autodiff through the alt (einsum + windowed sample) lookup."""
        f1, f2, _ = data
        b, h, w, _ = f1.shape
        coords = coords_grid(b, h, w) + 1.3
        rng = np.random.default_rng(3)
        ct = jnp.asarray(rng.normal(size=(b, h, w, 2 * 7)), jnp.float32)

        def loss(impl):
            def f(a, b2):
                state = init_corr(impl, a, b2, num_levels=2, radius=3)
                return jnp.sum(corr_lookup(state, coords) * ct)
            return jax.grad(f, argnums=(0, 1))(f1, f2)

        for a, b2 in zip(loss("fused"), loss("alt")):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b2),
                                       atol=1e-4, rtol=1e-4)

    def test_state_is_feature_pyramid_and_shrinks(self):
        """The scan carry: fused state must be the O(W) feature pyramid
        (alt-shaped, last dim D), strictly smaller than reg's volume
        pyramid once W2 > D — the whole point of the impl."""
        rng = np.random.default_rng(4)
        b, h, w, d = 1, 4, 512, 32
        f1 = jnp.asarray(rng.normal(size=(b, h, w, d)), jnp.float32)
        f2 = jnp.asarray(rng.normal(size=(b, h, w, d)), jnp.float32)

        def leaf_bytes(state):
            return sum(x.size * x.dtype.itemsize
                       for x in jax.tree_util.tree_leaves(state))

        fused = init_corr("fused", f1, f2, num_levels=4, radius=4)
        reg = init_corr("reg", f1, f2, num_levels=4, radius=4)
        assert all(lvl.shape[-1] == d for lvl in fused.levels)
        assert fused.fmap1 is not None
        # reg carries ~1.875*H*W*W fp32; fused carries ~2.875*H*W*D
        assert leaf_bytes(fused) * 4 < leaf_bytes(reg)

    def test_aliases_and_unknown_impl_error(self):
        for alias in ("alt_cuda", "fused_cuda", "memoryless"):
            assert CORR_ALIASES[alias] == "fused"
            cfg = RAFTStereoConfig(corr_implementation=alias)
            assert cfg.corr_implementation == "fused"
        with pytest.raises(ValueError) as e:
            RAFTStereoConfig(corr_implementation="bogus")
        msg = str(e.value)
        assert "fused" in msg and "memoryless" in msg and "reg" in msg

    def test_block_w_validation(self):
        with pytest.raises(ValueError):
            RAFTStereoConfig(fused_block_w=4)  # < 2r+3 at default radius
        cfg = RAFTStereoConfig(fused_block_w=16, corr_radius=3)
        assert cfg.fused_block_w == 16


class TestComposition:
    def test_scan_carry_pytree_matches_alt(self, data):
        """Inside a scan, the fused state's pytree structure is carried
        every iteration — it must stay the alt-shaped feature pyramid
        (no volume leaf can sneak in through the lookup)."""
        f1, f2, _ = data
        state = init_corr("fused", f1, f2, num_levels=2, radius=3)
        alt = init_corr("alt", f1, f2, num_levels=2, radius=3)
        assert ([x.shape for x in jax.tree_util.tree_leaves(state)]
                == [x.shape for x in jax.tree_util.tree_leaves(alt)])
        b, h, w, _ = f1.shape
        coords = coords_grid(b, h, w) + 1.3

        def body(carry, _):
            st, c = carry
            feat = corr_lookup(st, c)
            c = c + jnp.mean(feat)  # coords move, state is re-carried
            return (st, c), jnp.mean(feat)

        (_, _), ys = jax.lax.scan(body, (state, coords), None, length=3)
        assert np.isfinite(np.asarray(ys)).all()

    def test_fused_under_seq_mesh(self, data):
        """fused needs no collectives: under a seq-sharded mesh (the ring
        impl's home) it must still trace, run, and match reg."""
        from raft_stereo_tpu.parallel.mesh import make_mesh

        f1, f2, _ = data
        b, h, w, _ = f1.shape
        coords = coords_grid(b, h, w) + 1.3
        want = corr_lookup(init_corr("reg", f1, f2, num_levels=2, radius=3),
                           coords)
        mesh = make_mesh(1, 8)
        with mesh:
            got = corr_lookup(init_corr("fused", f1, f2, num_levels=2,
                                        radius=3), coords)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-4, rtol=1e-4)

    def test_bucket_impl_threshold(self):
        from raft_stereo_tpu.serve.server import ServeConfig, StereoServer

        ns = types.SimpleNamespace(serve=ServeConfig(fused_width=512),
                                   cfg=RAFTStereoConfig())
        assert StereoServer._bucket_impl(ns, 512) == "fused"
        assert StereoServer._bucket_impl(ns, 256) == ""
        # already-fused server config: no flavor split needed
        ns.cfg = dataclasses.replace(ns.cfg, corr_implementation="fused")
        assert StereoServer._bucket_impl(ns, 1024) == ""
        # off by default
        ns = types.SimpleNamespace(serve=ServeConfig(),
                                   cfg=RAFTStereoConfig())
        assert StereoServer._bucket_impl(ns, 4096) == ""

    def test_serve_cache_fused_flavor(self):
        """A BucketKey with impl='fused' compiles its own program against
        the SAME variables and serves finite output close to the reg
        flavor (fully convolutional model — the impl touches no params)."""
        from raft_stereo_tpu.models import init_model
        from raft_stereo_tpu.serve.cache import BucketKey, ExecutableCache

        h, w = 32, 64
        cfg = RAFTStereoConfig()
        _, variables = init_model(jax.random.PRNGKey(0), cfg, (1, h, w, 3))
        cache = ExecutableCache(cfg, variables)
        rng = np.random.default_rng(5)
        im1 = rng.uniform(0, 255, (1, h, w, 3)).astype(np.float32)
        im2 = rng.uniform(0, 255, (1, h, w, 3)).astype(np.float32)

        key_reg = BucketKey(h, w, 1, 2, False)
        key_fused = BucketKey(h, w, 1, 2, False, "", "fused")
        assert key_fused.label() == f"{h}x{w}b1i2+fused"
        # the reg key's 5-positional construction stays valid (impl="")
        assert key_reg.label() == f"{h}x{w}b1i2"

        _, up_reg, finite_reg = cache(key_reg, im1, im2)[:3]
        _, up_fused, finite_fused = cache(key_fused, im1, im2)[:3]
        assert bool(finite_reg.all()) and bool(finite_fused.all())
        assert len(cache) == 2  # two distinct executables, one cache
        np.testing.assert_allclose(np.asarray(up_fused), np.asarray(up_reg),
                                   atol=2e-2, rtol=2e-2)
