"""Fused pyramid-lookup+convc1 kernel (ops/pallas/lookup_kernels.py).

Kernel-level parity against the pure-JAX composition (windowed_linear_sample
pyramid + 1x1 conv + ReLU) for forward and every gradient, plus end-to-end
model equivalence fused vs unfused — the same test shape/strategy the r3
full-fusion kernel used (its compile-tractable replacement keeps the same
oracle discipline). Runs in interpreter mode on CPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.models import create_model, init_model
from raft_stereo_tpu.ops.pallas.lookup_kernels import (
    fused_lookup_applicable,
    fused_lookup_c1,
)
from raft_stereo_tpu.ops.sampler import windowed_linear_sample
from raft_stereo_tpu.training.state import TrainState, make_train_step

RADIUS = 4


def make_pyramid(seed=0, b=2, h=16, w=128, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    levels = tuple(
        jnp.asarray(rng.normal(size=(b, h, w, w >> i)), dtype)
        for i in range(4))
    coords = jnp.asarray(rng.uniform(-3, w + 3, (b, h, w)), jnp.float32)
    cc = 4 * (2 * RADIUS + 1)
    kern = jnp.asarray(rng.normal(size=(cc, 64)) * 0.1, jnp.float32)
    bias = jnp.asarray(rng.normal(size=(64,)) * 0.1, jnp.float32)
    return levels, coords, kern, bias


def reference(levels, coords, kern, bias):
    outs = [windowed_linear_sample(v, coords / (2 ** i), RADIUS)
            for i, v in enumerate(levels)]
    corr = jnp.concatenate(outs, -1)
    return jax.nn.relu(jnp.einsum("bhwc,cd->bhwd", corr, kern) + bias)


def test_applicable():
    levels, *_ = make_pyramid()
    assert fused_lookup_applicable(levels, RADIUS)
    # too-narrow coarsest level
    assert not fused_lookup_applicable(
        tuple(jnp.zeros((1, 8, 32, 32 >> i)) for i in range(4)), RADIUS)
    # wrong level count
    assert not fused_lookup_applicable(levels[:3], RADIUS)


def test_forward_matches_composition():
    levels, coords, kern, bias = make_pyramid()
    out = fused_lookup_c1(levels, coords, kern, bias, RADIUS, None)
    ref = reference(levels, coords, kern, bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_forward_bf16_volume():
    levels, coords, kern, bias = make_pyramid(dtype=jnp.bfloat16)
    out = fused_lookup_c1(levels, coords, kern, bias, RADIUS, None)
    lv32 = tuple(v.astype(jnp.float32) for v in levels)
    ref = reference(lv32, coords, kern, bias)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref), atol=2e-2)


def test_gradients_match_composition():
    levels, coords, kern, bias = make_pyramid(seed=1)
    ct = jnp.asarray(np.random.default_rng(2).normal(
        size=(levels[0].shape[0], 16, 128, 64)), jnp.float32)

    def loss(fn):
        return lambda lv, c, k, b: jnp.sum(fn(lv, c, k, b) * ct)

    g_fused = jax.grad(
        loss(lambda lv, c, k, b: fused_lookup_c1(lv, c, k, b, RADIUS, None)),
        argnums=(0, 1, 2, 3))(levels, coords, kern, bias)
    g_ref = jax.grad(loss(reference),
                     argnums=(0, 1, 2, 3))(levels, coords, kern, bias)
    for i in range(4):
        np.testing.assert_allclose(np.asarray(g_fused[0][i]),
                                   np.asarray(g_ref[0][i]), atol=1e-5,
                                   err_msg=f"d_level{i}")
    # the model detaches coords before the lookup; the kernel's coords
    # cotangent is structurally zero
    assert float(jnp.max(jnp.abs(g_fused[1]))) == 0.0
    np.testing.assert_allclose(np.asarray(g_fused[2]), np.asarray(g_ref[2]),
                               atol=1e-3, err_msg="d_kernel")
    np.testing.assert_allclose(np.asarray(g_fused[3]), np.asarray(g_ref[3]),
                               atol=1e-3, err_msg="d_bias")


def test_multiblock_grid_forward_and_gradients():
    """h=32 resolves ``_pick_hb`` to 16 -> TWO row blocks per batch: covers
    the j-axis BlockSpec index maps, the disjoint per-row-block d_volume
    writes, and the dk/db accumulation across grid steps that single-block
    shapes never reach (the coverage the removed full-fusion kernel's
    multiblock test provided)."""
    from raft_stereo_tpu.ops.pallas.lookup_kernels import _pick_hb

    levels, coords, kern, bias = make_pyramid(seed=5, b=2, h=32, w=128)
    w2s = tuple(v.shape[-1] for v in levels)
    hb = _pick_hb(32, 128, w2s, levels[0].dtype.itemsize)
    assert 0 < hb < 32, f"expected a multi-block grid, got hb={hb}"

    out = fused_lookup_c1(levels, coords, kern, bias, RADIUS, None)
    ref = reference(levels, coords, kern, bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    ct = jnp.asarray(np.random.default_rng(6).normal(
        size=out.shape), jnp.float32)

    def loss(fn):
        return lambda lv, c, k, b: jnp.sum(fn(lv, c, k, b) * ct)

    g_fused = jax.grad(
        loss(lambda lv, c, k, b: fused_lookup_c1(lv, c, k, b, RADIUS, None)),
        argnums=(0, 2, 3))(levels, coords, kern, bias)
    g_ref = jax.grad(loss(reference),
                     argnums=(0, 2, 3))(levels, coords, kern, bias)
    for i in range(4):
        np.testing.assert_allclose(np.asarray(g_fused[0][i]),
                                   np.asarray(g_ref[0][i]), atol=1e-5,
                                   err_msg=f"d_level{i} (multiblock)")
    np.testing.assert_allclose(np.asarray(g_fused[1]), np.asarray(g_ref[1]),
                               atol=1e-3, err_msg="d_kernel (multiblock)")
    np.testing.assert_allclose(np.asarray(g_fused[2]), np.asarray(g_ref[2]),
                               atol=1e-3, err_msg="d_bias (multiblock)")


# ---- end-to-end model equivalence (shape where the kernel engages) ----

H, W = 32, 352  # 1/4-res grid 8x88; pyramid W2s (88, 44, 22, 11)
ITERS = 2


def make_images(seed=0, batch=1):
    rng = np.random.default_rng(seed)
    i1 = jnp.asarray(rng.uniform(0, 255, (batch, H, W, 3)), jnp.float32)
    i2 = jnp.asarray(rng.uniform(0, 255, (batch, H, W, 3)), jnp.float32)
    return i1, i2


def test_fused_engages_at_this_shape():
    lv = tuple(jnp.zeros((1, H // 4, W // 4, (W // 4) >> i), jnp.float32)
               for i in range(4))
    assert fused_lookup_applicable(lv, 4)


@pytest.mark.parametrize("mixed", [False, True])
def test_model_forward_fused_vs_unfused(mixed):
    cfg_off = RAFTStereoConfig(mixed_precision=mixed, fused_lookup=False)
    cfg_on = RAFTStereoConfig(mixed_precision=mixed, fused_lookup=True)
    model_off, variables = init_model(jax.random.PRNGKey(0), cfg_off,
                                      (1, H, W, 3))
    model_on = create_model(cfg_on)
    i1, i2 = make_images()
    out_off = model_off.apply(variables, i1, i2, iters=ITERS)
    out_on = model_on.apply(variables, i1, i2, iters=ITERS)
    a = np.asarray(out_off, np.float32)
    b = np.asarray(out_on, np.float32)
    # bf16 GRU iteration compounds rounding differences between the fused
    # kernel and the XLA graph; fp32 agreement is the exactness check
    tol = 0.5 if mixed else 2e-3
    np.testing.assert_allclose(b, a, atol=tol,
                               err_msg="fused vs unfused predictions")


def test_train_step_fused_vs_unfused():
    i1, i2 = make_images(3)
    rng = np.random.default_rng(4)
    batch = {
        "image1": i1, "image2": i2,
        "flow": -jnp.asarray(rng.uniform(0, 8, (1, H, W, 1)), jnp.float32),
        "valid": jnp.ones((1, H, W), jnp.float32),
    }
    import optax

    outs = {}
    for name, fused in (("off", False), ("on", True)):
        cfg = RAFTStereoConfig(fused_lookup=fused)
        model, variables = init_model(jax.random.PRNGKey(0), cfg,
                                      (1, H, W, 3))
        # SGD(1.0): the parameter delta IS the (negated) gradient, so this
        # compares raw gradients — Adam's per-element normalization would
        # amplify fp noise on near-zero-gradient params into O(1) update
        # differences that say nothing about correctness.
        tx = optax.sgd(1.0)
        state = TrainState.create(variables, tx)
        step = make_train_step(model, tx, ITERS)
        new_state, metrics = step(state, batch)
        grads = jax.tree.map(lambda old, new: np.asarray(old - new,
                                                         np.float32),
                             state.params, new_state.params)
        outs[name] = (grads, metrics)

    m_off, m_on = outs["off"][1], outs["on"][1]
    np.testing.assert_allclose(float(m_on["loss"]), float(m_off["loss"]),
                               rtol=1e-4)
    np.testing.assert_allclose(float(m_on["epe"]), float(m_off["epe"]),
                               rtol=1e-4)

    flat_off = jax.tree_util.tree_leaves_with_path(outs["off"][0])
    flat_on = jax.tree_util.tree_leaves_with_path(outs["on"][0])
    gscale = max(np.abs(a).max() for _, a in flat_off) + 1e-6
    for (path_a, a), (_, b) in zip(flat_off, flat_on):
        np.testing.assert_allclose(
            b / gscale, a / gscale, atol=1e-3,
            err_msg=f"gradient {jax.tree_util.keystr(path_a)}")
