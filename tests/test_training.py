"""Tests for the training substrate: loss, optimizer schedule, train steps,
and the dp+sp parallel paths on the 8-device virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_stereo_tpu.config import RAFTStereoConfig, TrainConfig
from raft_stereo_tpu.models import init_model
from raft_stereo_tpu.training.loss import sequence_loss
from raft_stereo_tpu.training.optim import fetch_optimizer, one_cycle_lr
from raft_stereo_tpu.training.state import TrainState, make_train_step


# ------------------------------------------------------------------- loss

def test_sequence_loss_perfect_prediction():
    gt = jnp.ones((2, 8, 10, 1)) * -3.0
    preds = jnp.broadcast_to(gt[None], (4,) + gt.shape)
    valid = jnp.ones((2, 8, 10))
    loss, metrics = sequence_loss(preds, gt, valid)
    assert float(loss) == pytest.approx(0.0)
    assert float(metrics["epe"]) == pytest.approx(0.0)
    assert float(metrics["1px"]) == pytest.approx(1.0)


def test_sequence_loss_weighting_favors_late_iterations():
    gt = jnp.zeros((1, 4, 4, 1))
    valid = jnp.ones((1, 4, 4))
    # error only in the FIRST iteration vs only in the LAST
    early = jnp.zeros((3, 1, 4, 4, 1)).at[0].set(1.0)
    late = jnp.zeros((3, 1, 4, 4, 1)).at[-1].set(1.0)
    loss_early, _ = sequence_loss(early, gt, valid)
    loss_late, _ = sequence_loss(late, gt, valid)
    assert float(loss_late) > float(loss_early)


def test_sequence_loss_nonfinite_gt_masked_out():
    """A masked-out inf GT pixel (e.g. disparity 80/0 from zero depth) must
    not poison the loss: inf * 0 would be nan without the where-guard."""
    gt = jnp.zeros((1, 4, 4, 1)).at[0, 1, 1, 0].set(jnp.inf)
    preds = jnp.zeros((2, 1, 4, 4, 1))
    valid = jnp.ones((1, 4, 4))
    loss, metrics = sequence_loss(preds, gt, valid)
    assert jnp.isfinite(loss)
    assert jnp.isfinite(metrics["epe"])
    # 15 of 16 pixels are perfect; the inf pixel is excluded by the mag mask
    assert float(metrics["1px"]) == pytest.approx(1.0)


def test_sequence_loss_invalid_pixels_excluded():
    gt = jnp.full((1, 4, 4, 1), -2.0)
    preds = jnp.zeros((1, 1, 4, 4, 1))  # epe 2 everywhere
    valid = jnp.zeros((1, 4, 4)).at[0, 0, 0].set(1.0)
    _, metrics = sequence_loss(preds, gt, valid)
    assert float(metrics["epe"]) == pytest.approx(2.0)
    assert float(metrics["3px"]) == pytest.approx(1.0)


def test_sequence_loss_gamma_adjustment():
    """gamma_adj = 0.9 ** (15/(n-1)): n=16 gives 0.9 per-step decay."""
    gt = jnp.zeros((1, 2, 2, 1))
    valid = jnp.ones((1, 2, 2))
    preds = jnp.ones((16, 1, 2, 2, 1))
    loss, _ = sequence_loss(preds, gt, valid)
    expected = sum(0.9 ** (15 - i) for i in range(16))
    assert float(loss) == pytest.approx(expected, rel=1e-5)


# ------------------------------------------------------------------- optim

def test_one_cycle_lr_shape():
    sched = one_cycle_lr(peak_lr=1e-3, total_steps=1000, pct_start=0.01)
    warm = [float(sched(i)) for i in range(0, 12)]
    assert warm[0] < warm[5] < warm[10]  # warmup rises
    peak_step = int(0.01 * 1001)
    assert float(sched(peak_step)) == pytest.approx(1e-3, rel=1e-2)
    assert float(sched(999)) < 1e-4  # annealed near zero
    assert float(sched(1500)) >= 0.0  # past-end queries stay finite


def test_fetch_optimizer_steps():
    tcfg = TrainConfig(num_steps=50, lr=1e-3, wdecay=1e-5, batch_size=2)
    tx = fetch_optimizer(tcfg)
    params = {"w": jnp.ones((4, 4))}
    state = tx.init(params)
    grads = {"w": jnp.ones((4, 4))}
    updates, state = tx.update(grads, state, params)
    assert jnp.isfinite(updates["w"]).all()


# ------------------------------------------------------------------- train step

@pytest.fixture(scope="module")
def tiny_setup():
    cfg = RAFTStereoConfig()
    tcfg = TrainConfig(num_steps=10, batch_size=2)
    model, variables = init_model(jax.random.PRNGKey(0), cfg, (1, 32, 48, 3))
    tx = fetch_optimizer(tcfg)
    state = TrainState.create(variables, tx)
    rng = np.random.default_rng(0)
    batch = {
        "image1": jnp.asarray(rng.uniform(0, 255, (2, 32, 48, 3)), jnp.float32),
        "image2": jnp.asarray(rng.uniform(0, 255, (2, 32, 48, 3)), jnp.float32),
        "flow": jnp.asarray(rng.uniform(-8, 0, (2, 32, 48, 1)), jnp.float32),
        "valid": jnp.ones((2, 32, 48), jnp.float32),
    }
    return model, tx, state, batch


def test_train_step_updates_params_and_metrics(tiny_setup):
    model, tx, state, batch = tiny_setup
    step = jax.jit(make_train_step(model, tx, train_iters=2))
    new_state, metrics = step(state, batch)
    assert int(new_state.step) == 1
    assert jnp.isfinite(metrics["loss"])
    assert jnp.isfinite(metrics["epe"])
    # at least some parameters moved
    moved = jax.tree_util.tree_reduce(
        lambda acc, pair: acc or bool(jnp.any(pair[0] != pair[1])),
        jax.tree.map(lambda a, b: (a, b), state.params, new_state.params),
        False)
    assert moved


# ------------------------------------------------------------------- parallel

@pytest.mark.slow  # full-model 8-device XLA-CPU compile, minutes of wall clock
def test_dryrun_multichip_8dev():
    """The driver's multi-chip validation path: dp x sp pjit step and
    explicit shard_map DP step, one step each on the virtual 8-CPU mesh."""
    from raft_stereo_tpu.parallel import dryrun_train_step

    dryrun_train_step(8)


@pytest.mark.slow  # full-model 8-device XLA-CPU compile, minutes of wall clock
def test_shardmap_dp_matches_single_device():
    """psum-reduced DP gradients must equal the single-device gradients."""
    from raft_stereo_tpu.parallel.mesh import make_mesh, replicated
    from raft_stereo_tpu.parallel.data_parallel import make_shardmap_train_step
    from jax.sharding import NamedSharding, PartitionSpec as P

    cfg = RAFTStereoConfig()
    tcfg = TrainConfig(num_steps=10, batch_size=4, lr=1e-4)
    model, variables = init_model(jax.random.PRNGKey(0), cfg, (1, 32, 48, 3))
    tx = fetch_optimizer(tcfg)
    state = TrainState.create(variables, tx)

    rng = np.random.default_rng(1)
    batch = {
        "image1": jnp.asarray(rng.uniform(0, 255, (4, 32, 48, 3)), jnp.float32),
        "image2": jnp.asarray(rng.uniform(0, 255, (4, 32, 48, 3)), jnp.float32),
        "flow": jnp.asarray(rng.uniform(-8, 0, (4, 32, 48, 1)), jnp.float32),
        "valid": jnp.ones((4, 32, 48), jnp.float32),
    }

    single = jax.jit(make_train_step(model, tx, train_iters=1))
    ref_state, ref_metrics = single(jax.tree.map(jnp.array, state), batch)

    mesh = make_mesh(4, 1, devices=jax.devices()[:4])
    with mesh:
        st = jax.device_put(jax.tree.map(jnp.array, state), replicated(mesh))
        sharded_batch = {k: jax.device_put(
            v, NamedSharding(mesh, P("data"))) for k, v in batch.items()}
        dp_step = make_shardmap_train_step(model, tx, 1, mesh)
        dp_state, dp_metrics = dp_step(st, sharded_batch)

    assert float(dp_metrics["loss"]) == pytest.approx(
        float(ref_metrics["loss"]), rel=1e-4)
    leaves_ref = jax.tree_util.tree_leaves(ref_state.params)
    leaves_dp = jax.tree_util.tree_leaves(dp_state.params)
    for a, b in zip(leaves_ref, leaves_dp):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-5)


@pytest.mark.slow  # full-model 8-device XLA-CPU compile, minutes of wall clock
def test_pjit_stacked_step_runs():
    """trainer.py's multi-chip combination — make_pjit_train_step with the
    default stacked loss — must compile and execute on a dp x sp mesh (the
    driver dryrun now runs the fused variant, so this is the stacked
    path's only sharded execution)."""
    from raft_stereo_tpu.parallel.mesh import (make_mesh, replicated,
                                               shard_batch)
    from raft_stereo_tpu.parallel.data_parallel import make_pjit_train_step

    cfg = RAFTStereoConfig()
    tcfg = TrainConfig(num_steps=10, batch_size=4, lr=1e-4)
    model, variables = init_model(jax.random.PRNGKey(0), cfg, (1, 32, 48, 3))
    tx = fetch_optimizer(tcfg)
    state = TrainState.create(variables, tx)

    rng = np.random.default_rng(3)
    batch = {
        "image1": jnp.asarray(rng.uniform(0, 255, (4, 32, 48, 3)), jnp.float32),
        "image2": jnp.asarray(rng.uniform(0, 255, (4, 32, 48, 3)), jnp.float32),
        "flow": jnp.asarray(rng.uniform(-8, 0, (4, 32, 48, 1)), jnp.float32),
        "valid": jnp.ones((4, 32, 48), jnp.float32),
    }
    mesh = make_mesh(2, 2, devices=jax.devices()[:4])
    with mesh:
        st = jax.device_put(jax.tree.map(jnp.array, state), replicated(mesh))
        placed = shard_batch(mesh, batch)
        step = make_pjit_train_step(model, tx, 2, mesh, fused_loss=False)
        new_state, metrics = step(st, placed)
    assert int(new_state.step) == 1
    assert np.isfinite(float(metrics["loss"]))


@pytest.mark.slow  # full-model 8-device XLA-CPU compile, minutes of wall clock
def test_shardmap_fused_matches_single_device_fused():
    """The fused-loss shard_map DP step must equal the single-device
    fused-loss step (psum-global normalization of the in-scan error sums)."""
    from raft_stereo_tpu.parallel.mesh import make_mesh, replicated
    from raft_stereo_tpu.parallel.data_parallel import make_shardmap_train_step
    from jax.sharding import NamedSharding, PartitionSpec as P

    cfg = RAFTStereoConfig()
    tcfg = TrainConfig(num_steps=10, batch_size=4, lr=1e-4)
    model, variables = init_model(jax.random.PRNGKey(0), cfg, (1, 32, 48, 3))
    tx = fetch_optimizer(tcfg)
    state = TrainState.create(variables, tx)

    rng = np.random.default_rng(2)
    batch = {
        "image1": jnp.asarray(rng.uniform(0, 255, (4, 32, 48, 3)), jnp.float32),
        "image2": jnp.asarray(rng.uniform(0, 255, (4, 32, 48, 3)), jnp.float32),
        "flow": jnp.asarray(rng.uniform(-8, 0, (4, 32, 48, 1)), jnp.float32),
        "valid": jnp.ones((4, 32, 48), jnp.float32),
    }

    single = jax.jit(make_train_step(model, tx, train_iters=1,
                                     fused_loss=True))
    ref_state, ref_metrics = single(jax.tree.map(jnp.array, state), batch)

    mesh = make_mesh(4, 1, devices=jax.devices()[:4])
    with mesh:
        st = jax.device_put(jax.tree.map(jnp.array, state), replicated(mesh))
        sharded_batch = {k: jax.device_put(
            v, NamedSharding(mesh, P("data"))) for k, v in batch.items()}
        dp_step = make_shardmap_train_step(model, tx, 1, mesh,
                                           fused_loss=True)
        dp_state, dp_metrics = dp_step(st, sharded_batch)

    assert float(dp_metrics["loss"]) == pytest.approx(
        float(ref_metrics["loss"]), rel=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(ref_state.params),
                    jax.tree_util.tree_leaves(dp_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-5)


@pytest.mark.parametrize("deferred", [True, False])
def test_fused_loss_matches_stacked(deferred):
    """The fused loss paths (in-scan when deferred_upsample=False, post-scan
    tile-layout when True) must produce the same loss/metrics as
    sequence_loss over the stacked predictions."""
    import jax
    import jax.numpy as jnp
    from raft_stereo_tpu.config import RAFTStereoConfig
    from raft_stereo_tpu.models import init_model
    from raft_stereo_tpu.training.loss import (loss_mask, sequence_loss,
                                               sequence_loss_fused)

    cfg = RAFTStereoConfig(deferred_upsample=deferred)
    model, variables = init_model(jax.random.PRNGKey(0), cfg, (1, 48, 64, 3))
    rng = np.random.default_rng(0)
    img1 = jnp.asarray(rng.uniform(0, 255, (2, 48, 64, 3)), jnp.float32)
    img2 = jnp.asarray(rng.uniform(0, 255, (2, 48, 64, 3)), jnp.float32)
    gt = jnp.asarray(rng.uniform(-8, 0, (2, 48, 64, 1)), jnp.float32)
    valid = jnp.asarray(rng.uniform(size=(2, 48, 64)) > 0.3, jnp.float32)

    preds = model.apply(variables, img1, img2, iters=3)
    loss_a, metrics_a = sequence_loss(preds, gt, valid)

    mask = loss_mask(gt, valid)
    err_sums, final_flow = model.apply(variables, img1, img2, iters=3,
                                       flow_gt=gt, loss_mask=mask)
    loss_b, metrics_b = sequence_loss_fused(err_sums, final_flow, gt, mask)

    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-5)
    for k in metrics_a:
        np.testing.assert_allclose(float(metrics_a[k]), float(metrics_b[k]),
                                   rtol=1e-5, err_msg=k)


def test_chunked_deferred_upsample_matches():
    """Forcing the chunked post-scan upsample (tiny tile budget) must not
    change the fused loss/metrics."""
    from raft_stereo_tpu.models import raft_stereo as rs_mod
    from raft_stereo_tpu.training.loss import loss_mask, sequence_loss_fused

    cfg = RAFTStereoConfig()
    model, variables = init_model(jax.random.PRNGKey(0), cfg, (1, 32, 48, 3))
    rng = np.random.default_rng(5)
    img1 = jnp.asarray(rng.uniform(0, 255, (2, 32, 48, 3)), jnp.float32)
    img2 = jnp.asarray(rng.uniform(0, 255, (2, 32, 48, 3)), jnp.float32)
    gt = jnp.asarray(rng.uniform(-8, 0, (2, 32, 48, 1)), jnp.float32)
    valid = jnp.ones((2, 32, 48), jnp.float32)
    mask = loss_mask(gt, valid)

    err_a, up_a = model.apply(variables, img1, img2, iters=4, flow_gt=gt,
                              loss_mask=mask)
    budget0 = rs_mod._UPSAMPLE_TILE_BUDGET
    rs_mod._UPSAMPLE_TILE_BUDGET = 1  # force maximal chunking
    try:
        err_b, up_b = model.apply(variables, img1, img2, iters=4, flow_gt=gt,
                                  loss_mask=mask)
    finally:
        rs_mod._UPSAMPLE_TILE_BUDGET = budget0
    np.testing.assert_allclose(np.asarray(err_a), np.asarray(err_b),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(up_a), np.asarray(up_b), atol=1e-6)


def test_encoder_remat_variants_identical():
    """remat_encoders in {False, True, 'blocks'} is pure scheduling: forward
    outputs and parameter gradients must match up to XLA fusion-level
    float reassociation (~1e-6 absolute on this unit-scale output)."""
    import jax
    import jax.numpy as jnp
    from raft_stereo_tpu.config import RAFTStereoConfig
    from raft_stereo_tpu.models import create_model, init_model

    base = RAFTStereoConfig()
    model0, variables = init_model(jax.random.PRNGKey(0), base, (1, 32, 48, 3))
    rng = np.random.default_rng(1)
    img1 = jnp.asarray(rng.uniform(0, 255, (1, 32, 48, 3)), jnp.float32)
    img2 = jnp.asarray(rng.uniform(0, 255, (1, 32, 48, 3)), jnp.float32)
    rest = {k: v for k, v in variables.items() if k != "params"}

    def loss(model):
        def f(p):
            out = model.apply({"params": p, **rest}, img1, img2, iters=2)
            return jnp.mean(jnp.abs(out))
        return f

    want_out = model0.apply(variables, img1, img2, iters=2)
    want_g = jax.grad(loss(model0))(variables["params"])
    for variant in (True, "blocks", "blocks_hires", "norms"):
        kwargs = {"remat_encoders": variant}
        if variant in ("norms", "blocks", "blocks_hires"):
            # also exercise the lane-dense folded saves (auto rule keeps
            # them off at test shapes); for "blocks" the fold wraps the
            # remat boundary itself (encoder.py apply_block)
            kwargs["fold_enc_saves"] = True
        m = create_model(RAFTStereoConfig(**kwargs))
        got_out = m.apply(variables, img1, img2, iters=2)
        np.testing.assert_allclose(np.asarray(got_out), np.asarray(want_out),
                                   atol=1e-5, err_msg=str(variant))
        got_g = jax.grad(loss(m))(variables["params"])
        for a, b in zip(jax.tree_util.tree_leaves(want_g),
                        jax.tree_util.tree_leaves(got_g)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       atol=1e-5, err_msg=str(variant))


def test_schedule_knobs_identical_train_step():
    """remat_loss_tail and scan_unroll are pure scheduling: the fused-loss
    forward and the parameter gradients must match across settings (up to
    XLA fusion-level float reassociation — params-after-AdamW are NOT
    compared because Adam normalizes reassociation-dust gradients into
    lr-sized update differences). These are the knobs the r4 bench banker
    flips (bench.py)."""
    import jax
    import jax.numpy as jnp
    from raft_stereo_tpu.config import RAFTStereoConfig
    from raft_stereo_tpu.models import create_model, init_model

    base = RAFTStereoConfig()
    model0, variables = init_model(jax.random.PRNGKey(0), base, (1, 32, 64, 3))
    rng = np.random.default_rng(7)
    img1 = jnp.asarray(rng.uniform(0, 255, (1, 32, 64, 3)), jnp.float32)
    img2 = jnp.asarray(rng.uniform(0, 255, (1, 32, 64, 3)), jnp.float32)
    gt = jnp.asarray(rng.uniform(-16, 0, (1, 32, 64, 1)), jnp.float32)
    mask = jnp.ones((1, 32, 64, 1), jnp.float32)
    rest = {k: v for k, v in variables.items() if k != "params"}

    def l1_loss(model):
        def f(p):
            err, _ = model.apply({"params": p, **rest}, img1, img2, iters=2,
                                 flow_gt=gt, loss_mask=mask)
            return jnp.sum(err)
        return f

    def smooth_loss(model):
        # mean-of-squares over the prediction stack: the L1 objective's
        # sign() backward is discontinuous, so ulp-level forward changes
        # (which unroll's refusioning legitimately makes) flip cotangents
        # on near-zero elements; a smooth loss isolates scheduling bugs
        # from that amplification.
        def f(p):
            out = model.apply({"params": p, **rest}, img1, img2, iters=2)
            return jnp.mean(jnp.square(out))
        return f

    # remat_loss_tail flips only the save/recompute schedule of the loss
    # tail — same fusion decisions elsewhere, so L1 grads match tightly.
    want = l1_loss(model0)(variables["params"])
    want_g = jax.grad(l1_loss(model0))(variables["params"])
    m_tail = create_model(RAFTStereoConfig(remat_loss_tail=False))
    np.testing.assert_allclose(
        np.asarray(l1_loss(m_tail)(variables["params"])), np.asarray(want),
        rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(want_g),
                    jax.tree_util.tree_leaves(
                        jax.grad(l1_loss(m_tail))(variables["params"]))):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-4, atol=1e-5,
                                   err_msg="remat_loss_tail")

    # scan_unroll: forward pinned tightly on BOTH losses; grads on the
    # smooth loss (see smooth_loss's comment).
    m_unroll = create_model(RAFTStereoConfig(scan_unroll=2))
    np.testing.assert_allclose(
        np.asarray(l1_loss(m_unroll)(variables["params"])), np.asarray(want),
        rtol=1e-6)
    want_s = smooth_loss(model0)(variables["params"])
    want_sg = jax.grad(smooth_loss(model0))(variables["params"])
    np.testing.assert_allclose(
        np.asarray(smooth_loss(m_unroll)(variables["params"])),
        np.asarray(want_s), rtol=1e-6)
    got_sg = jax.grad(smooth_loss(m_unroll))(variables["params"])
    want_leaves = [np.asarray(x, np.float64)
                   for x in jax.tree_util.tree_leaves(want_sg)]
    got_leaves = [np.asarray(x, np.float64)
                  for x in jax.tree_util.tree_leaves(got_sg)]
    global_scale = max(np.linalg.norm(a) for a in want_leaves)
    for a, b in zip(want_leaves, got_leaves):
        # Relative-L2 per leaf: unroll's refusioning reorders fp32
        # accumulations throughout the backward, moving scattered
        # cancellation-prone elements by up to ~0.1% of leaf scale —
        # elementwise bounds chase that tail one outlier at a time, while
        # an aggregate 0.1% L2 bound pins the semantics (a scheduling bug
        # like a dropped iteration shows up at O(10-100%), not 0.1%).
        # Leaves that are pure float residue get an absolute bound: a conv
        # bias feeding instance norm has a structurally-ZERO gradient
        # (the norm subtracts any bias shift), so its computed value is
        # reassociation noise with O(1) relative spread across schedules.
        diff = np.linalg.norm(b - a)
        na = np.linalg.norm(a)
        if na < 1e-6 * global_scale:
            assert diff < 1e-6 * global_scale, \
                f"scan_unroll: residual leaf moved {diff:.2e}"
        else:
            rel = diff / na
            assert rel < 1e-3, f"scan_unroll: leaf rel-L2 {rel:.2e}"


def test_blocks_hires_shared_backbone_identical():
    """Under blocks_hires the context encoder is saved whole ONLY when it is
    not the shared backbone (models/raft_stereo.py cnet_remat); both layouts
    must be pure scheduling. Exercises the realtime preset's shared-backbone
    trunk, where cnet IS the doubled-batch encoder and keeps the remat."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    from raft_stereo_tpu.config import realtime_config
    from raft_stereo_tpu.models import create_model, init_model

    base = dataclasses.replace(realtime_config(), mixed_precision=False)
    model0, variables = init_model(jax.random.PRNGKey(0), base, (1, 32, 48, 3))
    rng = np.random.default_rng(5)
    img1 = jnp.asarray(rng.uniform(0, 255, (1, 32, 48, 3)), jnp.float32)
    img2 = jnp.asarray(rng.uniform(0, 255, (1, 32, 48, 3)), jnp.float32)
    rest = {k: v for k, v in variables.items() if k != "params"}

    def loss(model):
        def f(p):
            out = model.apply({"params": p, **rest}, img1, img2, iters=2)
            return jnp.mean(jnp.abs(out))
        return f

    want_out = model0.apply(variables, img1, img2, iters=2)
    want_g = jax.grad(loss(model0))(variables["params"])
    m = create_model(dataclasses.replace(base, remat_encoders="blocks_hires"))
    got_out = m.apply(variables, img1, img2, iters=2)
    np.testing.assert_allclose(np.asarray(got_out), np.asarray(want_out),
                               atol=1e-6)
    got_g = jax.grad(loss(m))(variables["params"])
    for a, b in zip(jax.tree_util.tree_leaves(want_g),
                    jax.tree_util.tree_leaves(got_g)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=1e-6)


def test_refinement_save_policy_variants_identical():
    """refinement_save_policy in {False, True, 'corr'} is pure scheduling:
    forward outputs and parameter gradients must match up to XLA
    fusion-level float reassociation. 'corr' saves
    only the corr lookup output across the refinement backward (~180 MB at
    SceneFlow b8 vs ~2.7 GB for the full set)."""
    import jax
    import jax.numpy as jnp
    from raft_stereo_tpu.config import RAFTStereoConfig
    from raft_stereo_tpu.models import create_model, init_model

    base = RAFTStereoConfig(refinement_save_policy=False)
    model0, variables = init_model(jax.random.PRNGKey(0), base, (1, 32, 48, 3))
    rng = np.random.default_rng(3)
    img1 = jnp.asarray(rng.uniform(0, 255, (1, 32, 48, 3)), jnp.float32)
    img2 = jnp.asarray(rng.uniform(0, 255, (1, 32, 48, 3)), jnp.float32)
    rest = {k: v for k, v in variables.items() if k != "params"}

    def loss(model):
        def f(p):
            out = model.apply({"params": p, **rest}, img1, img2, iters=2)
            return jnp.mean(jnp.abs(out))
        return f

    want_out = model0.apply(variables, img1, img2, iters=2)
    want_g = jax.grad(loss(model0))(variables["params"])
    for variant in (True, "corr"):
        m = create_model(RAFTStereoConfig(refinement_save_policy=variant))
        got_out = m.apply(variables, img1, img2, iters=2)
        np.testing.assert_allclose(np.asarray(got_out), np.asarray(want_out),
                                   atol=1e-5, err_msg=str(variant))
        got_g = jax.grad(loss(m))(variables["params"])
        # gradients accumulate the reassociation dust through the 2-iter
        # backward — wider absolute band than the forward outputs
        for a, b in zip(jax.tree_util.tree_leaves(want_g),
                        jax.tree_util.tree_leaves(got_g)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       atol=1e-4, err_msg=str(variant))


def test_save_policy_corr_with_fused_lookup_warns_and_matches():
    """'corr' + fused_lookup: no corr_feats tensor exists on the fused path,
    so the model must warn and fall back to full remat with outputs and
    grads unchanged (models/raft_stereo.py fallback branch). Width 352 keeps
    every pyramid level above the fused kernel's 2r+2 applicability bound."""
    import pytest as _pytest

    import jax
    import jax.numpy as jnp
    from raft_stereo_tpu.config import RAFTStereoConfig
    from raft_stereo_tpu.models import create_model, init_model

    shape = (1, 32, 352, 3)
    base = RAFTStereoConfig(fused_lookup=True, refinement_save_policy=False)
    model0, variables = init_model(jax.random.PRNGKey(0), base, shape)
    rng = np.random.default_rng(5)
    img1 = jnp.asarray(rng.uniform(0, 255, shape), jnp.float32)
    img2 = jnp.asarray(rng.uniform(0, 255, shape), jnp.float32)
    rest = {k: v for k, v in variables.items() if k != "params"}

    def loss(model):
        def f(p):
            out = model.apply({"params": p, **rest}, img1, img2, iters=2)
            return jnp.mean(jnp.abs(out))
        return f

    want_out = model0.apply(variables, img1, img2, iters=2)
    want_g = jax.grad(loss(model0))(variables["params"])

    m = create_model(RAFTStereoConfig(fused_lookup=True,
                                      refinement_save_policy="corr"))
    with _pytest.warns(UserWarning, match="no effect with fused_lookup"):
        got_out = m.apply(variables, img1, img2, iters=2)
    np.testing.assert_allclose(np.asarray(got_out), np.asarray(want_out),
                               atol=1e-6)
    with _pytest.warns(UserWarning, match="no effect with fused_lookup"):
        got_g = jax.grad(loss(m))(variables["params"])
    for a, b in zip(jax.tree_util.tree_leaves(want_g),
                    jax.tree_util.tree_leaves(got_g)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=1e-6)


def test_save_policy_without_remat_warns():
    """An explicit save policy with remat_refinement=False selects nothing;
    the config rejects the silent no-op loudly (ADVICE r4)."""
    import pytest as _pytest

    from raft_stereo_tpu.config import RAFTStereoConfig

    with _pytest.warns(UserWarning, match="remat_refinement=False"):
        RAFTStereoConfig(remat_refinement=False, refinement_save_policy=True)


def test_grad_accumulation_updates_every_k():
    """optax.MultiSteps wiring: params move only on each k-th micro-step."""
    import jax
    import jax.numpy as jnp
    from raft_stereo_tpu.config import RAFTStereoConfig, TrainConfig
    from raft_stereo_tpu.models import init_model
    from raft_stereo_tpu.training.optim import fetch_optimizer
    from raft_stereo_tpu.training.state import TrainState, make_train_step

    cfg = RAFTStereoConfig()
    tcfg = TrainConfig(num_steps=10, batch_size=1, grad_accum_steps=2)
    model, variables = init_model(jax.random.PRNGKey(0), cfg, (1, 32, 48, 3))
    tx = fetch_optimizer(tcfg)
    state = TrainState.create(variables, tx)
    rng = np.random.default_rng(0)
    batch = {
        "image1": jnp.asarray(rng.uniform(0, 255, (1, 32, 48, 3)), jnp.float32),
        "image2": jnp.asarray(rng.uniform(0, 255, (1, 32, 48, 3)), jnp.float32),
        "flow": jnp.asarray(rng.uniform(-4, 0, (1, 32, 48, 1)), jnp.float32),
        "valid": jnp.ones((1, 32, 48), jnp.float32),
    }
    step = make_train_step(model, tx, train_iters=2)
    leaf0 = np.asarray(state.params["fnet"]["conv2"]["kernel"])
    state, _ = step(state, batch)
    leaf1 = np.asarray(state.params["fnet"]["conv2"]["kernel"])
    np.testing.assert_array_equal(leaf1, leaf0)  # accumulating, no update yet
    state, _ = step(state, batch)
    leaf2 = np.asarray(state.params["fnet"]["conv2"]["kernel"])
    assert np.abs(leaf2 - leaf0).max() > 0  # k-th micro-step applied


def test_train_step_fused_matches_stacked():
    """make_train_step(fused_loss=True) takes one optimizer step identical
    (within fp tolerance) to the stacked-loss default."""
    import jax
    import jax.numpy as jnp
    from raft_stereo_tpu.config import RAFTStereoConfig, TrainConfig
    from raft_stereo_tpu.models import init_model
    from raft_stereo_tpu.training.optim import fetch_optimizer
    from raft_stereo_tpu.training.state import TrainState, make_train_step

    cfg = RAFTStereoConfig()
    tcfg = TrainConfig(batch_size=2, train_iters=2, num_steps=100,
                       image_size=(32, 48))
    model, variables = init_model(jax.random.PRNGKey(0), cfg, (1, 32, 48, 3))
    tx = fetch_optimizer(tcfg)
    rng = np.random.default_rng(1)
    batch = {
        "image1": jnp.asarray(rng.uniform(0, 255, (2, 32, 48, 3)), jnp.float32),
        "image2": jnp.asarray(rng.uniform(0, 255, (2, 32, 48, 3)), jnp.float32),
        "flow": jnp.asarray(rng.uniform(-8, 0, (2, 32, 48, 1)), jnp.float32),
        "valid": jnp.ones((2, 32, 48), jnp.float32),
    }

    s0 = TrainState.create(variables, tx)
    s_stacked, m_stacked = jax.jit(make_train_step(model, tx, 2))(s0, batch)
    s_fused, m_fused = jax.jit(
        make_train_step(model, tx, 2, fused_loss=True))(s0, batch)

    np.testing.assert_allclose(float(m_stacked["loss"]),
                               float(m_fused["loss"]), rtol=1e-5)
    la = jax.tree_util.tree_leaves(s_stacked.params)
    lb = jax.tree_util.tree_leaves(s_fused.params)
    for a, b in zip(la, lb):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=1e-5)


# ---- policy-selection pins (VERDICT r3 weak #5) ---------------------------
#
# The remat/save/fold/split heuristics carry one-point calibration constants
# measured on a 16 GB v5e. These tests pin WHICH policy engages at the
# SceneFlow-calibrated shapes, so a drifted estimate (or an edited constant)
# fails loudly here instead of silently mistuning the training step.

def test_policy_selection_pins_sceneflow_shapes():
    import jax.numpy as jnp

    from raft_stereo_tpu.config import RAFTStereoConfig
    from raft_stereo_tpu.models.raft_stereo import (
        fold_enc_saves_auto,
        refinement_save_policy_fits,
        upsample_chunk_count,
    )
    from raft_stereo_tpu.nn.gru import split_conv_engages

    cfg = RAFTStereoConfig(mixed_precision=True,
                           corr_storage_dtype="bfloat16")
    # SceneFlow recipe: 320x720 crop, 22 iters, 1/4-res grid 80x180.
    it, h, w = 22, 80, 180

    # Selective save policy: engages at b4 bf16 (1.36 GB est.), inverts to
    # full remat at b8 (measured 1085 vs 879 ms — PERF.md r2).
    assert refinement_save_policy_fits(cfg, it, 4, h, w, jnp.bfloat16)
    assert not refinement_save_policy_fits(cfg, it, 8, h, w, jnp.bfloat16)
    # fp32 halves the eligible batch.
    assert refinement_save_policy_fits(cfg, it, 2, h, w, None)
    assert not refinement_save_policy_fits(cfg, it, 4, h, w, None)

    # Folded encoder saves under "norms": fold at b8 (14.06 GB padded
    # measured), stay unfolded at b4 (folding cost -65 ms/step).
    assert fold_enc_saves_auto(cfg, 8, 320, 720)
    assert not fold_enc_saves_auto(cfg, 4, 320, 720)

    # Post-scan upsample chunking: b8 320x720 i22 busts the 1 GB budget and
    # chunks; b2 fits one-shot; and when even one iteration busts a tiny
    # budget the fallback is maximal chunking, never one-shot.
    assert upsample_chunk_count(it, 8, h, w, 4) > 1
    assert upsample_chunk_count(it, 2, h, w, 4) == 1
    assert upsample_chunk_count(it, 8, h, w, 4, budget=1) == it

    # Split-input gate convs: engage at the 80x180 train grid, not at the
    # realtime preset's 47x156 1/8-res grid (measured ~25% FPS regression
    # there).
    assert split_conv_engages(80, 180)
    assert not split_conv_engages(47, 156)
