"""Fault tolerance: atomic checkpoints, auto-resume, anomaly guard, loader
quarantine, and the resume-repositioning math (training/resilience.py;
drill companion: scripts/fault_drill.py — the end-to-end kill/corrupt/NaN
proofs run there, the unit contracts live here)."""

import json
import os
import signal
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_stereo_tpu.config import RAFTStereoConfig, TrainConfig
from raft_stereo_tpu.data.loader import Loader, infinite_batches
from raft_stereo_tpu.obs.events import (SCHEMA_VERSION, make_record,
                                        validate_record)
from raft_stereo_tpu.training import resilience as rz
from raft_stereo_tpu.training.checkpoint import (restore_train_state,
                                                 save_train_state)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def tiny_state(seed=0, scale=1.0):
    rng = np.random.default_rng(seed)
    return {
        "params": {"w": (scale * rng.standard_normal((4, 3))
                         ).astype(np.float32),
                   "b": np.zeros((3,), np.float32)},
        "opt_state": {"mu": np.zeros((4, 3), np.float32)},
        "step": np.int32(0),
    }


def corrupt_one_file(ckpt_path, mode="flip"):
    """Damage the largest file inside a checkpoint's state tree."""
    files = []
    for dirpath, _d, filenames in os.walk(os.path.join(ckpt_path, "state")):
        files += [os.path.join(dirpath, f) for f in filenames]
    victim = max(files, key=os.path.getsize)
    if mode == "truncate":
        with open(victim, "r+b") as f:
            f.truncate(max(os.path.getsize(victim) // 2, 1))
    else:
        with open(victim, "r+b") as f:
            f.seek(0)
            byte = f.read(1)
            f.seek(0)
            f.write(bytes([byte[0] ^ 0xFF]))
    return victim


# --- atomic checkpoint protocol ----------------------------------------------

def test_atomic_save_verify_restore_roundtrip(tmp_path):
    state = tiny_state()
    path = save_train_state(str(tmp_path), "run", state, step=7,
                            config_digest="abcd1234")
    assert path.endswith("7_run")
    manifest = rz.load_manifest(path)
    assert manifest["step"] == 7
    assert manifest["config_digest"] == "abcd1234"
    assert manifest["tree_hash"] == rz.tree_structure_hash(state)
    assert manifest["files"]  # per-file size+crc inventory
    ok, reason, _ = rz.verify_checkpoint(
        path, config_digest="abcd1234",
        tree_hash=rz.tree_structure_hash(state))
    assert ok, reason
    restored = restore_train_state(path, tiny_state(seed=99))
    np.testing.assert_array_equal(restored["params"]["w"],
                                  state["params"]["w"])
    # no temp dirs left behind
    assert not [e for e in os.listdir(tmp_path) if e.startswith(".")]


@pytest.mark.parametrize("mode", ["truncate", "flip"])
def test_verify_detects_damage(tmp_path, mode):
    path = save_train_state(str(tmp_path), "run", tiny_state(), step=3)
    ok, _, _ = rz.verify_checkpoint(path)
    assert ok
    corrupt_one_file(path, mode=mode)
    ok, reason, _ = rz.verify_checkpoint(path)
    assert not ok
    assert ("size mismatch" if mode == "truncate" else "crc") in reason


def test_verify_rejects_digest_and_structure_mismatch(tmp_path):
    state = tiny_state()
    path = save_train_state(str(tmp_path), "run", state, step=3,
                            config_digest="aaaa")
    ok, reason, _ = rz.verify_checkpoint(path, config_digest="bbbb")
    assert not ok and "config digest" in reason
    other = {"params": {"w": np.zeros((2, 2), np.float32)}}
    ok, reason, _ = rz.verify_checkpoint(
        path, tree_hash=rz.tree_structure_hash(other))
    assert not ok and "structure" in reason


def test_auto_resume_skips_corrupt_newest(tmp_path):
    state = tiny_state()
    old = save_train_state(str(tmp_path), "run", state, step=2,
                           config_digest="d1")
    new = save_train_state(str(tmp_path), "run", state, step=4,
                           config_digest="d1")
    corrupt_one_file(new, mode="truncate")
    best, reports = rz.find_latest_valid(str(tmp_path), "run",
                                         config_digest="d1")
    assert best == old
    assert [r["ok"] for r in reports] == [False, True]
    assert reports[0]["path"] == new and "size mismatch" in reports[0][
        "reason"]
    # a missing manifest (legacy/torn checkpoint) is skipped, not fatal
    os.remove(os.path.join(new, "MANIFEST.json"))
    best2, reports2 = rz.find_latest_valid(str(tmp_path), "run")
    assert best2 == old and "manifest" in reports2[0]["reason"]


def test_auto_resume_skips_foreign_digest(tmp_path):
    state = tiny_state()
    save_train_state(str(tmp_path), "other-config", state, step=9)
    theirs = save_train_state(str(tmp_path), "run", state, step=9,
                              config_digest="theirs")
    mine = save_train_state(str(tmp_path), "run", state, step=5,
                            config_digest="mine")
    # rotate-protection renamed nothing (different steps); auto-resume must
    # pick MY step-5 checkpoint over the foreign step-9 one
    best, reports = rz.find_latest_valid(str(tmp_path), "run",
                                         config_digest="mine")
    assert best == mine
    assert reports[0]["path"] == theirs and not reports[0]["ok"]


def test_clobber_same_digest_overwrites_in_place(tmp_path):
    a = tiny_state(seed=1)
    b = tiny_state(seed=2)
    p1 = save_train_state(str(tmp_path), "run", a, config_digest="same")
    p2 = save_train_state(str(tmp_path), "run", b, config_digest="same")
    assert p1 == p2
    assert not os.path.exists(p1 + ".bak")
    restored = restore_train_state(p1, tiny_state(seed=99))
    np.testing.assert_array_equal(restored["params"]["w"], b["params"]["w"])


def test_clobber_mismatched_digest_rotates_to_bak(tmp_path):
    a = tiny_state(seed=1)
    b = tiny_state(seed=2)
    p1 = save_train_state(str(tmp_path), "run", a, config_digest="old-run")
    p2 = save_train_state(str(tmp_path), "run", b, config_digest="new-run")
    assert p1 == p2
    # the old run's checkpoint survived, rotated aside
    bak = p1 + ".bak"
    assert os.path.isdir(bak)
    old = restore_train_state(bak, tiny_state(seed=99))
    np.testing.assert_array_equal(old["params"]["w"], a["params"]["w"])
    new = restore_train_state(p2, tiny_state(seed=99))
    np.testing.assert_array_equal(new["params"]["w"], b["params"]["w"])


def test_retention_keeps_last_k_and_every_nth(tmp_path):
    state = tiny_state()
    for step in (2, 4, 6, 8, 10):
        save_train_state(str(tmp_path), "run", state, step=step)
    deleted = rz.apply_retention(str(tmp_path), "run", keep_last=2,
                                 keep_every=4)
    kept = sorted(e for e in os.listdir(tmp_path) if e.endswith("_run"))
    # newest two (8, 10) plus the multiples of 4 (4, 8); 2 and 6 swept
    assert kept == ["10_run", "4_run", "8_run"]
    assert sorted(os.path.basename(d) for d in deleted) == ["2_run",
                                                            "6_run"]


def test_retention_rides_save(tmp_path):
    state = tiny_state()
    for step in (1, 2, 3, 4):
        save_train_state(str(tmp_path), "run", state, step=step,
                         keep_last=2)
    kept = sorted(e for e in os.listdir(tmp_path) if e.endswith("_run"))
    assert kept == ["3_run", "4_run"]


def test_config_digest_sensitivity():
    m1, t1 = RAFTStereoConfig(), TrainConfig()
    assert rz.config_digest(m1, t1) == rz.config_digest(
        RAFTStereoConfig(), TrainConfig())
    # run-identity fields move the digest ...
    assert rz.config_digest(m1, t1) != rz.config_digest(
        RAFTStereoConfig(hidden_dims=(96, 96, 96)), t1)
    assert rz.config_digest(m1, t1) != rz.config_digest(
        m1, TrainConfig(lr=1e-3))
    # ... cosmetic ones (name, dirs, cadence) do not: renaming a run or
    # moving its artifacts must not orphan its checkpoints
    assert rz.config_digest(m1, t1) == rz.config_digest(
        m1, TrainConfig(name="other", ckpt_dir="elsewhere",
                        validation_frequency=123,
                        checkpoint_frequency=7))


def test_tree_structure_hash_tracks_structure():
    a = tiny_state()
    assert rz.tree_structure_hash(a) == rz.tree_structure_hash(tiny_state())
    b = tiny_state()
    b["params"]["w"] = b["params"]["w"].astype(np.float16)
    assert rz.tree_structure_hash(a) != rz.tree_structure_hash(b)
    c = tiny_state()
    c["params"]["extra"] = np.zeros(1, np.float32)
    assert rz.tree_structure_hash(a) != rz.tree_structure_hash(c)


def test_state_is_finite():
    good = tiny_state()
    assert rz.state_is_finite(good)
    bad = tiny_state()
    bad["params"]["w"][0, 0] = np.nan
    assert not rz.state_is_finite(bad)


# --- signals + anomaly policy ------------------------------------------------

def test_signal_guard_records_and_restores():
    prev = signal.getsignal(signal.SIGTERM)
    with rz.SignalGuard() as guard:
        assert guard.installed
        assert not guard.requested
        os.kill(os.getpid(), signal.SIGTERM)
        assert guard.requested
        assert guard.signame == "SIGTERM"
    assert signal.getsignal(signal.SIGTERM) is prev


class _Bus:
    def __init__(self):
        self.events = []

    def emit(self, event, **payload):
        self.events.append(dict(payload, event=event))


def test_anomaly_policy_halts_after_consecutive_skips():
    bus = _Bus()
    policy = rz.AnomalyPolicy(max_consecutive=3, telemetry=bus)
    policy.observe(True, 1, grad_norm=float("nan"))
    policy.observe(True, 2)
    policy.observe(False, 3)  # streak broken: counter resets
    policy.observe(True, 4)
    policy.observe(True, 5)
    with pytest.raises(rz.AnomalyHalt):
        policy.observe(True, 6)
    kinds = [e["event"] + ":" + e["kind"] for e in bus.events]
    assert kinds.count("anomaly:nonfinite_grad") == 5
    assert kinds[-1] == "anomaly:halt"
    assert policy.total == 5


def test_anomaly_policy_zero_never_halts():
    policy = rz.AnomalyPolicy(max_consecutive=0)
    for step in range(1, 50):
        policy.observe(True, step)
    assert policy.total == 49


# --- schema v5 ---------------------------------------------------------------

def test_schema_v5_events_validate():
    assert SCHEMA_VERSION >= 5
    recs = [
        make_record("preempt", signal="SIGTERM", step=123),
        make_record("resume", step=120, path="/ckpts/120_run"),
        make_record("ckpt_integrity", path="/ckpts/120_run", ok=False,
                    reason="crc mismatch"),
        make_record("anomaly", kind="nonfinite_grad", step=7,
                    grad_norm=None, consecutive=1, skipped_total=1),
    ]
    for rec in recs:
        assert validate_record(rec) == [], rec
    # required fields enforced
    assert validate_record(make_record("preempt", step=1)) != []
    # a v4-stamped v5 event is schema drift
    stale = make_record("resume", step=1, path="x")
    stale["schema"] = 4
    assert any("introduced in schema 5" in e for e in validate_record(stale))
    # v4 artifacts still lint clean
    old = make_record("lint", source="x", findings=0)
    old["schema"] = 4
    assert validate_record(old) == []


# --- loader I/O resilience ---------------------------------------------------

class ArrayDataset:
    """Deterministic rng-consuming stub: sample i is f(i, rng)."""

    def __init__(self, n=8, fail=(), fail_times=None):
        self.n = n
        self.fail = set(fail)
        # index -> remaining failures (None = fail forever)
        self.fail_times = dict(fail_times or {})
        self.attempts = {}

    def __len__(self):
        return self.n

    def sample(self, index, rng):
        self.attempts[index] = self.attempts.get(index, 0) + 1
        if index in self.fail:
            raise IOError(f"decode failed for {index}")
        remaining = self.fail_times.get(index)
        if remaining:
            self.fail_times[index] = remaining - 1
            raise IOError(f"transient failure for {index}")
        jitter = rng.random(3).astype(np.float32)
        return {
            "image1": np.full((4, 6, 3), index, np.float32) + jitter[0],
            "image2": np.full((4, 6, 3), index, np.float32) + jitter[1],
            "flow": np.full((4, 6, 1), -index, np.float32) + jitter[2],
            "valid": np.ones((4, 6), np.float32),
        }


def collect(loader, n):
    out = []
    stream = infinite_batches(loader)
    for _ in range(n):
        out.append(next(stream))
    return out


def batches_equal(a, b):
    return all(np.array_equal(x[k], y[k])
               for x, y in zip(a, b) for k in x)


def test_loader_retry_recovers_transient_failures():
    clean = collect(Loader(ArrayDataset(), 2, seed=3, num_workers=2,
                           retry_backoff_s=0.001), 8)
    flaky_ds = ArrayDataset(fail_times={1: 1, 5: 2})
    flaky = Loader(flaky_ds, 2, seed=3, num_workers=2, decode_retries=2,
                   retry_backoff_s=0.001)
    got = collect(flaky, 8)
    assert batches_equal(clean, got)
    assert not flaky.quarantined  # retries absorbed it; no substitution


def test_loader_quarantine_is_deterministic_and_philox_preserving():
    n_batches = 8
    clean = collect(Loader(ArrayDataset(), 2, seed=3, num_workers=2), n_batches)
    records = []
    broken = Loader(ArrayDataset(fail=(5,)), 2, seed=3, num_workers=2,
                    decode_retries=1, retry_backoff_s=0.001)
    broken.quarantine_hook = records.append
    got = collect(broken, n_batches)
    assert broken.quarantined and records
    rec = broken.quarantined[0]
    assert rec["index"] == 5 and rec["substitute"] == 6
    # every slot that did NOT hit the broken sample is bitwise identical to
    # the clean stream (the Philox keys of other slots were never touched)
    diff_fields = 0
    for cb, gb in zip(clean, got):
        for k in cb:
            same = np.array_equal(cb[k], gb[k])
            if not same:
                diff_fields += 1
    # index 5 appears once per epoch; 8 batches of 2 over 8 samples = 2
    # epochs -> 2 substituted slots, 3 differing fields each (valid is
    # all-ones either way)
    assert diff_fields == 2 * 3
    # the substitution itself is deterministic: a second run quarantines
    # identically
    broken2 = Loader(ArrayDataset(fail=(5,)), 2, seed=3, num_workers=2,
                     decode_retries=1, retry_backoff_s=0.001)
    got2 = collect(broken2, n_batches)
    assert batches_equal(got, got2)


def test_loader_all_broken_fails_fast():
    ds = ArrayDataset(n=4, fail=(0, 1, 2, 3))
    loader = Loader(ds, 2, seed=0, num_workers=1, decode_retries=0,
                    retry_backoff_s=0.001)
    with pytest.raises(IOError):
        collect(loader, 1)


# --- resume repositioning math (the Philox exact-resume contract) ------------

def reposition(loader, step):
    """The trainer's restore-time formula (trainer.py)."""
    loader.epoch = step // max(len(loader), 1)
    loader.start_batch = step % max(len(loader), 1)


@pytest.mark.parametrize("n,batch", [(8, 2), (8, 8), (6, 4)])
def test_resume_repositioning_matches_uninterrupted_stream(n, batch):
    """Pin loader.epoch/start_batch reconstruction against ground truth:
    resuming at ANY step reproduces the uninterrupted stream's suffix,
    including epoch boundaries, len(loader)==1 (n==batch) and the
    drop_last partial-epoch case (6, 4)."""
    total = 10
    oracle = collect(Loader(ArrayDataset(n=n), batch, seed=11,
                            num_workers=2), total)
    for step in range(total):
        resumed = Loader(ArrayDataset(n=n), batch, seed=11, num_workers=2)
        reposition(resumed, step)
        got = collect(resumed, total - step)
        assert batches_equal(oracle[step:], got), f"resume at step {step}"


def test_resume_repositioning_counts_micro_steps_under_grad_accum():
    """grad_accum_steps>1 must NOT change the mapping: state.step counts
    micro-steps (every consumed batch advances it, trainer.py), so the
    formula is accumulation-agnostic — resuming at micro-step s always
    lands on batch s of the stream."""
    total, accum = 9, 3
    oracle = collect(Loader(ArrayDataset(), 2, seed=5, num_workers=2), total)
    # an interrupted run that stopped mid-accumulation-window (micro-step 7
    # inside the third window of 3)
    micro_step = 7
    assert micro_step % accum != 0
    resumed = Loader(ArrayDataset(), 2, seed=5, num_workers=2)
    reposition(resumed, micro_step)
    got = collect(resumed, total - micro_step)
    assert batches_equal(oracle[micro_step:], got)


# --- graftlint follow-through: the naive NaN check vs the shipped guard ------

NAIVE_HOST_CHECK = '''
import jax
import jax.numpy as jnp
import optax


def train_step(state, batch):
    grads = jax.grad(lambda p: jnp.sum(p * batch))(state)
    grad_norm = optax.global_norm(grads)
    if float(grad_norm) > 0 and bool(jnp.isfinite(grad_norm)):
        return state - grads
    return state


step = jax.jit(train_step)
'''


def test_tracer_unsafe_fires_on_naive_host_nan_check():
    """The tempting implementation — `float(grad_norm)` per step — is a
    host sync per step (and a ConcretizationTypeError under jit); the AST
    engine must flag it."""
    from raft_stereo_tpu.analysis.ast_rules import lint_source
    findings = lint_source(NAIVE_HOST_CHECK, "fixture/naive_guard.py")
    unsafe = [f for f in findings if f.rule == "tracer-unsafe"]
    assert len(unsafe) >= 2  # float() and bool()
    assert all(f.severity == "error" for f in unsafe)


def test_shipped_guard_module_is_tracer_safe():
    """training/state.py (the lax.cond guard) and resilience.py lint clean
    under the same engine."""
    from raft_stereo_tpu.analysis.ast_rules import lint_source
    for rel in ("raft_stereo_tpu/training/state.py",
                "raft_stereo_tpu/training/resilience.py"):
        with open(os.path.join(REPO, rel)) as f:
            findings = lint_source(f.read(), rel)
        errors = [f for f in findings
                  if f.rule in ("tracer-unsafe", "wall-clock")
                  and f.severity == "error"]
        assert errors == [], [f.message for f in errors]


@pytest.fixture(scope="module")
def guarded_step_setup():
    """One tiny model + optimizer shared by the device-guard tests."""
    from raft_stereo_tpu.models import init_model
    from raft_stereo_tpu.training.optim import fetch_optimizer
    from raft_stereo_tpu.training.state import TrainState

    model_cfg = RAFTStereoConfig(hidden_dims=(32, 32, 32))
    cfg = TrainConfig(num_steps=10, batch_size=1)
    model, variables = init_model(jax.random.PRNGKey(0), model_cfg,
                                  (1, 32, 48, 3))
    tx = fetch_optimizer(cfg)
    state = TrainState.create(variables, tx)
    rng = np.random.default_rng(0)
    batch = {
        "image1": jnp.asarray(rng.uniform(0, 255, (1, 32, 48, 3)),
                              jnp.float32),
        "image2": jnp.asarray(rng.uniform(0, 255, (1, 32, 48, 3)),
                              jnp.float32),
        "flow": jnp.asarray(rng.uniform(-8, 0, (1, 32, 48, 1)),
                            jnp.float32),
        "valid": jnp.ones((1, 32, 48), jnp.float32),
    }
    return model, tx, state, batch


def test_minimal_cond_guard_is_host_sync_clean():
    """The guard's shape — global-norm finiteness into a lax.cond over
    the update — introduces no host-sync primitive (cheap structural
    check; the REAL train_step[update] lowering is linted by the graph
    engine in `cli lint`, a rehearsal leg, and exercised end-to-end by
    scripts/fault_drill.py)."""
    import optax

    from raft_stereo_tpu.analysis.graph_rules import (GraphTarget,
                                                      rule_host_sync)

    def guarded_update(params, grads):
        gnorm = optax.global_norm(grads)
        ok = jnp.isfinite(gnorm)
        return jax.lax.cond(
            ok, lambda o: jax.tree.map(lambda p, g: p - 0.1 * g, *o),
            lambda o: o[0], (params, grads)), gnorm

    tree = {"w": jnp.ones((4, 3)), "b": jnp.zeros((3,))}
    jaxpr = jax.make_jaxpr(guarded_update)(tree, tree)
    target = GraphTarget(name="fixture", cfg=RAFTStereoConfig(),
                         closed_jaxpr=jaxpr)
    assert rule_host_sync(target, {}) == []
    # and the cond is actually there (the skip is a real branch, not DCE'd)
    from raft_stereo_tpu.obs.xla import iter_eqns
    prims = {e.primitive.name for e, _ in iter_eqns(jaxpr)}
    assert "cond" in prims


@pytest.mark.slow  # full (tiny-shape) train-step compile, ~40 s XLA-CPU
def test_device_guard_skips_nan_update_without_host_sync(guarded_step_setup):
    """The shipped guard on the real model: lax.cond on device — a NaN
    batch skips the optimizer update (params bitwise untouched, step still
    advances, skipped_updates=1 in metrics), a good batch applies it; and
    the guarded jaxpr contains no host-sync primitive. (The fault drill
    proves the same end-to-end through the CLI; this is the in-process
    pin.)"""
    from raft_stereo_tpu.analysis.graph_rules import (GraphTarget,
                                                      rule_host_sync)
    from raft_stereo_tpu.training.state import make_train_step

    model, tx, state, batch = guarded_step_setup
    step = jax.jit(make_train_step(model, tx, 1, anomaly_guard=True))

    # host-sync rule stays green over the guarded lowering
    jaxpr = jax.make_jaxpr(step)(state, batch)
    target = GraphTarget(name="train_step[update]",
                         cfg=RAFTStereoConfig(hidden_dims=(32, 32, 32)),
                         closed_jaxpr=jaxpr)
    assert rule_host_sync(target, {}) == []

    s1, m1 = step(state, batch)
    assert float(m1["skipped_updates"]) == 0.0
    assert np.isfinite(float(m1["grad_norm"]))
    p_good = jax.device_get(s1.params)

    nan_batch = dict(batch, image1=jnp.full_like(batch["image1"], jnp.nan))
    s2, m2 = step(s1, nan_batch)
    assert float(m2["skipped_updates"]) == 1.0
    assert not np.isfinite(float(m2["grad_norm"]))
    assert int(s2.step) == 2  # consumed-batch counter still advances
    for a, b in zip(jax.tree.leaves(p_good),
                    jax.tree.leaves(jax.device_get(s2.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # and a subsequent good batch trains on, with finite params
    s3, m3 = step(s2, batch)
    assert float(m3["skipped_updates"]) == 0.0
    assert rz.state_is_finite(s3)


def test_host_sync_rule_fires_on_callback_guard():
    """The other naive alternative — checking finiteness through a host
    callback inside the step — must trip graftlint's host-sync rule."""
    from raft_stereo_tpu.analysis.graph_rules import (GraphTarget,
                                                      rule_host_sync)

    def callback_guard(x):
        ok = jax.pure_callback(lambda v: np.isfinite(v),
                               jax.ShapeDtypeStruct((), np.bool_),
                               jnp.sum(x))
        return jnp.where(ok, x, 0.0)

    jaxpr = jax.make_jaxpr(callback_guard)(jnp.ones((4,)))
    target = GraphTarget(name="fixture", cfg=RAFTStereoConfig(),
                         closed_jaxpr=jaxpr)
    findings = rule_host_sync(target, {})
    assert findings and findings[0].severity == "error"
    assert "pure_callback" in findings[0].message


# --- emergency checkpoint on crash (the except-BaseException satellite) ------

class _Tel(_Bus):
    def checkpoint(self, step, path, **payload):
        self.events.append(dict(payload, event="checkpoint", step=step,
                                path=path))


def test_emergency_checkpoint_saves_finite_state(tmp_path):
    from raft_stereo_tpu.training.trainer import _emergency_checkpoint

    cfg = TrainConfig(name="crashy", ckpt_dir=str(tmp_path))
    tel = _Tel()
    path = _emergency_checkpoint(RuntimeError("boom"), tiny_state(), cfg,
                                 tel, 17, "dig")
    assert path is not None and path.endswith("17_crashy")
    assert tel.events[-1]["event"] == "checkpoint"
    assert tel.events[-1]["reason"] == "crash"
    ok, reason, manifest = rz.verify_checkpoint(path, config_digest="dig")
    assert ok, reason
    assert manifest["reason"] == "crash"
    # --restore_ckpt auto would resume from it
    best, _ = rz.find_latest_valid(str(tmp_path), "crashy",
                                   config_digest="dig")
    assert best == path


def test_emergency_checkpoint_refuses_nonfinite_state(tmp_path):
    from raft_stereo_tpu.training.trainer import _emergency_checkpoint

    bad = tiny_state()
    bad["params"]["w"][0, 0] = np.inf
    cfg = TrainConfig(name="crashy", ckpt_dir=str(tmp_path))
    tel = _Tel()
    path = _emergency_checkpoint(RuntimeError("boom"), bad, cfg, tel, 17,
                                 "dig")
    assert path is None
    assert not os.listdir(tmp_path)  # nothing (not even a temp) left
    assert tel.events[-1] == {"event": "anomaly", "kind": "nonfinite_state",
                              "step": 17}


def test_emergency_checkpoint_skipped_on_anomaly_halt(tmp_path):
    from raft_stereo_tpu.training.trainer import _emergency_checkpoint

    cfg = TrainConfig(name="crashy", ckpt_dir=str(tmp_path))
    tel = _Tel()
    path = _emergency_checkpoint(rz.AnomalyHalt("poisoned"), tiny_state(),
                                 cfg, tel, 17, "dig")
    # rollback-by-design: the halt must leave the last durable checkpoint
    # as the newest one, so nothing is saved and nothing emitted
    assert path is None and tel.events == []
    assert not os.listdir(tmp_path)


def _make_sceneflow_tree(root):
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from test_trainer import _make_sceneflow_tree as mk
    mk(root)


@pytest.mark.slow
def test_crash_saves_emergency_checkpoint(tmp_path, monkeypatch):
    """A crash mid-run (here: validation raising) must leave a
    reason="crash" checkpoint holding the latest state, then re-raise —
    and --restore_ckpt auto must be able to resume from it."""
    from raft_stereo_tpu.training import trainer as trainer_mod

    _make_sceneflow_tree(tmp_path)

    def boom(predictor, cfg):
        raise RuntimeError("injected validation crash")

    monkeypatch.setattr(trainer_mod, "_maybe_validate_things", boom)
    model_cfg = RAFTStereoConfig(hidden_dims=(32, 32, 32))
    cfg = TrainConfig(
        name="crashy", batch_size=2, num_steps=4, image_size=(48, 64),
        train_iters=1, valid_iters=1, data_root=str(tmp_path),
        ckpt_dir=str(tmp_path / "ckpts"), validation_frequency=2,
        checkpoint_frequency=100, num_workers=2, data_parallel=1,
        seq_parallel=1, lr=1e-4, run_dir=str(tmp_path / "runs"),
        stall_deadline_s=None)
    with pytest.raises(RuntimeError, match="injected validation crash"):
        trainer_mod.train(model_cfg, cfg)

    from raft_stereo_tpu.obs import read_events
    events = read_events(str(tmp_path / "runs" / "crashy" / "events.jsonl"))
    crash = [e for e in events if e["event"] == "checkpoint"
             and e.get("reason") == "crash"]
    assert crash and crash[0]["step"] == 2
    assert os.path.isdir(crash[0]["path"])
    ok, reason, manifest = rz.verify_checkpoint(
        crash[0]["path"], config_digest=rz.config_digest(model_cfg, cfg))
    assert ok, reason
    assert manifest["reason"] == "crash"
    end = events[-1]
    assert end["event"] == "run_end" and end["ok"] is False

    # auto-resume picks the emergency checkpoint up and finishes the run
    cfg2 = TrainConfig(**{**dataclasses_asdict(cfg),
                          "restore_ckpt": "auto",
                          "validation_frequency": 100,
                          "run_dir": str(tmp_path / "runs2")})
    final = trainer_mod.train(model_cfg, cfg2)
    events2 = read_events(
        str(tmp_path / "runs2" / "crashy" / "events.jsonl"))
    resume = next(e for e in events2 if e["event"] == "resume")
    assert resume["step"] == 2 and resume["path"] == crash[0]["path"]
    integ = [e for e in events2 if e["event"] == "ckpt_integrity"]
    assert integ and integ[-1]["ok"] is True
    restored = restore_train_state(final, None)
    assert int(np.asarray(restored["step"])) == 4


def dataclasses_asdict(cfg):
    import dataclasses
    return dataclasses.asdict(cfg)


# --- drill plumbing ----------------------------------------------------------

def test_drill_record_log_and_tree_fixture(tmp_path):
    """The drill's synthetic tree is loadable by the real dataloader (kept
    in sync with the trainer tests' fixture), and a green drill record
    exists under runs/fault_drill/ once the drill has run in this repo."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "fault_drill", os.path.join(REPO, "scripts", "fault_drill.py"))
    drill = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(drill)
    drill.make_sceneflow_tree(str(tmp_path), n=2)
    from raft_stereo_tpu.data.datasets import fetch_dataloader
    cfg = TrainConfig(batch_size=2, image_size=(48, 64),
                      data_root=str(tmp_path), num_workers=1)
    loader = fetch_dataloader(cfg)
    assert len(loader) >= 1
    # the banked drill evidence (written by scripts/fault_drill.py runs)
    log = os.path.join(REPO, "runs", "fault_drill", "drills.jsonl")
    if os.path.exists(log):
        with open(log) as f:
            records = [json.loads(line) for line in f if line.strip()]
        summaries = [r for r in records if r.get("drill") == "summary"]
        assert summaries and summaries[-1]["ok"]
