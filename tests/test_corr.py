import numpy as np
import jax.numpy as jnp
import pytest

from raft_stereo_tpu.ops.corr import (
    all_pairs_correlation,
    corr_lookup,
    init_corr,
)
from raft_stereo_tpu.ops.geometry import coords_grid


def _random_fmaps(rng, b=2, h=6, w=16, d=8):
    f1 = rng.standard_normal((b, h, w, d)).astype(np.float32)
    f2 = rng.standard_normal((b, h, w, d)).astype(np.float32)
    return jnp.asarray(f1), jnp.asarray(f2)


class TestAllPairs:
    def test_manual_small(self):
        f1 = jnp.asarray([[[[1.0, 0.0], [0.0, 2.0]]]])  # (1,1,2,2)
        f2 = jnp.asarray([[[[1.0, 1.0], [3.0, 0.0]]]])
        corr = all_pairs_correlation(f1, f2)
        s = np.sqrt(2.0)
        np.testing.assert_allclose(
            corr[0, 0], np.array([[1.0, 3.0], [2.0, 0.0]]) / s, rtol=1e-6)


class TestRegAltEquivalence:
    """'reg' and 'alt' are each other's oracles (SURVEY §4: numerical parity
    by flag). On integer coords both reduce to windowed dot products."""

    @pytest.mark.parametrize("impl", ["alt"])
    def test_alt_matches_reg(self, impl):
        rng = np.random.default_rng(10)
        f1, f2 = _random_fmaps(rng)
        b, h, w, _ = f1.shape
        reg = init_corr("reg", f1, f2, num_levels=4, radius=4)
        alt = init_corr(impl, f1, f2, num_levels=4, radius=4)
        # Only x is perturbed: the epipolar constraint keeps y on integer rows
        # (core/raft_stereo.py:120), which is what alt-style sampling relies on.
        dx = rng.uniform(-2, 2, size=(b, h, w, 1)).astype(np.float32)
        coords = coords_grid(b, h, w) + jnp.asarray(
            np.concatenate([dx, np.zeros_like(dx)], axis=-1))
        out_reg = corr_lookup(reg, coords)
        out_alt = corr_lookup(alt, coords)
        assert out_reg.shape == (b, h, w, 36)
        np.testing.assert_allclose(np.asarray(out_reg), np.asarray(out_alt),
                                   rtol=1e-4, atol=1e-5)

    def test_integer_coord_lookup_is_window_dot(self):
        """At level 0 and integer coords, lookup tap k equals
        <f1[x], f2[x-4+k]> / sqrt(D) (zero outside the image)."""
        rng = np.random.default_rng(11)
        f1, f2 = _random_fmaps(rng, b=1, h=2, w=10, d=4)
        state = init_corr("reg", f1, f2, num_levels=1, radius=4)
        coords = coords_grid(1, 2, 10)
        out = np.asarray(corr_lookup(state, coords))
        f1n, f2n = np.asarray(f1), np.asarray(f2)
        for x in range(10):
            for k in range(9):
                src = x - 4 + k
                want = 0.0
                if 0 <= src < 10:
                    want = f1n[0, 0, x] @ f2n[0, 0, src] / np.sqrt(4.0)
                np.testing.assert_allclose(out[0, 0, x, k], want, rtol=1e-5,
                                           atol=1e-6)


class TestTorchReferenceParity:
    """Numerical parity against the actual reference implementations, used as
    oracles via import (no code copied). Skipped when the checkout is absent."""

    def test_reg_matches_corrblock1d(self, torch_reference):
        import torch
        from core.corr import CorrBlock1D

        rng = np.random.default_rng(12)
        b, h, w, d = 2, 5, 32, 6
        f1 = rng.standard_normal((b, h, w, d)).astype(np.float32)
        f2 = rng.standard_normal((b, h, w, d)).astype(np.float32)
        coords = np.asarray(coords_grid(b, h, w)) + rng.uniform(
            -3, 3, size=(b, h, w, 2)).astype(np.float32)

        block = CorrBlock1D(torch.from_numpy(f1).permute(0, 3, 1, 2),
                            torch.from_numpy(f2).permute(0, 3, 1, 2),
                            num_levels=4, radius=4)
        want = block(torch.from_numpy(coords).permute(0, 3, 1, 2))
        want = want.permute(0, 2, 3, 1).numpy()

        state = init_corr("reg", jnp.asarray(f1), jnp.asarray(f2),
                          num_levels=4, radius=4)
        got = np.asarray(corr_lookup(state, jnp.asarray(coords)))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_alt_matches_pytorch_alternate(self, torch_reference):
        import torch
        from core.corr import PytorchAlternateCorrBlock1D

        rng = np.random.default_rng(13)
        b, h, w, d = 1, 4, 16, 8
        f1 = rng.standard_normal((b, h, w, d)).astype(np.float32)
        f2 = rng.standard_normal((b, h, w, d)).astype(np.float32)
        coords = np.asarray(coords_grid(b, h, w)) + rng.uniform(
            -2, 2, size=(b, h, w, 2)).astype(np.float32)
        coords[..., 1] = np.asarray(coords_grid(b, h, w))[..., 1]  # exact rows

        block = PytorchAlternateCorrBlock1D(
            torch.from_numpy(f1).permute(0, 3, 1, 2),
            torch.from_numpy(f2).permute(0, 3, 1, 2), num_levels=4, radius=4)
        want = block(torch.from_numpy(coords).permute(0, 3, 1, 2))
        want = want.permute(0, 2, 3, 1).numpy()

        state = init_corr("alt", jnp.asarray(f1), jnp.asarray(f2),
                          num_levels=4, radius=4)
        got = np.asarray(corr_lookup(state, jnp.asarray(coords)))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
