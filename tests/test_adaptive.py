"""Adaptive iteration: the compiled early-exit from recorded
convergence policies (ISSUE 17).

* the zero-threshold pin: ``adaptive_tau=0.0`` never freezes a sample,
  so the adaptive program's flow is bitwise-equal to the fixed scan and
  every sample reports the full budget;
* ``adaptive_tau=None`` keeps the traced test-mode program byte-identical
  to the prior one (the no-policy HLO pin), and an ``adaptive=False``
  predictor with a policy on hand stays bitwise-equal to a plain one;
* masked-scan freeze semantics vs a NumPy oracle on the recorded fixed
  curves: per-sample iters_taken, the strict ``r < tau`` exit, the
  ``min_iters`` floor, frozen iterations recording 0.0 residual rows;
* ``adaptive_mode="while_loop"`` (whole-batch dynamic trip) agrees with
  the masked scan sample-for-sample;
* policy schema lint: a doctored ``iter_policy.json`` fails at load with
  a named reason — entry/provenance tau mismatch, budget above the
  recorded budget, τ=0, missing coverage — and fails StereoPredictor
  construction, never silently mis-budgets the graph;
* StereoPredictor policy resolution: padded-bucket lookup, the budget
  capping the requested trip count, uncovered buckets falling back to
  the fixed path, and the adaptive guards (no policy / numerics taps);
* serving: adaptive and fixed flavors coexist in ONE server — the
  policy digest is part of the compiled-program identity (BucketKey's
  ``@digest`` label), covered requests retire with iters_taken + the
  slo "iters" rollup + Prometheus gauges, uncovered ones stay on the
  fixed path with none of that.
"""

import dataclasses
import json

import numpy as np
import pytest

import jax

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.inference import StereoPredictor
from raft_stereo_tpu.models import create_model, init_model
from raft_stereo_tpu.obs import Telemetry, read_events
from raft_stereo_tpu.obs import converge as cv
from raft_stereo_tpu.obs.validate import check_iter_policy, check_path

H, W = 32, 64          # /32-exact: raw == padded, bucket "32x64"
ITERS = 3


@pytest.fixture(scope="module")
def tiny():
    cfg = RAFTStereoConfig(hidden_dims=(32, 32, 32))
    model, variables = init_model(jax.random.PRNGKey(0), cfg, (1, H, W, 3))
    return cfg, model, variables


def _frames(seeds, h=H, w=W):
    rng_pairs = [np.random.default_rng(s) for s in seeds]
    im1 = np.stack([r.integers(0, 255, (h, w, 3)).astype(np.float32)
                    for r in rng_pairs])
    im2 = np.stack([r.integers(0, 255, (h, w, 3)).astype(np.float32)
                    for r in rng_pairs])
    return im1, im2


def _entry(tau, budget, min_iters=1, recorded=None):
    """One schema-valid policy entry (provenance row included)."""
    return {"tau": tau, "budget": budget, "min_iters": min_iters,
            "provenance": {"source": "eval:test",
                           "row": {"tau": tau,
                                   "budget": recorded or budget}}}


def _policy(buckets, default=None):
    doc = {"kind": "iter_policy", "version": 1, "source_run": "runs/test",
           "buckets": buckets}
    if default is not None:
        doc["default"] = default
    assert check_iter_policy(doc) == []
    return doc


# --------------------------------------------------- model-level pins

def test_tau_zero_is_bitwise_parity(tiny):
    """τ=0 with strict ``r < tau`` freezes nothing: flow bitwise-equal to
    the fixed scan, full budget reported for every sample."""
    _, model, variables = tiny
    im1, im2 = _frames([0, 1])
    fixed_lr, fixed_up, fixed_res = model.apply(
        variables, im1, im2, iters=ITERS, test_mode=True,
        iter_metrics="per_sample")
    lr, up, res, taken = model.apply(
        variables, im1, im2, iters=ITERS, test_mode=True,
        iter_metrics="per_sample", adaptive_tau=0.0)
    np.testing.assert_array_equal(np.asarray(up), np.asarray(fixed_up))
    np.testing.assert_array_equal(np.asarray(lr), np.asarray(fixed_lr))
    np.testing.assert_array_equal(np.asarray(res), np.asarray(fixed_res))
    assert list(np.asarray(taken)) == [ITERS, ITERS]


def test_adaptive_none_keeps_prior_hlo(tiny):
    """``adaptive_tau=None`` (every pre-policy call site) must leave the
    traced program byte-identical to the prior plain test-mode one."""
    _, model, variables = tiny
    spec = jax.ShapeDtypeStruct((1, H, W, 3), np.float32)
    vspec = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype), variables)

    def run_off(v, a, b):
        return model.apply(v, a, b, iters=ITERS, test_mode=True,
                           adaptive_tau=None, adaptive_min_iters=1)

    def run_prior(v, a, b):
        return model.apply(v, a, b, iters=ITERS, test_mode=True)

    run_off.__name__ = run_prior.__name__ = "forward"
    text_off = jax.jit(run_off).lower(vspec, spec, spec).as_text()
    text_prior = jax.jit(run_prior).lower(vspec, spec, spec).as_text()
    assert text_off == text_prior


def _oracle_taken(res_fixed, tau, min_iters, budget):
    """NumPy twin of the freeze rule: after applied update i (1-indexed,
    residual row i-1), the sample freezes iff r < tau and i >= min_iters;
    iters_taken = the freezing i, else the full budget."""
    taken = []
    for j in range(res_fixed.shape[1]):
        t = budget
        for i in range(min_iters, budget + 1):
            if res_fixed[i - 1, j] < tau:
                t = i
                break
        taken.append(t)
    return taken


def test_masked_scan_freeze_matches_numpy_oracle(tiny):
    _, model, variables = tiny
    im1, im2 = _frames([3, 4, 5])
    _, _, res_fixed = model.apply(
        variables, im1, im2, iters=ITERS, test_mode=True,
        iter_metrics="per_sample")
    res_fixed = np.asarray(res_fixed, np.float64)
    # a tau strictly inside the recorded residual range exercises a real
    # mid-budget freeze (residual curves of random weights vary by sample)
    tau = float(np.median(res_fixed[:-1]))
    _, _, res_a, taken = model.apply(
        variables, im1, im2, iters=ITERS, test_mode=True,
        iter_metrics="per_sample", adaptive_tau=tau)
    res_a, taken = np.asarray(res_a), list(np.asarray(taken))
    assert taken == _oracle_taken(res_fixed, tau, 1, ITERS)
    assert min(taken) < ITERS        # the chosen tau did freeze something
    for j, t in enumerate(taken):
        # applied iterations record the fixed curve's rows ...
        np.testing.assert_array_equal(res_a[:t, j],
                                      np.asarray(res_fixed)[:t, j])
        # ... frozen ones record 0.0 padding
        assert np.all(res_a[t:, j] == 0.0)
    # the min_iters floor outranks an always-passing threshold
    _, _, _, floored = model.apply(
        variables, im1, im2, iters=ITERS, test_mode=True,
        iter_metrics="per_sample", adaptive_tau=1e9,
        adaptive_min_iters=2)
    assert list(np.asarray(floored)) == [2, 2, 2]


def test_while_loop_matches_masked_scan(tiny):
    cfg, model, variables = tiny
    wl = create_model(dataclasses.replace(cfg,
                                          adaptive_mode="while_loop"))
    im1, im2 = _frames([3, 4, 5])
    _, _, res_fixed = model.apply(
        variables, im1, im2, iters=ITERS, test_mode=True,
        iter_metrics="per_sample")
    tau = float(np.median(np.asarray(res_fixed)[:-1]))
    out_ms = model.apply(variables, im1, im2, iters=ITERS, test_mode=True,
                         iter_metrics="per_sample", adaptive_tau=tau)
    out_wl = wl.apply(variables, im1, im2, iters=ITERS, test_mode=True,
                      iter_metrics="per_sample", adaptive_tau=tau)
    for a, b in zip(out_ms, out_wl):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)
    assert list(np.asarray(out_ms[-1])) == list(np.asarray(out_wl[-1]))


# ------------------------------------------------------ policy lint

def test_policy_lint_catches_doctored_policies(tmp_path):
    good = _policy({"32x64": _entry(0.05, 3)})
    assert check_iter_policy(good) == []

    def errs(mutate):
        doc = json.loads(json.dumps(good))
        mutate(doc)
        return check_iter_policy(doc)

    def set_tau(doc):
        doc["buckets"]["32x64"]["tau"] = 0.2      # != provenance row

    assert any("provenance row tau" in e for e in errs(set_tau))

    def inflate(doc):
        doc["buckets"]["32x64"]["budget"] = 9     # > recorded budget 3

    assert any("exceeds the recorded iteration budget" in e
               for e in errs(inflate))

    def zero_tau(doc):
        doc["buckets"]["32x64"]["tau"] = 0.0
        doc["buckets"]["32x64"]["provenance"]["row"]["tau"] = 0.0

    assert any("tau must be > 0" in e for e in errs(zero_tau))
    assert any("no bucket coverage" in e
               for e in errs(lambda d: d["buckets"].clear()))
    assert any("not 'HxW'" in e for e in errs(
        lambda d: d["buckets"].update({"32x": _entry(0.05, 3)})))
    assert any("min_iters" in e for e in errs(
        lambda d: d["buckets"]["32x64"].update(min_iters=7)))
    assert any("kind" in e for e in errs(
        lambda d: d.update(kind="nope")))

    # load_policy raises with the first named reason; a predictor handed
    # the doctored artifact must fail at construction
    doctored = json.loads(json.dumps(good))
    doctored["buckets"]["32x64"]["budget"] = 9
    path = tmp_path / "iter_policy.json"
    path.write_text(json.dumps(doctored))
    with pytest.raises(ValueError, match="exceeds the recorded"):
        cv.load_policy(str(path))
    with pytest.raises(ValueError, match="exceeds the recorded"):
        StereoPredictor(RAFTStereoConfig(), {}, iter_policy=str(path))


# ------------------------------------------------- predictor plumbing

@pytest.fixture(scope="module")
def pred_fixed(tiny):
    cfg, _, variables = tiny
    return StereoPredictor(cfg, variables, valid_iters=ITERS,
                           converge=True)


@pytest.fixture(scope="module")
def pred_adaptive(tiny):
    """Policy whose tiny tau never fires: the parity flavor."""
    cfg, _, variables = tiny
    policy = _policy({f"{H}x{W}": _entry(1e-9, ITERS)})
    return StereoPredictor(cfg, variables, valid_iters=ITERS,
                           iter_policy=policy)


def test_predictor_guards(tiny):
    cfg, _, variables = tiny
    with pytest.raises(ValueError, match="needs an iter_policy"):
        StereoPredictor(cfg, variables, adaptive=True)
    policy = _policy({f"{H}x{W}": _entry(0.05, ITERS)})
    with pytest.raises(ValueError, match="numerics taps"):
        StereoPredictor(cfg, variables, iter_policy=policy, numerics=True)


def test_predictor_tiny_tau_parity_and_aux(pred_fixed, pred_adaptive):
    """A never-firing tau leaves the flow bitwise-equal to the fixed
    predictor while the aux gains the full-budget iters_taken."""
    im1, im2 = _frames([7, 8])
    flow_f = pred_fixed(im1, im2, ITERS)
    flow_a = pred_adaptive(im1, im2, ITERS)
    np.testing.assert_array_equal(flow_a, flow_f)
    assert pred_adaptive.adaptive and not pred_fixed.adaptive
    assert pred_adaptive.policy_digest
    aux = pred_adaptive.take_aux()
    assert set(aux) == {"residual", "iters_taken"}
    assert list(aux["iters_taken"]) == [ITERS, ITERS]
    assert aux["residual"].shape == (ITERS, 2)


def test_predictor_budget_caps_and_policy_entry(tiny):
    cfg, _, variables = tiny
    policy = _policy({f"{H}x{W}": _entry(1e9, 2, recorded=ITERS)})
    pred = StereoPredictor(cfg, variables, valid_iters=ITERS,
                           iter_policy=policy)
    # padded-bucket resolution: a 30x60 raw frame lands in 32x64
    doc = pred.policy_entry(30, 60)
    assert doc is not None and doc["budget"] == 2
    assert pred.policy_entry(40, 80) is None     # 64x96: uncovered
    im1, im2 = _frames([9])
    pred(im1, im2, ITERS)                        # asks 3, budget caps at 2
    aux = pred.take_aux()
    # a huge tau freezes right after the min_iters floor
    assert list(aux["iters_taken"]) == [1]
    assert aux["residual"].shape == (2, 1)


def test_predictor_uncovered_bucket_falls_back_to_fixed(pred_adaptive,
                                                        pred_fixed):
    """No bucket, no default: the call runs the fixed program and the
    aux carries no iters_taken."""
    im1, im2 = _frames([11], h=40, w=80)         # pads to 64x96
    flow_a = pred_adaptive(im1, im2, ITERS)
    flow_f = pred_fixed(im1, im2, ITERS)
    np.testing.assert_array_equal(flow_a, flow_f)
    assert set(pred_adaptive.take_aux()) == {"residual"}


def test_adaptive_false_with_policy_stays_fixed(tiny, pred_fixed):
    """adaptive=False pins the fixed path even with a policy on hand —
    the digest is still reported for provenance, the flow is bitwise."""
    cfg, _, variables = tiny
    policy = _policy({f"{H}x{W}": _entry(1e9, 2)})
    pred = StereoPredictor(cfg, variables, valid_iters=ITERS,
                           iter_policy=policy, adaptive=False,
                           converge=True)
    assert not pred.adaptive and pred.policy_digest
    im1, im2 = _frames([12])
    np.testing.assert_array_equal(pred(im1, im2, ITERS),
                                  pred_fixed(im1, im2, ITERS))
    assert set(pred.take_aux()) == {"residual"}


# ------------------------------------------------------------- serving

def test_serve_cache_guards_and_bucketkey_backcompat():
    from raft_stereo_tpu.serve import BucketKey
    from raft_stereo_tpu.serve.cache import ExecutableCache
    stub = {"params": {"w": np.zeros((1,), np.float32)}}
    with pytest.raises(ValueError, match="iter_policy"):
        ExecutableCache(RAFTStereoConfig(), stub, adaptive=True)
    policy = _policy({"32x64": _entry(0.05, 2)})
    with pytest.raises(ValueError, match="numerics"):
        ExecutableCache(RAFTStereoConfig(), stub, iter_policy=policy,
                        numerics=True)
    cache = ExecutableCache(RAFTStereoConfig(), stub, iter_policy=policy)
    assert cache.adaptive and cache.converge     # forced residual aux
    assert cache.bucket_entry(32, 64)["budget"] == 2
    assert cache.bucket_entry(64, 96) is None
    # the 5-field key is the fixed-trip program; digest changes the label
    key = BucketKey(32, 64, 1, 2, False)
    assert key.policy == "" and key.label() == "32x64b1i2"
    assert BucketKey(32, 64, 1, 2, False, "abc").label() \
        == "32x64b1i2@abc"


def test_serve_mixed_adaptive_and_fixed_flavors(tiny, tmp_path):
    """One server, one policy covering one bucket: covered requests ride
    the @digest executable and retire with iters_taken (slo rollup +
    Prometheus gauges), uncovered ones stay on the fixed program."""
    from raft_stereo_tpu.serve import ServeConfig, StereoServer
    from raft_stereo_tpu.serve.http import prometheus_metrics
    cfg, _, variables = tiny
    policy = _policy({f"{H}x{W}": _entry(1e9, 2, recorded=ITERS)})
    digest = cv.policy_digest(policy)
    tel = Telemetry(str(tmp_path / "serve"), stall_deadline_s=None)
    tel.run_start(config={"mode": "serve"})
    server = StereoServer(
        cfg, variables,
        ServeConfig(max_batch=2, window=2, default_iters=ITERS,
                    linger_s=0.0, slo_every=1, iter_policy=policy),
        telemetry=tel)
    try:
        rng = np.random.default_rng(0)

        def pair(h, w):
            return (rng.random((h, w, 3)).astype(np.float32),
                    rng.random((h, w, 3)).astype(np.float32))

        res_a = [server.submit(*pair(H, W)).result(timeout=300)
                 for _ in range(2)]
        res_f = server.submit(*pair(40, 80)).result(timeout=300)
    finally:
        server.request_drain()
        assert server.join(timeout=60)
    stats = server.stats()
    tel.emit("run_end", steps=3, ok=True)
    tel.close()

    for r in res_a:
        assert r.ok and r.bucket == f"{H}x{W}b1i2@{digest}"
        assert r.iters_taken == 1            # huge tau: freeze at the floor
        assert r.final_residual is not None
    assert res_f.ok and res_f.bucket == "64x96b1i3"
    assert res_f.iters_taken is None

    # slo rollup + exposition carry the per-bucket iteration gauges
    iters = stats["iters"]
    assert set(iters) == {f"{H}x{W}b1i2@{digest}"}
    gauges = iters[f"{H}x{W}b1i2@{digest}"]
    assert gauges["iters_taken_p50"] == 1.0
    assert gauges["iters_taken_p95"] == 1.0
    assert gauges["n"] == 2
    text = prometheus_metrics(stats)
    assert (f'raft_serve_iters_taken_p50'
            f'{{bucket="{H}x{W}b1i2@{digest}"}}') in text
    assert "raft_serve_iters_window_requests" in text

    # the event stream: covered requests carry iters_taken, the fixed
    # one does not; everything still lints
    events = read_events(str(tmp_path / "serve" / "events.jsonl"))
    reqs = [e for e in events if e.get("event") == "request"
            and e.get("status") == "ok"]
    taken = sorted(e.get("iters_taken", -1) for e in reqs)
    assert taken == [-1, 1, 1]
    curves = [e for e in events if e.get("event") == "converge"]
    assert any(e.get("iters_taken") == 1 for e in curves)
    assert check_path(str(tmp_path / "serve")) == []
