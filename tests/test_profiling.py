"""Profiling harness: trace capture + summary (SURVEY §5 tracing row)."""

import jax
import jax.numpy as jnp
import pytest

from raft_stereo_tpu.utils.profiling import (format_report, summarize_trace,
                                             trace)


def test_trace_and_summarize(tmp_path):
    log_dir = str(tmp_path / "trace")

    @jax.jit
    def f(x):
        return jnp.sum(x @ x.T)

    x = jnp.ones((256, 256))
    float(f(x))  # compile outside the trace
    with trace(log_dir):
        for _ in range(2):
            float(f(x))

    report = summarize_trace(log_dir)
    assert report["total_device_ms"] >= 0
    assert isinstance(report["by_category"], list)
    assert isinstance(report["top_ops"], list)
    text = format_report(report)
    assert "total device-op time" in text


def test_summarize_missing_dir(tmp_path):
    with pytest.raises(FileNotFoundError):
        summarize_trace(str(tmp_path / "nope"))
