"""Native C++ data-path library vs the numpy reference implementations."""

import numpy as np
import pytest

from raft_stereo_tpu.data import frame_utils, native


@pytest.fixture(scope="module")
def have_native():
    if not native.available():
        pytest.skip("native library unavailable (no toolchain?)")


def test_pfm_native_bit_identical(tmp_path, have_native):
    rng = np.random.default_rng(0)
    for shape in [(37, 53), (16, 128)]:
        arr = rng.normal(scale=100.0, size=shape).astype(np.float32)
        p = str(tmp_path / f"x_{shape[0]}.pfm")
        frame_utils.write_pfm(p, arr)
        got = native.read_pfm(p)
        want = frame_utils._read_pfm_numpy(p)
        np.testing.assert_array_equal(got, want)
        np.testing.assert_array_equal(got, arr)


def test_pfm_native_rejects_garbage(tmp_path, have_native):
    p = str(tmp_path / "bad.pfm")
    with open(p, "wb") as f:
        f.write(b"NOTPFM\n1 1\n-1.0\n\x00\x00\x00\x00")
    with pytest.raises(ValueError):
        native.read_pfm(p)


def test_pfm_native_truncated(tmp_path, have_native):
    p = str(tmp_path / "trunc.pfm")
    with open(p, "wb") as f:
        f.write(b"Pf\n8 8\n-1.0\n")
        f.write(b"\x00" * 16)  # far fewer than 8*8*4 bytes
    with pytest.raises(ValueError):
        native.read_pfm(p)


def test_collate_matches_numpy(have_native):
    rng = np.random.default_rng(1)
    imgs = [rng.integers(0, 255, (24, 32, 3), dtype=np.uint8)
            for _ in range(4)]
    got = native.collate_u8(imgs)
    want = np.stack(imgs).astype(np.float32)
    np.testing.assert_array_equal(got, want)


def test_read_pfm_dispatch_uses_native(tmp_path, have_native):
    """frame_utils.read_pfm returns the same array regardless of path."""
    arr = np.arange(12, dtype=np.float32).reshape(3, 4)
    p = str(tmp_path / "d.pfm")
    frame_utils.write_pfm(p, arr)
    np.testing.assert_array_equal(frame_utils.read_pfm(p), arr)


def test_pfm_crlf_scale_line(tmp_path, have_native):
    """CRLF-terminated scale line must not shift the payload offset."""
    arr = np.arange(20, dtype=np.float32).reshape(4, 5)
    p = str(tmp_path / "crlf.pfm")
    with open(p, "wb") as f:
        f.write(b"Pf\n5 4\n-1.0\r\n")
        f.write(np.flipud(arr).astype("<f4").tobytes())
    got = native.read_pfm(p)
    want = frame_utils._read_pfm_numpy(p)
    np.testing.assert_array_equal(got, want)
    np.testing.assert_array_equal(got, arr)
