"""Native C++ data-path library vs the numpy reference implementations."""

import numpy as np
import pytest

from raft_stereo_tpu.data import frame_utils, native


@pytest.fixture(scope="module")
def have_native():
    if not native.available():
        pytest.skip("native library unavailable (no toolchain?)")


def test_pfm_native_bit_identical(tmp_path, have_native):
    rng = np.random.default_rng(0)
    for shape in [(37, 53), (16, 128)]:
        arr = rng.normal(scale=100.0, size=shape).astype(np.float32)
        p = str(tmp_path / f"x_{shape[0]}.pfm")
        frame_utils.write_pfm(p, arr)
        got = native.read_pfm(p)
        want = frame_utils._read_pfm_numpy(p)
        np.testing.assert_array_equal(got, want)
        np.testing.assert_array_equal(got, arr)


def test_pfm_native_rejects_garbage(tmp_path, have_native):
    p = str(tmp_path / "bad.pfm")
    with open(p, "wb") as f:
        f.write(b"NOTPFM\n1 1\n-1.0\n\x00\x00\x00\x00")
    with pytest.raises(ValueError):
        native.read_pfm(p)


def test_pfm_native_truncated(tmp_path, have_native):
    p = str(tmp_path / "trunc.pfm")
    with open(p, "wb") as f:
        f.write(b"Pf\n8 8\n-1.0\n")
        f.write(b"\x00" * 16)  # far fewer than 8*8*4 bytes
    with pytest.raises(ValueError):
        native.read_pfm(p)


def test_collate_matches_numpy(have_native):
    rng = np.random.default_rng(1)
    imgs = [rng.integers(0, 255, (24, 32, 3), dtype=np.uint8)
            for _ in range(4)]
    got = native.collate_u8(imgs)
    want = np.stack(imgs).astype(np.float32)
    np.testing.assert_array_equal(got, want)


def test_read_pfm_dispatch_uses_native(tmp_path, have_native):
    """frame_utils.read_pfm returns the same array regardless of path."""
    arr = np.arange(12, dtype=np.float32).reshape(3, 4)
    p = str(tmp_path / "d.pfm")
    frame_utils.write_pfm(p, arr)
    np.testing.assert_array_equal(frame_utils.read_pfm(p), arr)


def test_pfm_crlf_scale_line(tmp_path, have_native):
    """CRLF-terminated scale line must not shift the payload offset."""
    arr = np.arange(20, dtype=np.float32).reshape(4, 5)
    p = str(tmp_path / "crlf.pfm")
    with open(p, "wb") as f:
        f.write(b"Pf\n5 4\n-1.0\r\n")
        f.write(np.flipud(arr).astype("<f4").tobytes())
    got = native.read_pfm(p)
    want = frame_utils._read_pfm_numpy(p)
    np.testing.assert_array_equal(got, want)
    np.testing.assert_array_equal(got, arr)


def test_png16_decode_matches_cv2(tmp_path):
    """Native 16-bit PNG decoder vs cv2 on synthetic KITTI-style disparity
    maps (varied content exercises every PNG scanline filter)."""
    import cv2

    from raft_stereo_tpu.data import native

    if not native.available():
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(7)
    cases = [
        rng.integers(0, 65535, (37, 53), np.uint16),         # noise
        np.tile(np.arange(64, dtype=np.uint16) * 512, (16, 1)),  # gradients
        np.zeros((8, 8), np.uint16),                         # constant
        (np.outer(np.arange(41), np.arange(29)) % 65536).astype(np.uint16),
    ]
    for i, arr in enumerate(cases):
        path = str(tmp_path / f"d{i}.png")
        assert cv2.imwrite(path, arr)
        out = native.read_png16(path)
        assert out is not None, "probe rejected a 16-bit grey PNG"
        np.testing.assert_array_equal(out, arr)


def test_png16_probe_rejects_8bit(tmp_path):
    """8-bit / RGB PNGs must defer to the PIL/cv2 path, not error."""
    import cv2

    from raft_stereo_tpu.data import native

    if not native.available():
        pytest.skip("native library unavailable")
    path = str(tmp_path / "rgb.png")
    assert cv2.imwrite(path, np.zeros((5, 5, 3), np.uint8))
    assert native.read_png16(path) is None


def test_read_disp_kitti_via_native(tmp_path):
    """read_disp_kitti end-to-end through the native decoder."""
    import cv2

    from raft_stereo_tpu.data import frame_utils

    arr = (np.arange(12, dtype=np.uint16).reshape(3, 4) * 256)
    path = str(tmp_path / "disp.png")
    assert cv2.imwrite(path, arr)
    disp, valid = frame_utils.read_disp_kitti(path)
    np.testing.assert_allclose(disp, arr.astype(np.float32) / 256.0)
    assert valid.dtype == bool or valid.dtype == np.bool_
    assert not valid[0, 0] and valid[1, 1]


def test_stale_library_rebuilds(tmp_path):
    """A stale .so missing newly-added symbols is rebuilt before first load
    (fresh process: the real-world 'old checkout pulled new code' case)."""
    import os
    import shutil
    import subprocess
    import sys

    from raft_stereo_tpu.data import native

    if not native.available():
        pytest.skip("native library unavailable")
    src = tmp_path / "empty.cpp"
    src.write_text('extern "C" int unrelated_symbol() { return 0; }\n')
    decoy = tmp_path / "decoy.so"
    subprocess.run(["g++", "-shared", "-fPIC", "-o", str(decoy), str(src)],
                   check=True)
    backup = native._LIB_PATH + ".bak"
    shutil.copy(native._LIB_PATH, backup)
    try:
        shutil.copy(str(decoy), native._LIB_PATH)
        cpp = os.path.join(os.path.dirname(native._LIB_PATH),
                           "stereodata.cpp")
        os.utime(native._LIB_PATH, (0, os.path.getmtime(cpp) - 10))
        # fresh interpreter: no dlopen handle cached for the path
        probe = subprocess.run(
            [sys.executable, "-c",
             "import sys; sys.path.insert(0, '/root/repo'); "
             "from raft_stereo_tpu.data import native; "
             "print(native.available())"],
            capture_output=True, text=True, timeout=180)
        assert probe.stdout.strip().endswith("True"), probe.stderr[-500:]
    finally:
        shutil.move(backup, native._LIB_PATH)
        # NOTE: no available() assert here — this process's dlopen cache is
        # poisoned by the decoy-content inode; fresh processes are fine.
        native._lib = None
        native._tried = False
