#!/usr/bin/env python
"""Inference demo CLI (reference demo.py:55-78, same flag surface).

Globs left/right image pairs, runs the model in test mode, writes
``<name>-disparity.png`` jet-colormapped visualizations and optionally raw
``.npy`` disparities (reference demo.py:34-52).
"""

import glob
import logging
import os

import numpy as np

from raft_stereo_tpu import cli
from raft_stereo_tpu.inference import StereoPredictor


def load_image(path):
    from PIL import Image
    img = np.asarray(Image.open(path)).astype(np.uint8)
    if img.ndim == 2:
        img = np.tile(img[..., None], (1, 1, 3))
    return img[..., :3]


def save_colormapped(path, disparity):
    import matplotlib.pyplot as plt
    plt.imsave(path, disparity, cmap="jet")


def main():
    args = cli.build_demo_parser().parse_args()

    logging.basicConfig(level=logging.INFO)

    cfg = cli.model_config(args)
    model, variables = cli.load_variables(args.restore_ckpt, cfg)
    predictor = StereoPredictor(cfg, variables, valid_iters=args.valid_iters)

    left_list = sorted(glob.glob(args.left_imgs, recursive=True))
    right_list = sorted(glob.glob(args.right_imgs, recursive=True))
    if not left_list or len(left_list) != len(right_list):
        raise SystemExit(f"found {len(left_list)} left / {len(right_list)} "
                         "right images; need matching non-empty lists")
    print(f"found {len(left_list)} image pairs; saving files to "
          f"{args.output_directory}/")
    os.makedirs(args.output_directory, exist_ok=True)

    for lpath, rpath in zip(left_list, right_list):
        disp = predictor.compute_disparity(load_image(lpath),
                                           load_image(rpath))
        stem = os.path.join(args.output_directory,
                            os.path.splitext(os.path.basename(lpath))[0])
        save_colormapped(f"{stem}-disparity.png", disp)
        if args.save_numpy:
            np.save(f"{stem}.npy", disp)
        print(f"{lpath}: disparity range "
              f"[{disp.min():.2f}, {disp.max():.2f}]")


if __name__ == "__main__":
    main()
