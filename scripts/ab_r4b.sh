#!/bin/bash
# r4 follow-up A/B: re-run the flaked fused-OFF control, and probe whether
# the r3 lax.map upsample chunking (bounded a residual the r4 remat removed)
# now just costs time at b8.
set -u
cd "$(dirname "$0")/.."
R='{"batch": 8, "h": 320, "w": 720, "train_iters": 22, "steps": 6, "fused_loss": true'
run() {
  echo "=== $1"
  timeout 1500 python bench.py --attempt "$2" 2>&1 | grep -E "BENCH_RESULT|Error|Exceeded|RESOURCE" | tail -2
}
run "banker blocks + fused_lookup OFF (control, re-run)" "$R, \"remat_encoders\": \"blocks\", \"fused_lookup\": false}"
run "banker blocks + ON + one-shot upsample (budget 2G)" "$R, \"remat_encoders\": \"blocks\", \"upsample_budget\": 2147483648}"
