#!/usr/bin/env python
"""Fleet drill: prove the multi-process observatory on real processes.

The fleet observatory (obs/fleet.py) claims that N processes' event logs
can be merged onto one aligned clock, that a propagated traceparent joins
a client's span to the server's request lifecycle across the process
boundary, and that ``cli doctor`` names a straggler and a dead host with
correct attribution. This drill makes those claims a gate. It launches
THREE real processes over the real CLI surfaces, on CPU, in-sandbox:

* **host0** — ``cli serve`` on an ephemeral port; the drill driver (the
  "client" host) opens a root span, exports it as the
  ``RAFT_TRACEPARENT`` envelope to every child launch, and POSTs one
  /v1/predict request under a ``traceparent`` header from a
  client-side span — the server must echo the header and its request
  span tree must join the client's trace.
* **host1** — a ``cli train`` child with ``RAFT_FAULT_SLEEP_S`` injected:
  every step's dispatch leg is stretched by a real sleep, making this
  host a deterministic straggler the rollup must name.
* **host2** — an identical trainer, SIGKILL'd mid-run: its heartbeats
  stop with no ``run_end`` while the rest of the fleet runs on — the
  DEAD_HOST signature.

Assertions drive the real consumers: ``cli fleet <dir> --json`` must
attribute STRAGGLER to host1 and DEAD_HOST to host2, report a cross-host
trace join whose remote link parents the server's request under the
client, and build one merged Perfetto timeline with a process-group per
host; ``cli doctor <dir> --json`` must route to the same verdicts.

Each run appends a JSON record to ``runs/fleet_drill/drills.jsonl``
through the shared obs/ sink; exit status is non-zero on any failed
assertion, so scripts/rehearse_round.py's ``fleet`` leg can gate a round
on it.

Run: python scripts/fleet_drill.py [--steps 6] [--sleep-s 1.0]
     [--kill-step 3] [--keep-work]
"""

import argparse
import io
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from fault_drill import (CHILD_TIMEOUT_S, H, W,  # noqa: E402
                         make_sceneflow_tree, read_events_lenient,
                         wait_for_step)
from raft_stereo_tpu.obs.events import append_json_log  # noqa: E402
from raft_stereo_tpu.obs.fleet import (TRACEPARENT_ENV,  # noqa: E402
                                       format_traceparent)

OUT = os.path.join(REPO, "runs", "fleet_drill")
LOG = os.path.join(OUT, "drills.jsonl")

HEARTBEAT_S = 0.5
REQ_H, REQ_W = 48, 96  # one aligned /32 request shape for the POST


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def train_cmd(work, fleet, name, steps):
    return [sys.executable, "-m", "raft_stereo_tpu.cli", "train",
            "--name", name,
            "--data_root", os.path.join(work, "data"),
            "--ckpt_dir", os.path.join(work, "ckpts", name),
            "--run_dir", fleet,
            "--batch_size", "2", "--num_steps", str(steps),
            "--image_size", str(H), str(W),
            "--train_iters", "1", "--valid_iters", "1",
            "--hidden_dims", "32", "32", "32",
            "--validation_frequency", "1000000",
            "--checkpoint_frequency", "1000000",
            "--num_workers", "2", "--lr", "1e-4",
            "--data_parallel", "1", "--stall_deadline_s", "0",
            "--host_id", name, "--heartbeat_every", str(HEARTBEAT_S)]


def serve_cmd(fleet, port):
    return [sys.executable, "-m", "raft_stereo_tpu.cli", "serve",
            "--host", "127.0.0.1", "--port", str(port),
            "--run_dir", os.path.join(fleet, "host0"),
            "--hidden_dims", "32", "32", "32",
            "--iters", "1", "--max_batch", "2",
            "--host_id", "host0", "--heartbeat_every", str(HEARTBEAT_S)]


def launch(cmd, work, leg, env_extra=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1")
    env.pop("XLA_FLAGS", None)  # 1-device children (pure speed)
    env.update(env_extra or {})
    log_path = os.path.join(work, f"{leg}.log")
    log = open(log_path, "w")
    proc = subprocess.Popen(cmd, cwd=REPO, stdout=log,
                            stderr=subprocess.STDOUT, env=env)
    return proc, log_path


def wait_http_ready(port, proc, timeout_s=CHILD_TIMEOUT_S):
    t0 = time.monotonic()
    url = f"http://127.0.0.1:{port}/healthz"
    while time.monotonic() - t0 < timeout_s:
        if proc.poll() is not None:
            raise RuntimeError(f"serve child exited rc={proc.returncode} "
                               "before becoming ready")
        try:
            with urllib.request.urlopen(url, timeout=2) as resp:
                if resp.status == 200:
                    return
        except Exception:
            pass
        time.sleep(0.2)
    raise RuntimeError(f"serve not ready on :{port} within {timeout_s:.0f}s")


def post_predict(port, header):
    import numpy as np
    rng = np.random.default_rng(0)
    left = rng.integers(0, 255, (REQ_H, REQ_W, 3)).astype(np.float32)
    right = rng.integers(0, 255, (REQ_H, REQ_W, 3)).astype(np.float32)
    buf = io.BytesIO()
    np.savez_compressed(buf, left=left, right=right)
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/predict", data=buf.getvalue(),
        method="POST", headers={"traceparent": header})
    with urllib.request.urlopen(req, timeout=600) as resp:
        return resp.status, resp.headers.get("traceparent")


def run_drill(args, work):
    """The 3-process drill body; returns (ok, detail)."""
    from raft_stereo_tpu.obs import Telemetry
    from raft_stereo_tpu.obs.trace import Tracer

    fleet_dir = os.path.join(work, "fleet")
    detail = {"steps": args.steps, "sleep_s": args.sleep_s,
              "kill_step": args.kill_step}
    port = free_port()
    detail["port"] = port

    # the client host: its root span is the cross-process trace the
    # children join — exported to every launch via the env envelope
    tel = Telemetry(os.path.join(fleet_dir, "client"), host_id="client")
    Tracer(tel)
    tel.run_start(config={"mode": "fleet-drill-client", "port": port})
    root = tel.tracer.start("fleet_drill", port=port)
    envelope = {TRACEPARENT_ENV: format_traceparent(root.context)}

    procs = {}
    try:
        procs["host0"], log0 = launch(serve_cmd(fleet_dir, port), work,
                                      "host0", env_extra=envelope)
        procs["host1"], log1 = launch(
            train_cmd(work, fleet_dir, "host1", args.steps), work, "host1",
            env_extra=dict(envelope,
                           RAFT_FAULT_SLEEP_S=str(args.sleep_s)))
        procs["host2"], log2 = launch(
            train_cmd(work, fleet_dir, "host2", args.steps), work, "host2",
            env_extra=envelope)

        # the cross-process request: client span -> traceparent header ->
        # the server's request lifecycle spans
        wait_http_ready(port, procs["host0"])
        span = tel.tracer.start("client_request", shape=[REQ_H, REQ_W])
        header = format_traceparent(span.context)
        status, echoed = post_predict(port, header)
        span.set(status="ok" if status == 200 else f"http {status}").end()
        detail["request_status"] = status
        detail["traceparent_echoed"] = echoed == header
        if status != 200:
            return False, dict(detail, error=f"predict HTTP {status}; "
                                             f"see {log0}")
        if echoed != header:
            return False, dict(detail, error=f"traceparent not echoed: "
                                             f"sent {header}, got {echoed}")

        # the dead host: SIGKILL host2 once its event stream shows real
        # steps (the step for s lands while s+1 runs — fault_drill timing)
        seen = wait_for_step(
            os.path.join(fleet_dir, "host2", "events.jsonl"),
            max(args.kill_step - 1, 1), procs["host2"])
        if seen is None:
            return False, dict(detail, error="host2 exited before the "
                                             f"kill step; see {log2}")
        procs["host2"].send_signal(signal.SIGKILL)
        rc2 = procs["host2"].wait(timeout=30)
        detail["host2_rc"] = rc2
        if rc2 == 0:
            return False, dict(detail, error="SIGKILL'd host2 exited 0?!")

        # the straggler must finish its full run (its slowness is the
        # signal, not a failure) while host2's silence grows the gap
        rc1 = procs["host1"].wait(timeout=CHILD_TIMEOUT_S)
        detail["host1_rc"] = rc1
        if rc1 != 0:
            return False, dict(detail, error=f"straggler host1 rc={rc1}; "
                                             f"see {log1}")

        # graceful serve drain: SIGTERM -> run_end on host0's log
        procs["host0"].send_signal(signal.SIGTERM)
        rc0 = procs["host0"].wait(timeout=120)
        detail["host0_rc"] = rc0
        if rc0 != 0:
            return False, dict(detail, error=f"serve drain rc={rc0}; "
                                             f"see {log0}")
    finally:
        for proc in procs.values():
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
        root.end()
        tel.emit("run_end", steps=1, ok=True)
        tel.close()

    return check_consumers(fleet_dir, detail)


def check_consumers(fleet_dir, detail):
    """Drive the REAL consumers over the drill's logs and assert the
    acceptance bar: attribution, trace join, merged timeline, doctor."""
    r = subprocess.run(
        [sys.executable, "-m", "raft_stereo_tpu.cli", "fleet", fleet_dir,
         "--json"], cwd=REPO, capture_output=True, text=True, timeout=300)
    if r.returncode != 0:
        return False, dict(detail, error=f"cli fleet rc={r.returncode}: "
                                         f"{r.stderr[-500:]}")
    report = json.loads(r.stdout)
    verdicts = {v["verdict"]: v for v in report["verdicts"]}
    detail["verdicts"] = {v: verdicts[v].get("host") for v in verdicts}

    straggler = verdicts.get("STRAGGLER")
    if straggler is None or straggler.get("host") != "host1":
        return False, dict(detail, error="STRAGGLER not attributed to "
                                         f"host1: {report['verdicts']}")
    dead = verdicts.get("DEAD_HOST")
    if dead is None or dead.get("host") != "host2":
        return False, dict(detail, error="DEAD_HOST not attributed to "
                                         f"host2: {report['verdicts']}")
    # evidence quotes both sides of each comparison
    if "host1" not in straggler["evidence"][0] \
            or "other hosts" not in straggler["evidence"][0]:
        return False, dict(detail,
                           error=f"thin STRAGGLER evidence: {straggler}")

    # the cross-process trace: client's span parents the server's request
    joins = [j for j in report["cross_host_traces"]
             if "client" in j["hosts"] and "host0" in j["hosts"]]
    remote = [l for j in joins for l in j["remote_links"]
              if l["parent_host"] == "client"
              and l["child_host"] == "host0"]
    detail["cross_host_traces"] = len(report["cross_host_traces"])
    detail["remote_links"] = remote
    if not remote:
        return False, dict(detail, error="no cross-host trace join with a "
                                         "client-parented server span: "
                                         f"{report['cross_host_traces']}")

    # one merged timeline, one process-group per host, on one clock
    tl = report["timeline"]
    if tl["hosts"] != 4 or tl["spans"] <= 0:
        return False, dict(detail, error=f"timeline not merged: {tl}")
    if not os.path.exists(tl["path"]):
        return False, dict(detail, error=f"timeline missing: {tl['path']}")
    detail["timeline"] = {"hosts": tl["hosts"], "spans": tl["spans"],
                          "markers": tl["markers"]}

    # doctor routes a fleet dir to the same verdicts
    r = subprocess.run(
        [sys.executable, "-m", "raft_stereo_tpu.cli", "doctor", fleet_dir,
         "--json"], cwd=REPO, capture_output=True, text=True, timeout=300)
    if r.returncode != 0:
        return False, dict(detail, error=f"cli doctor rc={r.returncode}")
    doc = json.loads(r.stdout)
    kinds = {v["verdict"] for v in doc["verdicts"]}
    if not {"STRAGGLER", "DEAD_HOST"} <= kinds:
        return False, dict(detail, error=f"doctor fleet verdicts: {kinds}")

    # the dead host's truncated log is still read (lenient), and its
    # heartbeat count is frozen where the SIGKILL landed
    h2 = read_events_lenient(
        os.path.join(fleet_dir, "host2", "events.jsonl"))
    detail["host2_beats"] = sum(e.get("event") == "heartbeat" for e in h2)
    if not any(e.get("event") == "clock_anchor" for e in h2):
        return False, dict(detail, error="host2 log has no clock_anchor")
    return True, detail


def main(argv=None):
    p = argparse.ArgumentParser(
        description="3-process fleet drill: straggler + SIGKILL'd host + "
                    "cross-process trace join (see module doc)")
    p.add_argument("--steps", type=int, default=6)
    p.add_argument("--sleep-s", type=float, default=8.0,
                   help="injected per-step sleep on the straggler host — "
                        "must dwarf the natural CPU step time (~2-3s on a "
                        "contended runner) so the p95 ratio clears the "
                        "2x STRAGGLER threshold with margin")
    p.add_argument("--kill-step", type=int, default=3)
    p.add_argument("--keep-work", action="store_true",
                   help="keep the work dir (child run artifacts) on success")
    args = p.parse_args(argv)

    os.makedirs(OUT, exist_ok=True)
    work = os.path.join(OUT, "work")
    if os.path.exists(work):
        shutil.rmtree(work)
    os.makedirs(work)
    make_sceneflow_tree(os.path.join(work, "data"))

    t0 = time.monotonic()
    try:
        ok, detail = run_drill(args, work)
    except Exception as e:
        ok, detail = False, {"error": f"{type(e).__name__}: {e}"}
    record = {"drill": "fleet", "ok": ok,
              "wall_s": round(time.monotonic() - t0, 1), "detail": detail}
    append_json_log(LOG, record, stream=sys.stderr)
    if ok and not args.keep_work:
        shutil.rmtree(work, ignore_errors=True)
    print("fleet drill ok" if ok
          else f"FLEET DRILL FAILED: {detail.get('error')}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
