#!/usr/bin/env python
"""AOT allocation breakdown: name the buffers behind a recipe's residency.

``memory_analysis()`` totals (obs/xla.py) say HOW MUCH an executable needs;
this harness says WHICH buffers — the question VERDICT r5 weak #4 asks about
the b10/b12 step-time collapse ("XLA buffer-assignment falling into a spill
regime" was hypothesized with no allocation breakdown behind it). It
compiles EXACTLY the bench attempt's graph (bench.py ``--attempt`` with
``compile_only``, so the persistent-cache key matches the timed attempt) in
a subprocess whose ``XLA_FLAGS=--xla_dump_to`` captures the
buffer-assignment dump, then parses the dump into a named breakdown: top
allocations by size and, inside the dominant temp allocation, the largest
HLO values (instruction + shape) — the concrete buffer a spill claim must
name.

Every run also reports the CORRELATION-VOLUME CLASS (obs/xla.py
volume_class_summary): count and bytes of values shaped like the all-pairs
volume pyramid, ``(..., W1, W2_level)`` spanning at least the feature-map
height. ``--ab`` compiles the same recipe under ``reg`` and ``fused`` and
diffs the class — the r18 proof that the memoryless kernel leaves the class
EMPTY (count 0), not merely smaller.

Artifacts under ``--out`` (default ``runs/alloc_b<batch>_<schedule>``;
``runs/alloc_fused_b<batch>_<schedule>`` when --corr_implementation=fused):

* ``analysis.json`` — config, compile result, memory_analysis totals, the
  named breakdown, and the volume-class summary;
* ``events.jsonl`` — the child's xla_memory/xla_cost introspection events
  (``BENCH_RUN_DIR`` is pointed at the artifact dir);
* ``memory-usage-report.txt`` — XLA's own sorted-allocation report, kept
  verbatim (the raw dump is pruned unless ``--keep-dump``: the optimized-
  HLO text for the flagship graph runs to hundreds of MB).
* with ``--ab``: the two runs' dirs plus ``compare.json`` next to them.

Run: python scripts/alloc_breakdown.py --batch 10 --schedule frugal
     [--h 320 --w 720] [--timeout 1500] [--corr_implementation fused] [--ab]
"""

import argparse
import json
import os
import shutil
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from bench import (  # noqa: E402  (no jax at module level)
    FLAGSHIP_RECIPE, run_attempt_subprocess_detailed)
from raft_stereo_tpu.config import R4_BEST_SCHEDULE  # noqa: E402
from raft_stereo_tpu.obs.xla import (  # noqa: E402
    find_buffer_assignment, summarize_buffer_assignment,
    volume_class_summary)

SCHEDULES = {
    # the bench banker: hi-res-only block remat + the r4 best schedule
    "banker": dict(remat_encoders="blocks_hires", **R4_BEST_SCHEDULE),
    # the memory-frugal fallback the >b8 frontier rows ran on
    "frugal": dict(remat_encoders=True),
    # the no-remat monolith (the primary attempt's graph)
    "monolith": dict(**R4_BEST_SCHEDULE),
}

# feature maps run at 1/4 resolution (n_downsample=2) in every shipped recipe
_FEAT_FACTOR = 4


def run_one(args, impl, out):
    """Compile one (schedule, corr impl) recipe; write its artifact dir and
    return the analysis report dict."""
    dump_dir = os.path.join(out, "xla_dump")
    os.makedirs(dump_dir, exist_ok=True)

    kw = dict(batch=args.batch, h=args.h, w=args.w,
              train_iters=args.train_iters, steps=1, fused_loss=True,
              corr_storage_dtype=args.dtype, corr_implementation=impl,
              compile_only=True, **SCHEDULES[args.schedule])

    # the child inherits env: route the dump + the introspection events to
    # the artifact dir; restore afterwards so nothing leaks into later use
    saved = {k: os.environ.get(k) for k in ("XLA_FLAGS", "BENCH_RUN_DIR",
                                            "JAX_COMPILATION_CACHE_DIR")}
    os.environ["XLA_FLAGS"] = (
        f"--xla_dump_to={dump_dir} "
        + (saved["XLA_FLAGS"] or "")).strip()
    os.environ["BENCH_RUN_DIR"] = out
    # a cache hit would skip compilation — and the dump with it
    os.environ["JAX_COMPILATION_CACHE_DIR"] = os.path.join(dump_dir, "cache")
    try:
        result, err, wall = run_attempt_subprocess_detailed(kw, args.timeout)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    ba_path = find_buffer_assignment(dump_dir)
    breakdown = None
    vol_class = None
    if ba_path is not None:
        with open(ba_path) as f:
            text = f.read()
        breakdown = summarize_buffer_assignment(text, top=args.top)
        vol_class = volume_class_summary(
            text, w1=args.w // _FEAT_FACTOR, h1=args.h // _FEAT_FACTOR)
    report = {
        "config": kw,
        "ok": result is not None,
        "compile_s": None if result is None else result["value"],
        "platform": None if result is None else result.get("platform"),
        "xla": None if result is None else result.get("xla"),
        "error": None if err is None else err[:400],
        "wall_s": round(wall, 1),
        "buffer_assignment": breakdown,
        "volume_class": vol_class,
    }
    with open(os.path.join(out, "analysis.json"), "w") as f:
        json.dump(report, f, indent=1)

    # keep XLA's own compact report FOR THE ANALYZED MODULE (same dump
    # prefix as its buffer-assignment file — wrapper modules for trivial
    # ops dump alongside); prune the multi-hundred-MB HLO text
    if ba_path is not None:
        report_path = ba_path.replace("buffer-assignment.txt",
                                      "memory-usage-report.txt")
        if os.path.exists(report_path):
            shutil.copy(report_path,
                        os.path.join(out, "memory-usage-report.txt"))
    if not args.keep_dump:
        shutil.rmtree(dump_dir, ignore_errors=True)
    return report


def _print_report(args, impl, out, report):
    breakdown = report["buffer_assignment"]
    if breakdown is None:
        print(f"[{impl}] no buffer-assignment dump captured "
              f"(error: {report['error']})", file=sys.stderr)
        print(json.dumps({k: report[k] for k in
                          ("ok", "compile_s", "error", "wall_s")}))
        return False
    gib = 1024 ** 3
    dom = breakdown["dominant_temp"]
    print(f"b{args.batch} {args.schedule} ({args.dtype}, {impl}) "
          f"{args.h}x{args.w}x{args.train_iters}it — "
          f"total {breakdown['total_bytes'] / gib:.2f} GiB, "
          f"temps {breakdown['temp_bytes'] / gib:.2f} GiB")
    if dom:
        print(f"dominant temp allocation: {dom['size'] / gib:.2f} GiB; "
              f"largest values:")
        for v in dom["top_values"]:
            print(f"  {v['size'] / gib:8.3f} GiB  {v['shape']:28s} "
                  f"{v['instruction'][:70]}")
    vc = report["volume_class"]
    if vc is not None:
        print(f"volume class (trailing ({vc['w1']}, {vc['pool_widths']}), "
              f">= {vc['h1']} rows): {vc['count']} values, "
              f"{vc['bytes'] / gib:.3f} GiB")
    print(f"artifact: {out}/analysis.json")
    return True


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=10)
    p.add_argument("--schedule", choices=sorted(SCHEDULES), default="frugal")
    p.add_argument("--dtype", choices=["bfloat16", "float32"],
                   default="bfloat16")
    p.add_argument("--corr_implementation", default="reg",
                   choices=["reg", "alt", "reg_pallas", "alt_pallas",
                            "fused"])
    p.add_argument("--ab", action="store_true",
                   help="compile BOTH reg and fused at this recipe and diff "
                        "the volume allocation class (the r18 memoryless "
                        "proof)")
    p.add_argument("--h", type=int, default=FLAGSHIP_RECIPE["h"])
    p.add_argument("--w", type=int, default=FLAGSHIP_RECIPE["w"])
    p.add_argument("--train_iters", type=int,
                   default=FLAGSHIP_RECIPE["train_iters"])
    p.add_argument("--timeout", type=float, default=1500.0)
    p.add_argument("--out", default=None)
    p.add_argument("--top", type=int, default=10)
    p.add_argument("--keep-dump", action="store_true")
    args = p.parse_args()

    def default_out(impl):
        prefix = "alloc_fused" if impl == "fused" else "alloc"
        return os.path.join(
            REPO, "runs", f"{prefix}_b{args.batch}_{args.schedule}")

    if not args.ab:
        impl = args.corr_implementation
        out = args.out or default_out(impl)
        report = run_one(args, impl, out)
        return 0 if _print_report(args, impl, out, report) else 1

    # --ab: the named-class comparison. Same batch/shape/schedule, two
    # compiles; the claim under test is count_fused == 0 while reg's class
    # carries the pyramid.
    reports = {}
    ok = True
    for impl in ("reg", "fused"):
        out = default_out(impl)
        reports[impl] = (out, run_one(args, impl, out))
        ok = _print_report(args, impl, out, reports[impl][1]) and ok
    gib = 1024 ** 3
    compare = {"batch": args.batch, "schedule": args.schedule,
               "dtype": args.dtype,
               "shape": [args.h, args.w, args.train_iters]}
    for impl, (out, rep) in reports.items():
        vc = rep["volume_class"] or {}
        xla = rep["xla"] or {}
        compare[impl] = {
            "volume_class_count": vc.get("count"),
            "volume_class_bytes": vc.get("bytes"),
            "peak_bytes": xla.get("peak_bytes"),
            "temp_bytes": xla.get("temp_bytes"),
            "artifact": out,
        }
    vc_fused = (reports["fused"][1].get("volume_class") or {})
    vc_reg = (reports["reg"][1].get("volume_class") or {})
    compare["volume_class_gone"] = (vc_fused.get("count") == 0
                                    and (vc_reg.get("count") or 0) > 0)
    cmp_path = os.path.join(
        REPO, "runs", f"alloc_fused_ab_b{args.batch}_{args.schedule}.json")
    with open(cmp_path, "w") as f:
        json.dump(compare, f, indent=1)
    if vc_reg and vc_fused:
        print(f"volume class: reg {vc_reg['count']} values "
              f"({(vc_reg['bytes'] or 0) / gib:.3f} GiB) -> fused "
              f"{vc_fused['count']} values "
              f"({(vc_fused['bytes'] or 0) / gib:.3f} GiB); "
              f"gone={compare['volume_class_gone']}")
    print(f"comparison: {cmp_path}")
    return 0 if (ok and compare["volume_class_gone"]) else 1


if __name__ == "__main__":
    sys.exit(main())
