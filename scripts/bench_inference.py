#!/usr/bin/env python
"""Inference FPS benchmark: KITTI-sized frames, default and realtime presets.

The reference reports KITTI FPS at eval time after a warmup
(evaluate_stereo.py:77-81,105-107) and documents a "realtime" configuration
(README.md:105). This measures both on synthetic KITTI-resolution pairs
(375x1242, padded to /32), with honest host-fetch synchronization per frame.

  python scripts/bench_inference.py            # both presets
  python scripts/bench_inference.py --preset realtime --iters 7
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--preset", choices=["default", "realtime", "both"],
                        default="both")
    parser.add_argument("--iters", type=int, default=None,
                        help="refinement iterations (default: 32 / 7)")
    parser.add_argument("--size", type=int, nargs=2, default=[375, 1242])
    parser.add_argument("--frames", type=int, default=12)
    args = parser.parse_args()

    import jax

    from raft_stereo_tpu.config import RAFTStereoConfig, realtime_config
    from raft_stereo_tpu.inference import StereoPredictor
    from raft_stereo_tpu.models import init_model

    presets = {
        "default": (RAFTStereoConfig(mixed_precision=True), 32),
        "realtime": (realtime_config(), 7),
    }
    chosen = ["default", "realtime"] if args.preset == "both" else [args.preset]

    h, w = args.size
    rng = np.random.default_rng(0)
    left = rng.uniform(0, 255, (1, h, w, 3)).astype(np.float32)
    right = rng.uniform(0, 255, (1, h, w, 3)).astype(np.float32)

    for name in chosen:
        cfg, default_iters = presets[name]
        iters = args.iters or default_iters
        _, variables = init_model(jax.random.PRNGKey(0), cfg, (1, 64, 128, 3))
        predictor = StereoPredictor(cfg, variables, valid_iters=iters)
        predictor(left, right)  # compile + warmup
        predictor(left, right)
        t0 = time.perf_counter()
        for _ in range(args.frames):
            out = predictor(left, right)  # returns host numpy: honest sync
        dt = (time.perf_counter() - t0) / args.frames
        print(f"{name:9s} iters={iters:2d} {h}x{w}: "
              f"{dt * 1000:7.1f} ms/frame = {1.0 / dt:6.2f} FPS "
              f"(platform {jax.devices()[0].platform})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
