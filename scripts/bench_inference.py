#!/usr/bin/env python
"""Inference FPS benchmark: KITTI-sized frames, default and realtime presets.

The reference reports KITTI FPS at eval time after a warmup
(evaluate_stereo.py:77-81,105-107) and documents a "realtime" configuration
(README.md:105). This measures both on synthetic KITTI-resolution pairs
(375x1242, padded to /32), with honest host-fetch synchronization per frame.

  python scripts/bench_inference.py            # both presets
  python scripts/bench_inference.py --preset realtime --iters 7
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--preset", choices=["default", "realtime", "both"],
                        default="both")
    parser.add_argument("--iters", type=int, default=None,
                        help="refinement iterations (default: 32 / 7)")
    parser.add_argument("--size", type=int, nargs=2, default=[375, 1242])
    parser.add_argument("--frames", type=int, default=12)
    parser.add_argument("--window", type=int, default=3,
                        help="in-flight dispatches for the pipelined row "
                             "(predict_async; 1 disables overlap)")
    parser.add_argument("--fused_lookup", choices=["auto", "on", "off"],
                        default="auto")
    parser.add_argument("--scan_unroll", type=int, default=1,
                        help="refinement-scan unroll factor (training A/B'd "
                             "at b8 where it lost; inference at batch 1 is "
                             "dispatch-heavier, hence the separate knob)")
    parser.add_argument("--iter_policy", metavar="PATH", default=None,
                        help="recorded iteration policy (cli converge "
                             "--emit-policy): adds an adaptive end-to-end "
                             "row running the compiled early-exit flavor, "
                             "reporting mean iters_taken and the wall-clock "
                             "delta vs the fixed-trip row")
    args = parser.parse_args()

    import jax

    from raft_stereo_tpu.config import RAFTStereoConfig, realtime_config
    from raft_stereo_tpu.inference import StereoPredictor
    from raft_stereo_tpu.models import init_model

    presets = {
        "default": (RAFTStereoConfig(mixed_precision=True), 32),
        "realtime": (realtime_config(), 7),
    }
    tri = {"auto": None, "on": True, "off": False}
    import dataclasses
    presets = {k: (dataclasses.replace(c, fused_lookup=tri[args.fused_lookup],
                                       scan_unroll=args.scan_unroll),
                   it) for k, (c, it) in presets.items()}
    chosen = ["default", "realtime"] if args.preset == "both" else [args.preset]

    h, w = args.size
    rng = np.random.default_rng(0)
    left = rng.uniform(0, 255, (1, h, w, 3)).astype(np.float32)
    right = rng.uniform(0, 255, (1, h, w, 3)).astype(np.float32)

    import jax.numpy as jnp

    from raft_stereo_tpu.ops.geometry import InputPadder

    platform = jax.devices()[0].platform
    for name in chosen:
        cfg, default_iters = presets[name]
        iters = args.iters or default_iters
        model, variables = init_model(jax.random.PRNGKey(0), cfg,
                                      (1, 64, 128, 3))

        # --- device-only throughput: N frames chained device-side, one
        # scalar fetch at the end. This is the model-compute FPS and matches
        # the reference's methodology of timing with images already resident
        # (evaluate_stereo.py:77-81: .cuda() happens outside the timer).
        lj = jnp.asarray(left)
        rj = jnp.asarray(right)
        padder = InputPadder(lj.shape, divis_by=32)
        lp, rp = padder.pad(lj, rj)

        n = args.frames

        @jax.jit
        def device_loop(v, a, b):
            def body(c, _):
                _, up = model.apply(v, a + c, b, iters=iters, test_mode=True)
                return c + 1e-9 * jnp.sum(up), None
            c, _ = jax.lax.scan(body, 0.0, None, length=n)
            return c

        float(device_loop(variables, lp, rp))  # compile + warmup
        t0 = time.perf_counter()
        float(device_loop(variables, lp, rp))
        dev = (time.perf_counter() - t0) / n

        # --- end-to-end latency: numpy in -> numpy disparity out per frame
        # (includes host<->device transfers; on tunneled devices this is
        # dominated by the tunnel round-trip, not the chip).
        predictor = StereoPredictor(cfg, variables, valid_iters=iters)
        predictor(left, right)  # compile + warmup
        predictor(left, right)
        t0 = time.perf_counter()
        for _ in range(n):
            predictor(left, right)
        e2e = (time.perf_counter() - t0) / n

        # --- pipelined end-to-end: the same numpy-in/numpy-out path, but
        # dispatched through predict_async with a bounded in-flight window
        # (the eval/stream.py discipline) so frame i's D2H fetch overlaps
        # frames i+1..i+K's device compute. The gap between this row and the
        # serial end-to-end row is the per-frame sync cost (tunnel RTT +
        # blocking host work) the streaming validators amortize away.
        from collections import deque

        window = max(1, args.window)
        q = deque()
        t0 = time.perf_counter()
        for _ in range(n):
            q.append(predictor.predict_async(left, right))
            if len(q) >= window:
                q.popleft().result()
        while q:
            q.popleft().result()
        pipe = (time.perf_counter() - t0) / n

        print(f"{name:9s} iters={iters:2d} {h}x{w}: "
              f"device {dev*1e3:7.1f} ms/frame = {1/dev:6.2f} FPS | "
              f"end-to-end {e2e*1e3:7.1f} ms/frame = {1/e2e:6.2f} FPS | "
              f"pipelined(K={window}) {pipe*1e3:7.1f} ms/frame = "
              f"{1/pipe:6.2f} FPS (platform {platform})")

        # --- adaptive end-to-end: the same numpy-in/numpy-out path on the
        # compiled early-exit flavor. The policy's budget replaces the
        # fixed trip count and each frame reports the iterations actually
        # applied — the honest iters-saved + wall-clock evidence next to
        # the fixed row above.
        if args.iter_policy:
            pred_a = StereoPredictor(cfg, variables, valid_iters=iters,
                                     iter_policy=args.iter_policy)
            entry = pred_a.policy_entry(h, w)
            pred_a(left, right)  # compile + warmup
            pred_a(left, right)
            pred_a.take_aux()
            taken = []
            t0 = time.perf_counter()
            for _ in range(n):
                pred_a(left, right)
                aux = pred_a.take_aux() or {}
                if aux.get("iters_taken") is not None:
                    taken.extend(int(x) for x in
                                 np.ravel(aux["iters_taken"]))
            ada = (time.perf_counter() - t0) / n
            budget = entry["budget"] if entry else iters
            mean_taken = sum(taken) / len(taken) if taken else float(iters)
            cov = "covered" if entry is not None else "UNCOVERED -> fixed"
            print(f"{name:9s} adaptive  {h}x{w}: "
                  f"end-to-end {ada*1e3:7.1f} ms/frame = {1/ada:6.2f} FPS "
                  f"| mean iters_taken {mean_taken:.2f} of budget {budget} "
                  f"(fixed {iters}; {cov}; "
                  f"saved {(e2e-ada)*1e3:+.1f} ms/frame)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
