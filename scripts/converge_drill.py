#!/usr/bin/env python
"""Convergence-observatory rehearsal: prove `cli converge` on real runs.

The observatory's acceptance bar (r14) is not "the unit tests pass" — it
is that the curves a real run leaves behind replay into the early-exit
decision table without re-running the model:

1. **eval leg** — a tiny CPU `cli eval --dataset things --stream on
   --iter_epe` over a synthetic FlyingThings TEST tree (the fault_drill
   fixture layout): every frame must leave a ``converge`` event carrying
   both the residual and the in-graph EPE curve, and the run dir must
   lint clean under schema v8.
2. **serve leg** — a tiny `cli loadtest` (convergence aux on by
   default): every served request must leave a ``converge`` event and
   the slo rollups must carry the per-bucket quality gauges.
3. **replay leg** — `cli converge <run_dir>` over BOTH run dirs must
   exit 0 with a non-empty decision table; the eval table must carry
   EPE-delta columns (the GT-backed what-if), the serve one residual
   statistics per shape bucket.

Each leg appends a dated JSON record to
``runs/converge_drill/drills.jsonl``; exit non-zero if any check failed.
Driven by scripts/rehearse_round.py's ``converge`` leg.

Run: JAX_PLATFORMS=cpu python scripts/converge_drill.py [--keep-work]
"""

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

OUT = os.path.join(REPO, "runs", "converge_drill")
LOG = os.path.join(OUT, "drills.jsonl")

CHILD_TIMEOUT_S = 900.0
ITERS = 4


def _run(cmd, env_extra=None, timeout=CHILD_TIMEOUT_S):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1")
    # 1-device is plenty for the drill; drop any test-harness device forcing
    env.pop("XLA_FLAGS", None)
    env.update(env_extra or {})
    proc = subprocess.run(cmd, cwd=REPO, stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT, text=True,
                          timeout=timeout, env=env)
    return proc.returncode, proc.stdout or ""


def make_things_test_tree(root, n=4, h=48, w=64):
    """FlyingThings TEST-split tree (validate_things reads finalpass/TEST;
    same file layout as fault_drill.make_sceneflow_tree's TRAIN tree)."""
    import numpy as np
    from PIL import Image

    from raft_stereo_tpu.data import frame_utils

    rng = np.random.default_rng(0)
    for side in ("left", "right"):
        os.makedirs(os.path.join(root, "FlyingThings3D", "frames_finalpass",
                                 "TEST", "A", "0000", side), exist_ok=True)
    os.makedirs(os.path.join(root, "FlyingThings3D", "disparity", "TEST",
                             "A", "0000", "left"), exist_ok=True)
    for i in range(n):
        for side in ("left", "right"):
            img = rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
            Image.fromarray(img).save(os.path.join(
                root, "FlyingThings3D", "frames_finalpass", "TEST", "A",
                "0000", side, f"{i:04d}.png"))
        frame_utils.write_pfm(
            os.path.join(root, "FlyingThings3D", "disparity", "TEST", "A",
                         "0000", "left", f"{i:04d}.pfm"),
            rng.uniform(0.5, 8, (h, w)).astype(np.float32))


def _curves(run_dir):
    from raft_stereo_tpu.obs.events import read_events
    records = read_events(os.path.join(run_dir, "events.jsonl"))
    return records, [r for r in records if r.get("event") == "converge"]


def _lint(run_dir):
    from raft_stereo_tpu.obs.validate import check_path
    return check_path(run_dir)


def _replay(run_dir, expect_epe):
    """`cli converge` over a recorded run; returns (errors, summary)."""
    errors = []
    rc, out = _run([sys.executable, "-m", "raft_stereo_tpu.cli",
                    "converge", run_dir, "--json", "-"])
    if rc != 0:
        return [f"cli converge rc={rc}: {out.splitlines()[-1:]}"], None
    try:
        doc = json.loads(out[out.index("{"):])
    except ValueError as e:
        return [f"unparseable converge report: {e}"], None
    if not doc.get("table"):
        errors.append("decision table is empty")
    if not doc.get("curves"):
        errors.append("no curves replayed")
    if expect_epe and not any(r.get("epe_delta_mean") is not None
                              for r in doc.get("table", [])):
        errors.append("eval table carries no epe_delta (GT curves missing)")
    summary = {"curves": doc.get("curves"),
               "rows": len(doc.get("table", [])),
               "taus": doc.get("taus")}
    return errors, summary


def drill_eval(work):
    make_things_test_tree(os.path.join(work, "data"))
    run_dir = os.path.join(work, "runs", "eval")
    rc, out = _run([
        sys.executable, "-m", "raft_stereo_tpu.cli", "eval",
        "--dataset", "things", "--data_root", os.path.join(work, "data"),
        "--run_dir", run_dir, "--stream", "on", "--iter_epe",
        "--valid_iters", str(ITERS),
        "--hidden_dims", "32", "32", "32"])
    if rc != 0:
        return {"drill": "eval", "ok": False, "error": f"eval rc={rc}",
                "tail": "\n".join(out.splitlines()[-6:])}
    errors = []
    _, curves = _curves(run_dir)
    if not curves:
        errors.append("eval run emitted no converge events")
    if not all("epe" in c for c in curves):
        errors.append("--iter_epe eval curves missing the epe series")
    lint = _lint(run_dir)
    if lint:
        errors.append(f"v8 lint: {lint[:3]}")
    replay_errors, summary = _replay(run_dir, expect_epe=True)
    errors.extend(replay_errors)
    return {"drill": "eval", "ok": not errors, "run_dir": run_dir,
            "frames": len(curves), "replay": summary,
            "error": "; ".join(errors) or None}


def drill_serve(work):
    run_dir = os.path.join(work, "loadtest")
    rc, out = _run([
        sys.executable, "-m", "raft_stereo_tpu.cli", "loadtest",
        "--run_dir", run_dir, "--no_baseline", "--no_progress",
        "--shapes", "48x96", "64x128",
        "--clients", "3", "--requests_per_client", "2",
        "--video_streams", "0", "--max_batch", "2", "--window", "2",
        "--iters", str(ITERS), "--hidden_dims", "32", "32", "32"])
    if rc != 0:
        return {"drill": "serve", "ok": False, "error": f"loadtest rc={rc}",
                "tail": "\n".join(out.splitlines()[-6:])}
    serve_dir = os.path.join(run_dir, "serve")
    errors = []
    records, curves = _curves(serve_dir)
    n_ok = sum(1 for r in records
               if r.get("event") == "request" and r.get("status") == "ok")
    if not curves:
        errors.append("serve run emitted no converge events")
    elif len(curves) != n_ok:
        errors.append(f"{len(curves)} converge events != {n_ok} ok requests")
    if not any(e.get("event") == "slo" and "quality" in e for e in records):
        errors.append("no slo rollup carries the quality gauges")
    lint = _lint(serve_dir)
    if lint:
        errors.append(f"v8 lint: {lint[:3]}")
    replay_errors, summary = _replay(serve_dir, expect_epe=False)
    errors.extend(replay_errors)
    return {"drill": "serve", "ok": not errors, "run_dir": serve_dir,
            "requests": n_ok, "replay": summary,
            "error": "; ".join(errors) or None}


def main(argv=None):
    p = argparse.ArgumentParser(
        description="convergence-observatory rehearsal over real tiny runs "
                    "(see module doc)")
    p.add_argument("--keep-work", action="store_true",
                   help="keep the scratch tree (default: delete on exit)")
    args = p.parse_args(argv)

    from raft_stereo_tpu.obs.events import append_json_log

    os.makedirs(OUT, exist_ok=True)
    work = tempfile.mkdtemp(prefix="converge_drill_")
    t0 = time.monotonic()
    try:
        records = [drill_eval(work), drill_serve(work)]
    finally:
        if args.keep_work:
            print(f"work tree kept: {work}")
        else:
            shutil.rmtree(work, ignore_errors=True)
    ok = True
    for rec in records:
        rec["wall_s"] = round(time.monotonic() - t0, 1)
        append_json_log(LOG, rec, stream=sys.stderr)
        ok = ok and rec["ok"]
    print(("CONVERGE DRILL ok: " if ok else "CONVERGE DRILL FAILED: ")
          + ", ".join(f"{r['drill']}={'ok' if r['ok'] else 'FAIL'}"
                      for r in records))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
