#!/usr/bin/env python
"""Convergence-observatory rehearsal: prove `cli converge` on real runs.

The observatory's acceptance bar (r14) is not "the unit tests pass" — it
is that the curves a real run leaves behind replay into the early-exit
decision table without re-running the model:

1. **eval leg** — a tiny CPU `cli eval --dataset things --stream on
   --iter_epe` over a synthetic FlyingThings TEST tree (the fault_drill
   fixture layout): every frame must leave a ``converge`` event carrying
   both the residual and the in-graph EPE curve, and the run dir must
   lint clean under schema v8.
2. **serve leg** — a tiny `cli loadtest` (convergence aux on by
   default): every served request must leave a ``converge`` event and
   the slo rollups must carry the per-bucket quality gauges.
3. **replay leg** — `cli converge <run_dir>` over BOTH run dirs must
   exit 0 with a non-empty decision table; the eval table must carry
   EPE-delta columns (the GT-backed what-if), the serve one residual
   statistics per shape bucket.
4. **adaptive leg (r16)** — close the loop the simulator only predicts:
   emit a policy from the eval leg's recorded curves (`cli converge
   --emit-policy`, tau picked so every curve converges inside the
   budget), schema-lint it, then RE-RUN eval and loadtest with
   ``--iter_policy``. The compiled early exit must actually save
   iterations (per-frame/request ``iters_taken`` present, p95 < budget,
   mean strictly below the fixed trip count), the slo rollups must carry
   the per-bucket ``iters`` gauges, and the adaptive run's final EPE must
   stay within the table's predicted ``epe_delta`` (+ a small in-graph/
   simulator boundary slack).

Each leg appends a dated JSON record to
``runs/converge_drill/drills.jsonl``; exit non-zero if any check failed.
Driven by scripts/rehearse_round.py's ``converge`` leg.

Run: JAX_PLATFORMS=cpu python scripts/converge_drill.py [--keep-work]
"""

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

OUT = os.path.join(REPO, "runs", "converge_drill")
LOG = os.path.join(OUT, "drills.jsonl")

CHILD_TIMEOUT_S = 900.0
ITERS = 4


def _run(cmd, env_extra=None, timeout=CHILD_TIMEOUT_S):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1")
    # 1-device is plenty for the drill; drop any test-harness device forcing
    env.pop("XLA_FLAGS", None)
    env.update(env_extra or {})
    proc = subprocess.run(cmd, cwd=REPO, stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT, text=True,
                          timeout=timeout, env=env)
    return proc.returncode, proc.stdout or ""


def make_things_test_tree(root, n=4, h=48, w=64):
    """FlyingThings TEST-split tree (validate_things reads finalpass/TEST;
    same file layout as fault_drill.make_sceneflow_tree's TRAIN tree)."""
    import numpy as np
    from PIL import Image

    from raft_stereo_tpu.data import frame_utils

    rng = np.random.default_rng(0)
    for side in ("left", "right"):
        os.makedirs(os.path.join(root, "FlyingThings3D", "frames_finalpass",
                                 "TEST", "A", "0000", side), exist_ok=True)
    os.makedirs(os.path.join(root, "FlyingThings3D", "disparity", "TEST",
                             "A", "0000", "left"), exist_ok=True)
    for i in range(n):
        for side in ("left", "right"):
            img = rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
            Image.fromarray(img).save(os.path.join(
                root, "FlyingThings3D", "frames_finalpass", "TEST", "A",
                "0000", side, f"{i:04d}.png"))
        frame_utils.write_pfm(
            os.path.join(root, "FlyingThings3D", "disparity", "TEST", "A",
                         "0000", "left", f"{i:04d}.pfm"),
            rng.uniform(0.5, 8, (h, w)).astype(np.float32))


def _curves(run_dir):
    from raft_stereo_tpu.obs.events import read_events
    records = read_events(os.path.join(run_dir, "events.jsonl"))
    return records, [r for r in records if r.get("event") == "converge"]


def _lint(run_dir):
    from raft_stereo_tpu.obs.validate import check_path
    return check_path(run_dir)


def _replay(run_dir, expect_epe):
    """`cli converge` over a recorded run; returns (errors, summary)."""
    errors = []
    rc, out = _run([sys.executable, "-m", "raft_stereo_tpu.cli",
                    "converge", run_dir, "--json", "-"])
    if rc != 0:
        return [f"cli converge rc={rc}: {out.splitlines()[-1:]}"], None
    try:
        doc = json.loads(out[out.index("{"):])
    except ValueError as e:
        return [f"unparseable converge report: {e}"], None
    if not doc.get("table"):
        errors.append("decision table is empty")
    if not doc.get("curves"):
        errors.append("no curves replayed")
    if expect_epe and not any(r.get("epe_delta_mean") is not None
                              for r in doc.get("table", [])):
        errors.append("eval table carries no epe_delta (GT curves missing)")
    summary = {"curves": doc.get("curves"),
               "rows": len(doc.get("table", [])),
               "taus": doc.get("taus")}
    return errors, summary


def drill_eval(work):
    make_things_test_tree(os.path.join(work, "data"))
    run_dir = os.path.join(work, "runs", "eval")
    rc, out = _run([
        sys.executable, "-m", "raft_stereo_tpu.cli", "eval",
        "--dataset", "things", "--data_root", os.path.join(work, "data"),
        "--run_dir", run_dir, "--stream", "on", "--iter_epe",
        "--valid_iters", str(ITERS),
        "--hidden_dims", "32", "32", "32"])
    if rc != 0:
        return {"drill": "eval", "ok": False, "error": f"eval rc={rc}",
                "tail": "\n".join(out.splitlines()[-6:])}
    errors = []
    _, curves = _curves(run_dir)
    if not curves:
        errors.append("eval run emitted no converge events")
    if not all("epe" in c for c in curves):
        errors.append("--iter_epe eval curves missing the epe series")
    lint = _lint(run_dir)
    if lint:
        errors.append(f"v8 lint: {lint[:3]}")
    replay_errors, summary = _replay(run_dir, expect_epe=True)
    errors.extend(replay_errors)
    return {"drill": "eval", "ok": not errors, "run_dir": run_dir,
            "frames": len(curves), "replay": summary,
            "error": "; ".join(errors) or None}


def drill_serve(work):
    run_dir = os.path.join(work, "loadtest")
    rc, out = _run([
        sys.executable, "-m", "raft_stereo_tpu.cli", "loadtest",
        "--run_dir", run_dir, "--no_baseline", "--no_progress",
        "--shapes", "48x96", "64x128",
        "--clients", "3", "--requests_per_client", "2",
        "--video_streams", "0", "--max_batch", "2", "--window", "2",
        "--iters", str(ITERS), "--hidden_dims", "32", "32", "32"])
    if rc != 0:
        return {"drill": "serve", "ok": False, "error": f"loadtest rc={rc}",
                "tail": "\n".join(out.splitlines()[-6:])}
    serve_dir = os.path.join(run_dir, "serve")
    errors = []
    records, curves = _curves(serve_dir)
    n_ok = sum(1 for r in records
               if r.get("event") == "request" and r.get("status") == "ok")
    if not curves:
        errors.append("serve run emitted no converge events")
    elif len(curves) != n_ok:
        errors.append(f"{len(curves)} converge events != {n_ok} ok requests")
    if not any(e.get("event") == "slo" and "quality" in e for e in records):
        errors.append("no slo rollup carries the quality gauges")
    lint = _lint(serve_dir)
    if lint:
        errors.append(f"v8 lint: {lint[:3]}")
    replay_errors, summary = _replay(serve_dir, expect_epe=False)
    errors.extend(replay_errors)
    return {"drill": "serve", "ok": not errors, "run_dir": serve_dir,
            "requests": n_ok, "replay": summary,
            "error": "; ".join(errors) or None}


def _final_epes(curves):
    """Per-frame final in-graph EPE from recorded converge events."""
    return [float(c["epe"][-1]) for c in curves if c.get("epe")]


def drill_adaptive(work, eval_rec):
    """Emit a policy from the eval leg's curves, re-run eval + loadtest
    with it, and assert the compiled early exit saved iterations without
    giving up the predicted quality."""
    if not eval_rec.get("ok"):
        return {"drill": "adaptive", "ok": False,
                "error": "eval leg failed; no curves to emit a policy from"}
    src = eval_rec["run_dir"]
    _, curves = _curves(src)
    errors = []

    # Pick tau from the recorded curves so every curve converges at least
    # one iteration before the recorded budget: the smallest threshold
    # strictly above every curve's best pre-final residual. Deterministic,
    # and independent of the (random-weight) model's absolute scale.
    best = [min(float(v) for v in c["residual"][:-1]) for c in curves]
    tau = float(f"{max(best) * 1.01 + 1e-6:.6g}")
    policy_path = os.path.join(work, "iter_policy.json")
    rc, out = _run([sys.executable, "-m", "raft_stereo_tpu.cli",
                    "converge", src, "--emit-policy", policy_path,
                    "--policy-tau", repr(tau), "--taus", repr(tau),
                    "--json", "-"])
    if rc != 0:
        return {"drill": "adaptive", "ok": False,
                "error": f"emit-policy rc={rc}",
                "tail": "\n".join(out.splitlines()[-6:])}
    with open(policy_path) as f:
        policy = json.load(f)
    lint = _lint(policy_path)
    if lint:
        errors.append(f"policy lint: {lint[:3]}")
    table = json.loads(out[out.index("{"):]).get("table", [])
    pooled = next((r for r in table
                   if r["bucket"] == "*" and abs(r["tau"] - tau) < 1e-9),
                  None)
    epe_delta_pred = (pooled or {}).get("epe_delta_mean") or 0.0
    entries = list(policy.get("buckets", {}).values())
    if "default" in policy:
        entries.append(policy["default"])
    budget = max(int(e["budget"]) for e in entries)

    # adaptive EVAL re-run: same dataset, the policy drives the trip count
    run_dir = os.path.join(work, "runs", "eval_adaptive")
    rc, out = _run([
        sys.executable, "-m", "raft_stereo_tpu.cli", "eval",
        "--dataset", "things", "--data_root", os.path.join(work, "data"),
        "--run_dir", run_dir, "--stream", "on", "--iter_epe",
        "--valid_iters", str(ITERS), "--iter_policy", policy_path,
        "--hidden_dims", "32", "32", "32"])
    if rc != 0:
        return {"drill": "adaptive", "ok": False,
                "error": f"adaptive eval rc={rc}",
                "tail": "\n".join(out.splitlines()[-6:])}
    _, acurves = _curves(run_dir)
    taken = [int(c["iters_taken"]) for c in acurves if "iters_taken" in c]
    if len(taken) != len(acurves) or not taken:
        errors.append("adaptive eval curves missing iters_taken")
    else:
        p95 = sorted(taken)[min(len(taken) - 1,
                                int(round(0.95 * (len(taken) - 1))))]
        if p95 >= budget:
            errors.append(f"iters_taken p95 {p95} not below budget {budget}")
        if sum(taken) / len(taken) >= ITERS:
            errors.append(f"mean iters_taken {sum(taken) / len(taken):.2f} "
                          f"not below the fixed trip count {ITERS}")
    fixed_epe = _final_epes(curves)
    adaptive_epe = _final_epes(acurves)
    epe_excess = None
    if fixed_epe and adaptive_epe:
        measured_delta = (sum(adaptive_epe) / len(adaptive_epe)
                          - sum(fixed_epe) / len(fixed_epe))
        # slack: the simulator exits on <= tau over stored points, the
        # graph freezes on < tau — allow a small boundary margin
        epe_excess = measured_delta - max(float(epe_delta_pred), 0.0)
        if epe_excess > 0.05:
            errors.append(
                f"adaptive EPE delta {measured_delta:.4f}px exceeds the "
                f"table's prediction {epe_delta_pred:.4f}px by "
                f"{epe_excess:.4f}px")
    else:
        errors.append("missing final-EPE series for the quality check")
    if _lint(run_dir):
        errors.append(f"adaptive eval lint: {_lint(run_dir)[:3]}")

    # adaptive SERVE re-run: the same policy drives the AOT bucket cache
    lt_dir = os.path.join(work, "loadtest_adaptive")
    rc, out = _run([
        sys.executable, "-m", "raft_stereo_tpu.cli", "loadtest",
        "--run_dir", lt_dir, "--no_baseline", "--no_progress",
        "--shapes", "48x96", "64x128",
        "--clients", "3", "--requests_per_client", "2",
        "--video_streams", "0", "--max_batch", "2", "--window", "2",
        "--iters", str(ITERS), "--iter_policy", policy_path,
        "--hidden_dims", "32", "32", "32"])
    if rc != 0:
        return {"drill": "adaptive", "ok": False,
                "error": f"adaptive loadtest rc={rc}",
                "tail": "\n".join(out.splitlines()[-6:])}
    serve_dir = os.path.join(lt_dir, "serve")
    records, scurves = _curves(serve_dir)
    req_taken = [int(r["iters_taken"]) for r in records
                 if r.get("event") == "request"
                 and r.get("status") == "ok" and "iters_taken" in r]
    if not req_taken:
        errors.append("no served request event carries iters_taken")
    elif max(req_taken) > budget:
        errors.append(f"served iters_taken max {max(req_taken)} exceeds "
                      f"budget {budget}")
    elif sum(req_taken) / len(req_taken) >= ITERS:
        errors.append(f"served mean iters_taken not below the fixed "
                      f"trip count {ITERS}")
    if not any(e.get("event") == "slo" and "iters" in e for e in records):
        errors.append("no slo rollup carries the per-bucket iters gauges")
    if _lint(serve_dir):
        errors.append(f"adaptive serve lint: {_lint(serve_dir)[:3]}")

    return {"drill": "adaptive", "ok": not errors,
            "policy": {"tau": round(tau, 6), "budget": budget,
                       "buckets": sorted(policy.get("buckets", {})),
                       "default": "default" in policy},
            "eval_iters_taken": taken, "serve_iters_taken": req_taken,
            "epe_delta_pred": epe_delta_pred,
            "epe_excess": epe_excess,
            "error": "; ".join(errors) or None}


def main(argv=None):
    p = argparse.ArgumentParser(
        description="convergence-observatory rehearsal over real tiny runs "
                    "(see module doc)")
    p.add_argument("--keep-work", action="store_true",
                   help="keep the scratch tree (default: delete on exit)")
    args = p.parse_args(argv)

    from raft_stereo_tpu.obs.events import append_json_log

    os.makedirs(OUT, exist_ok=True)
    work = tempfile.mkdtemp(prefix="converge_drill_")
    t0 = time.monotonic()
    try:
        eval_rec = drill_eval(work)
        records = [eval_rec, drill_serve(work), drill_adaptive(work,
                                                              eval_rec)]
    finally:
        if args.keep_work:
            print(f"work tree kept: {work}")
        else:
            shutil.rmtree(work, ignore_errors=True)
    ok = True
    for rec in records:
        rec["wall_s"] = round(time.monotonic() - t0, 1)
        append_json_log(LOG, rec, stream=sys.stderr)
        ok = ok and rec["ok"]
    print(("CONVERGE DRILL ok: " if ok else "CONVERGE DRILL FAILED: ")
          + ", ".join(f"{r['drill']}={'ok' if r['ok'] else 'FAIL'}"
                      for r in records))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
