#!/usr/bin/env python
"""Numerics-observatory rehearsal: prove attribution on seeded faults.

The observatory's acceptance bar (r15) is not "the unit tests pass" — it
is that seeded numeric faults come back with the CORRECT attribution
through the real recording + replay pipeline:

1. **train leg** — a tiny ``cli train`` with an injected all-NaN batch
   (``RAFT_FAULT_NAN_STEP``, the fault_drill fixture): the run must
   survive (anomaly guard), the grad ``numerics`` record at the injected
   step must carry null per-leaf norms, the ``anomaly`` event must name
   the offending leaves (``top_leaves``), and ``cli doctor --json`` must
   return the NONFINITE_ORIGIN verdict.
2. **fixture leg** — in-process seeded tensors: (a) a NaN-poisoned input
   through the real tiny model must attribute ``first_nonfinite`` to the
   dataflow-earliest tap (``corr_feats``) at iteration 0, and doctor must
   echo it; (b) a seeded bf16-overflow/underflow stack (3.4e38 / 1e-41)
   must fire the saturation + underflow counters, put the tap on
   ``cli numerics``'s leaderboard, and earn the BF16_SATURATION verdict.
3. **eval leg** — a tiny ``cli eval --stream on`` over a synthetic
   FlyingThings TEST tree with numerics ON (the default): every dispatch
   must leave a ``taps`` record that lints clean under schema v9, and
   ``cli numerics <run_dir> --json -`` must replay them.
4. **serve leg** — a tiny ``cli loadtest --numerics``: per-dispatch
   ``numerics`` events, per-request ``output_min``/``output_max``, and
   the per-bucket ``output_range`` gauges on the slo rollup.

Each leg appends a dated JSON record to
``runs/numerics_drill/drills.jsonl``; exit non-zero if any check failed.
Driven by scripts/rehearse_round.py's ``numerics`` leg.

Run: JAX_PLATFORMS=cpu python scripts/numerics_drill.py [--keep-work]
"""

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

OUT = os.path.join(REPO, "runs", "numerics_drill")
LOG = os.path.join(OUT, "drills.jsonl")

CHILD_TIMEOUT_S = 900.0
ITERS = 4
NAN_STEP = 2


def _run(cmd, env_extra=None, timeout=CHILD_TIMEOUT_S):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1")
    env.pop("XLA_FLAGS", None)
    env.update(env_extra or {})
    proc = subprocess.run(cmd, cwd=REPO, stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT, text=True,
                          timeout=timeout, env=env)
    return proc.returncode, proc.stdout or ""


def _records(run_dir):
    from raft_stereo_tpu.obs.events import read_events
    return read_events(os.path.join(run_dir, "events.jsonl"))


def _numerics(records, kind=None):
    out = [r for r in records if r.get("event") == "numerics"]
    if kind is not None:
        out = [r for r in out if r.get("kind") == kind]
    return out


def _lint(run_dir):
    from raft_stereo_tpu.obs.validate import check_path
    return check_path(run_dir)


def _doctor_verdict(run_dir, phase="numerics"):
    """(verdict, errors) of `cli doctor --json` for one phase."""
    rc, out = _run([sys.executable, "-m", "raft_stereo_tpu.cli",
                    "doctor", run_dir, "--json"])
    if rc != 0:
        return None, [f"cli doctor rc={rc}: {out.splitlines()[-1:]}"]
    try:
        doc = json.loads(out[out.index("{"):])
    except ValueError as e:
        return None, [f"unparseable doctor report: {e}"]
    for v in doc.get("verdicts", []):
        if v.get("phase") == phase:
            return v.get("verdict"), []
    return None, [f"doctor report carries no {phase} phase verdict"]


def _replay(run_dir):
    """`cli numerics --json -` over a recorded run; (errors, report)."""
    rc, out = _run([sys.executable, "-m", "raft_stereo_tpu.cli",
                    "numerics", run_dir, "--json", "-"])
    if rc != 0:
        return [f"cli numerics rc={rc}: {out.splitlines()[-1:]}"], None
    try:
        doc = json.loads(out[out.index("{"):])
    except ValueError as e:
        return [f"unparseable numerics report: {e}"], None
    return [], doc


def drill_train(work):
    """Seeded NaN batch: grad record must carry the null-leaf provenance
    and the anomaly event the top-leaves attribution."""
    from fault_drill import make_sceneflow_tree, run_child
    make_sceneflow_tree(os.path.join(work, "data"))
    rc, run_dir, log = run_child(
        "numerics@nan-train", work, steps=4, ckpt_every=100,
        env_extra={"RAFT_FAULT_NAN_STEP": str(NAN_STEP)})
    if rc != 0:
        return {"drill": "train", "ok": False,
                "error": f"train rc={rc}; see {log}"}
    errors = []
    records = _records(run_dir)
    grads = _numerics(records, kind="grad")
    if not grads:
        errors.append("train run emitted no grad numerics events")
    poisoned = [r for r in grads if r.get("step") == NAN_STEP
                and any(v is None for v in r.get("grad_norm", []))]
    if grads and not poisoned:
        errors.append(f"no null-norm grad record at the injected step "
                      f"{NAN_STEP} (cadence must not hide provenance)")
    anomalies = [r for r in records if r.get("event") == "anomaly"
                 and r.get("kind") == "nonfinite_grad"]
    if not any(a.get("top_leaves") for a in anomalies):
        errors.append("anomaly event carries no top_leaves attribution")
    lint = _lint(run_dir)
    if lint:
        errors.append(f"v9 lint: {lint[:3]}")
    verdict, verr = _doctor_verdict(run_dir)
    errors.extend(verr)
    if verdict is not None and verdict != "NONFINITE_ORIGIN":
        errors.append(f"doctor verdict {verdict} != NONFINITE_ORIGIN")
    replay_errors, report = _replay(run_dir)
    errors.extend(replay_errors)
    if report is not None and not any(
            e.get("kind") == "grad" and e.get("step") == NAN_STEP
            for e in report.get("first_nonfinite", [])):
        errors.append("replay report misses the grad NaN origin")
    return {"drill": "train", "ok": not errors, "run_dir": run_dir,
            "grad_events": len(grads), "verdict": verdict,
            "error": "; ".join(errors) or None}


def drill_fixture(work):
    """In-process attribution checks: NaN-poisoned input through the real
    model, and a seeded bf16 overflow/underflow stack."""
    import numpy as np

    from raft_stereo_tpu.config import RAFTStereoConfig
    from raft_stereo_tpu.inference import StereoPredictor
    from raft_stereo_tpu.models import init_model
    from raft_stereo_tpu.obs import Telemetry
    from raft_stereo_tpu.obs import numerics as obs_numerics
    import jax

    errors = []
    # (a) NaN provenance through the real forward: the poisoned input
    # must surface at the dataflow-earliest tap, iteration 0
    cfg = RAFTStereoConfig(hidden_dims=(32, 32, 32))
    _, variables = init_model(jax.random.PRNGKey(0), cfg, (1, 48, 64, 3))
    predictor = StereoPredictor(cfg, variables, valid_iters=ITERS,
                                numerics=True)
    img = np.random.default_rng(0).uniform(
        0, 255, (1, 48, 64, 3)).astype(np.float32)
    poisoned = img.copy()
    poisoned[0, 10:14, 10:14, :] = np.nan
    predictor(poisoned, img)
    aux = predictor.take_aux()
    taps = aux.get("numerics") if aux else None
    nan_dir = os.path.join(work, "runs", "fixture_nan")
    payload = obs_numerics.taps_payload("fixture:nan", taps or {},
                                        bucket="48x64", frame=0)
    fnf = (payload or {}).get("first_nonfinite")
    if not fnf:
        errors.append("NaN input left no first_nonfinite")
    elif fnf.get("tap") != "corr_feats" or fnf.get("iter") != 0:
        errors.append(f"NaN origin misattributed: {fnf} != "
                      f"{{'tap': 'corr_feats', 'iter': 0}}")
    with Telemetry(nan_dir, stall_deadline_s=None) as tel:
        tel.run_start(config={"mode": "numerics-fixture-nan"})
        obs_numerics.emit(tel, payload)
        tel.emit("run_end", steps=1, ok=True)
    verdict, verr = _doctor_verdict(nan_dir)
    errors.extend(verr)
    if verdict is not None and verdict != "NONFINITE_ORIGIN":
        errors.append(f"NaN fixture verdict {verdict} != NONFINITE_ORIGIN")

    # (b) bf16 counters on seeded values: at-the-rail magnitudes count as
    # saturation, subnormal-below-bf16 values as underflow-to-zero
    from raft_stereo_tpu.nn.gru import numerics_taps, record_numerics_tap

    def fixture(x, y):
        # the sink is armed inside the trace (the model-apply pattern):
        # the recorded stat vectors are this call's outputs
        with numerics_taps() as sink:
            record_numerics_tap(x, "overflow_stack")
            record_numerics_tap(y, "underflow_stack")
            return dict(sink)

    stacks = {}
    for _ in range(2):  # two "iterations" of the same taps
        out = jax.jit(fixture)(np.full((8, 8), 3.4e38, np.float32),
                               np.full((8, 8), 1e-41, np.float32))
        for k, v in out.items():
            stacks.setdefault(k, []).append(np.asarray(v))
    taps2 = {k: np.stack(v) for k, v in stacks.items()}
    payload2 = obs_numerics.taps_payload("fixture:bf16", taps2,
                                         bucket="8x8", frame=0)
    if not payload2 or payload2.get("sat_total", 0) <= 0:
        errors.append("seeded 3.4e38 stack fired no saturation counter")
    if not payload2 or payload2.get("underflow_total", 0) <= 0:
        errors.append("seeded 1e-41 stack fired no underflow counter")
    bf16_dir = os.path.join(work, "runs", "fixture_bf16")
    with Telemetry(bf16_dir, stall_deadline_s=None) as tel:
        tel.run_start(config={"mode": "numerics-fixture-bf16"})
        obs_numerics.emit(tel, payload2)
        tel.emit("run_end", steps=1, ok=True)
    verdict2, verr = _doctor_verdict(bf16_dir)
    errors.extend(verr)
    if verdict2 is not None and verdict2 != "BF16_SATURATION":
        errors.append(f"bf16 fixture verdict {verdict2} != BF16_SATURATION")
    replay_errors, report = _replay(bf16_dir)
    errors.extend(replay_errors)
    if report is not None and not any(
            r.get("tap") == "overflow_stack"
            for r in report.get("saturation", [])):
        errors.append("leaderboard misses the seeded overflow stack")
    for d in (nan_dir, bf16_dir):
        lint = _lint(d)
        if lint:
            errors.append(f"v9 lint ({os.path.basename(d)}): {lint[:3]}")
    return {"drill": "fixture", "ok": not errors,
            "nan_origin": fnf, "sat": (payload2 or {}).get("sat_total"),
            "underflow": (payload2 or {}).get("underflow_total"),
            "verdicts": [verdict, verdict2],
            "error": "; ".join(errors) or None}


def drill_eval(work):
    from converge_drill import make_things_test_tree
    data = os.path.join(work, "data_eval")
    make_things_test_tree(data)
    run_dir = os.path.join(work, "runs", "eval")
    rc, out = _run([
        sys.executable, "-m", "raft_stereo_tpu.cli", "eval",
        "--dataset", "things", "--data_root", data,
        "--run_dir", run_dir, "--stream", "on",
        "--valid_iters", str(ITERS),
        "--hidden_dims", "32", "32", "32"])
    if rc != 0:
        return {"drill": "eval", "ok": False, "error": f"eval rc={rc}",
                "tail": "\n".join(out.splitlines()[-6:])}
    errors = []
    taps = _numerics(_records(run_dir), kind="taps")
    if not taps:
        errors.append("eval run emitted no taps numerics events")
    if taps and not all("corr_feats" in (r.get("taps") or {})
                        and "delta_flow" in (r.get("taps") or {})
                        for r in taps):
        errors.append("tap records miss the corr/delta taps")
    lint = _lint(run_dir)
    if lint:
        errors.append(f"v9 lint: {lint[:3]}")
    replay_errors, report = _replay(run_dir)
    errors.extend(replay_errors)
    if report is not None and not report.get("taps"):
        errors.append("replay report has no tap trend rows")
    return {"drill": "eval", "ok": not errors, "run_dir": run_dir,
            "dispatches": len(taps), "error": "; ".join(errors) or None}


def drill_serve(work):
    run_dir = os.path.join(work, "loadtest")
    rc, out = _run([
        sys.executable, "-m", "raft_stereo_tpu.cli", "loadtest",
        "--run_dir", run_dir, "--no_baseline", "--no_progress",
        "--numerics", "--shapes", "48x96", "64x128",
        "--clients", "3", "--requests_per_client", "2",
        "--video_streams", "0", "--max_batch", "2", "--window", "2",
        "--iters", str(ITERS), "--hidden_dims", "32", "32", "32"])
    if rc != 0:
        return {"drill": "serve", "ok": False, "error": f"loadtest rc={rc}",
                "tail": "\n".join(out.splitlines()[-6:])}
    serve_dir = os.path.join(run_dir, "serve")
    errors = []
    records = _records(serve_dir)
    taps = _numerics(records, kind="taps")
    if not taps:
        errors.append("serve run emitted no numerics events")
    oks = [r for r in records if r.get("event") == "request"
           and r.get("status") == "ok"]
    if not any("output_min" in r and "output_max" in r for r in oks):
        errors.append("no request record carries the output range")
    if not any(e.get("event") == "slo" and "output_range" in e
               for e in records):
        errors.append("no slo rollup carries the output_range gauges")
    lint = _lint(serve_dir)
    if lint:
        errors.append(f"v9 lint: {lint[:3]}")
    replay_errors, report = _replay(serve_dir)
    errors.extend(replay_errors)
    return {"drill": "serve", "ok": not errors, "run_dir": serve_dir,
            "dispatches": len(taps), "requests": len(oks),
            "error": "; ".join(errors) or None}


def main(argv=None):
    p = argparse.ArgumentParser(
        description="numerics-observatory rehearsal over seeded faults "
                    "(see module doc)")
    p.add_argument("--keep-work", action="store_true",
                   help="keep the scratch tree (default: delete on exit)")
    args = p.parse_args(argv)

    from raft_stereo_tpu.obs.events import append_json_log

    os.makedirs(OUT, exist_ok=True)
    work = tempfile.mkdtemp(prefix="numerics_drill_")
    t0 = time.monotonic()
    try:
        records = [drill_train(work), drill_fixture(work),
                   drill_eval(work), drill_serve(work)]
    finally:
        if args.keep_work:
            print(f"work tree kept: {work}")
        else:
            shutil.rmtree(work, ignore_errors=True)
    ok = True
    for rec in records:
        rec["wall_s"] = round(time.monotonic() - t0, 1)
        append_json_log(LOG, rec, stream=sys.stderr)
        ok = ok and rec["ok"]
    print(("NUMERICS DRILL ok: " if ok else "NUMERICS DRILL FAILED: ")
          + ", ".join(f"{r['drill']}={'ok' if r['ok'] else 'FAIL'}"
                      for r in records))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
