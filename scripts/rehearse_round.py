#!/usr/bin/env python
"""Round rehearsal: run the EXACT driver commands under the driver's budgets
(VERDICT r5 #8).

Both r5 artifact regressions — the bench number wobbling below published
figures and the multichip dryrun timing out into an ``ok:false`` record —
would have been caught by running the driver's own command lines, under the
driver's own ``timeout`` budgets, once before round end. This script is that
rehearsal:

* **bench** — ``python bench.py`` (the full attempt chain, parent-mode),
  bounded by ``BENCH_DEADLINE_S`` plus probe/teardown margin; the leg fails
  unless stdout's last line parses as the result JSON with a numeric
  ``value``.
* **multichip** — ``python __graft_entry__.py`` (entry + dryrun_multichip),
  bounded by ``GRAFT_DRYRUN_DEADLINE_S`` plus margin; the leg fails on
  non-zero rc — the budget-aware stage skipping inside the entry point is
  exactly what this rehearses.
* **events** — schema lint (scripts/check_events.py semantics) over the
  artifact logs a round leaves behind, so a drifted record fails here, not
  in the next round's summarizer.
* **compare** — the run-regression gate (obs/compare.py): diff this chain's
  bench telemetry (``runs/bench/current``, written by the bench leg — the
  bench.py parent rotates the prior chain's log to ``runs/bench/previous``)
  against the previous round's banked run, so an r5-style throughput wobble
  or memory/compile-time regression fails the rehearsal instead of waiting
  for a reviewer to notice. Skipped (ok, with a note) while no baseline
  exists yet.
* **scangrad** — the scan-gradient-equivalence leg (r8): run the FAST
  custom-VJP parity tests (tests/test_scan_grad.py, ``-m 'not slow'``,
  forced onto ``JAX_PLATFORMS=cpu`` so it runs identically on a TPU host)
  so a gradient regression in the batched-weight-grad backward surfaces
  before round end; a throughput regression in the same path is what the
  compare leg gates (the bench chain's scan A/B attempt writes into
  ``runs/bench/current``).
* **fusedcorr** — the memoryless fused-correlation leg (r18): run the
  fused-vs-reg parity, custom-VJP and serve-flavor tests
  (tests/test_fused_corr.py, forced onto ``JAX_PLATFORMS=cpu``) so a
  kernel regression in the W2-blocked lookup — the impl whose whole value
  is deleting the volume allocation class — surfaces before round end;
  the residency claim itself is gated by the fingerprint leg's
  ``inference[wide]``/``inference[fused]`` peak-bytes pair.
* **lint** — graftlint (r9): ``python -m raft_stereo_tpu.cli lint`` under
  ``JAX_PLATFORMS=cpu`` — the jaxpr/compiled-artifact contract rules
  (wgrad placement, dtype policy, donation, host-sync, carry/constant
  size), the SPMD engine (collective placement / sharding-propagation /
  axis / mesh-donation contracts on the fake 8-device mesh, r10), the
  tracer-safety AST lint and the concurrency engine (r19: host thread
  topology, shared-write-unlocked / lock-order / signal-handler /
  queue-discipline rules over the serve+obs+data threads), gated on
  unsuppressed error-severity findings against the checked-in
  ``.graftlint.json`` baseline. A structural regression in the hot path
  fails the rehearsal even when every numeric test still passes.
* **fingerprint** — the structural regression gate (r10): ``cli lint
  --fingerprint`` diffs the canonical executables' checked-in fingerprint
  (``.graftlint-fingerprint.json``: conv placement, collective
  kinds/counts in- and out-of-loop, peak bytes, donation pairs) against
  HEAD's lowerings — a new collective, a wgrad conv re-entering the
  backward loop or a >10% peak-bytes jump fails the leg — and (r19) the
  host thread topology against ``.graftlint-threads.json`` — a new
  thread entry, a lock dropped from a path or a new shared attribute is
  gated drift; intentional changes re-bank with ``--update-fingerprint``.
* **fault** — the fault-tolerance drill (r11): ``python
  scripts/fault_drill.py`` — SIGTERM and SIGKILL kill→auto-resume drills
  must end bitwise-identical to an uninterrupted oracle, the
  corrupt-checkpoint drill must roll back to the previous valid
  checkpoint, and the injected-NaN drill must survive via the device-side
  anomaly guard. The exact-resume contract is a standing gate, not a
  docstring claim.
* **serve** — the serving load drill (r12): ``python
  scripts/load_drill.py --small`` — a budgeted CPU trace (2 shape
  buckets, 4 concurrent clients incl. one warm-start video stream)
  through the continuous-batching scheduler: the poisoned request must
  fail alone, a mid-load SIGTERM must drain with zero lost admitted
  requests, ``cli compare`` must arbitrate served-vs-sequential
  throughput from the phase's telemetry, and the witness leg (r19) must
  find the load's actual lock-acquisition orders consistent with the
  static thread topology. The full >=3-bucket/8-client acceptance
  record is banked separately in runs/load_drill/.
* **trace** — the tracing rehearsal (r13): ``python
  scripts/trace_drill.py`` — a tiny CPU train and a tiny loadtest must
  each yield ``cli timeline`` exit 0 with >= 90% of every step's/
  request's wall time covered by named child spans, and ``cli doctor``
  exit 0 with a non-UNKNOWN verdict. The span instrumentation earns its
  keep on real runs, not just in tests/test_trace.py.
* **converge** — the convergence-observatory rehearsal (r14): ``python
  scripts/converge_drill.py`` — a tiny ``cli eval --stream on
  --iter_epe`` and a tiny ``cli loadtest`` must each leave schema-v8
  ``converge`` curves that lint clean, and ``cli converge <run_dir>``
  must replay them into a non-empty early-exit decision table
  (EPE-delta columns on the GT-backed eval leg) without re-running the
  model. The r16 adaptive leg closes the loop: ``cli converge
  --emit-policy`` distills the recorded eval run into a linted
  ``iter_policy.json``, the eval and loadtest re-run with
  ``--iter_policy``, and every request/frame must report
  ``iters_taken`` with p95 strictly under the policy budget at an EPE
  within the table's prediction.
* **numerics** — the numerics-observatory rehearsal (r15): ``python
  scripts/numerics_drill.py`` — seeded faults must come back with the
  CORRECT attribution: an injected all-NaN train batch names its step
  and leaves (NONFINITE_ORIGIN), a NaN-poisoned eval input names the
  dataflow-earliest tap at iteration 0, a seeded bf16-overflow stack
  fires the saturation counters (BF16_SATURATION), and a
  ``cli loadtest --numerics`` leaves per-dispatch ``numerics`` events
  plus the per-bucket output-range gauges.
* **fleet** — the fleet-observatory rehearsal (r17): ``python
  scripts/fleet_drill.py`` — a real 3-process CPU drill (one ``cli
  serve`` host, one sleep-injected straggler trainer, one SIGKILL'd
  trainer) whose merged ``cli fleet`` rollup must attribute STRAGGLER
  to the slow host and DEAD_HOST to the killed one, join the client's
  span to the server's request lifecycle across the process boundary
  via the traceparent header, and build one clock-aligned Perfetto
  timeline with a process-group per host; ``cli doctor`` over the
  fleet dir must route to the same verdicts.

Each leg appends a dated JSON record to ``runs/rehearsal.log`` through the
shared obs/ sink; exit status is non-zero if any attempted leg failed, so
the rehearsal can gate a round's end ritual.

Run: python scripts/rehearse_round.py
     [--legs bench multichip events compare scangrad lint fingerprint fault]
     [--bench-budget S] [--multichip-budget S] [--baseline RUN_DIR]
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from raft_stereo_tpu.obs.events import append_json_log  # noqa: E402

LOG = os.path.join(REPO, "runs", "rehearsal.log")

# The driver's own budgets (bench.py _DEADLINE_S; __graft_entry__
# _DRYRUN_DEADLINE_S), plus margin for the platform probe / interpreter
# startup / teardown that runs outside the inner deadline's clock.
BENCH_BUDGET_S = float(os.environ.get("BENCH_DEADLINE_S", "4800")) + 600
MULTICHIP_BUDGET_S = float(
    os.environ.get("GRAFT_DRYRUN_DEADLINE_S", "3600")) + 600


def run_leg(name, cmd, timeout_s, cwd=REPO, check_stdout=None, env=None):
    """Run one driver command under its budget; return the log record.

    ``check_stdout(stdout) -> error_or_None`` validates the artifact the
    driver would capture (e.g. the bench result JSON), because a command
    that exits 0 with an unparseable artifact is still a failed round.
    ``env``: extra environment entries layered over ``os.environ``.
    """
    t0 = time.monotonic()
    run_env = None if env is None else {**os.environ, **env}
    try:
        proc = subprocess.run(cmd, cwd=cwd, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True,
                              timeout=timeout_s, env=run_env)
        rc, out = proc.returncode, proc.stdout or ""
    except subprocess.TimeoutExpired as e:
        out = (e.stdout or b"")
        out = out.decode(errors="replace") if isinstance(out, bytes) else out
        rc = f"timeout>{timeout_s:.0f}s"
    wall = time.monotonic() - t0
    error = None
    if rc != 0:
        error = f"rc={rc}"
    elif check_stdout is not None:
        error = check_stdout(out)
    return {
        "leg": name,
        "cmd": cmd if isinstance(cmd, str) else " ".join(cmd),
        "ok": error is None,
        "rc": rc,
        "wall_s": round(wall, 1),
        "budget_s": timeout_s,
        "error": error,
        "tail": "\n".join(out.splitlines()[-6:]),
    }


def check_bench_stdout(out):
    """The driver parses bench.py's LAST stdout line as the result JSON."""
    lines = [l for l in out.splitlines() if l.strip()]
    if not lines:
        return "empty stdout (no result JSON)"
    try:
        result = json.loads(lines[-1])
    except json.JSONDecodeError:
        return f"last line is not JSON: {lines[-1][:120]!r}"
    if not isinstance(result.get("value"), (int, float)):
        return f"result JSON has no numeric 'value': {result}"
    return None


def check_event_artifacts(paths):
    """Schema-lint the round's JSONL artifacts that exist (missing is fine —
    a round need not have produced every log)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import check_events
    existing = [p for p in paths
                if os.path.exists(p if not os.path.isdir(p)
                                  else os.path.join(p, "events.jsonl"))]
    errors = []
    for p in existing:
        # attempt/frontier logs are dated-JSON but not schema-stamped event
        # records; only events.jsonl files go through the full lint
        if os.path.basename(p) == "events.jsonl" or os.path.isdir(p):
            errors.extend(check_events.check(p))
    return existing, errors


def compare_leg(baseline, candidate, timeout_s=300.0):
    """The regression-gate leg; skip-ok while either run dir is absent.

    Consumes the gate's machine report (``cli compare --json``) rather than
    scraping the text table: the rehearsal record carries the actual
    regression list and per-metric verdicts, so a failed leg says WHICH
    metric moved — and by how much — without re-running the comparison."""
    missing = [d for d in (baseline, candidate)
               if not os.path.exists(os.path.join(d, "events.jsonl"))]
    if missing:
        return {"leg": "compare", "ok": True, "skipped": True,
                "error": None, "baseline": baseline, "candidate": candidate,
                "note": f"no events.jsonl under {missing} — gate skipped"}
    report_path = os.path.join(REPO, "runs", "rehearsal_compare.json")
    rec = run_leg("compare",
                  [sys.executable, "-m", "raft_stereo_tpu.cli", "compare",
                   baseline, candidate, "--json", report_path],
                  timeout_s)
    rec.update(baseline=baseline, candidate=candidate)
    try:
        with open(report_path) as f:
            report = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        rec["ok"] = False
        rec["error"] = f"no readable JSON report at {report_path}: {e}"
        return rec
    rec["regressions"] = report.get("regressions", [])
    rec["metrics"] = {
        name: {"baseline": m["baseline"], "candidate": m["candidate"],
               "regression_rel": m["regression_rel"]}
        for name, m in report.get("metrics", {}).items()}
    if not rec["ok"] and rec["regressions"]:
        rec["error"] = "regressions: " + ", ".join(rec["regressions"])
    elif not rec["ok"] and report.get("error"):
        rec["error"] = report["error"]
    return rec


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Rehearse the driver's end-of-round commands under the "
                    "driver's budgets (see module doc)")
    p.add_argument("--legs", nargs="+",
                   default=["bench", "multichip", "events", "compare",
                            "scangrad", "fusedcorr", "lint", "fingerprint",
                            "fault", "serve", "trace", "converge",
                            "numerics", "fleet"],
                   choices=["bench", "multichip", "events", "compare",
                            "scangrad", "fusedcorr", "lint", "fingerprint",
                            "fault", "serve", "trace", "converge",
                            "numerics", "fleet"])
    p.add_argument("--scangrad-budget", type=float, default=1800.0)
    p.add_argument("--fusedcorr-budget", type=float, default=1800.0)
    p.add_argument("--lint-budget", type=float, default=900.0)
    p.add_argument("--fingerprint-budget", type=float, default=900.0)
    p.add_argument("--fault-budget", type=float, default=1800.0)
    p.add_argument("--serve-budget", type=float, default=1800.0)
    p.add_argument("--trace-budget", type=float, default=1800.0)
    p.add_argument("--converge-budget", type=float, default=1800.0)
    p.add_argument("--numerics-budget", type=float, default=1800.0)
    p.add_argument("--fleet-budget", type=float, default=1800.0)
    p.add_argument("--bench-budget", type=float, default=BENCH_BUDGET_S)
    p.add_argument("--multichip-budget", type=float,
                   default=MULTICHIP_BUDGET_S)
    p.add_argument("--baseline",
                   default=os.path.join(REPO, "runs", "bench", "previous"),
                   help="baseline run dir for the compare gate (default: "
                        "the previous bench chain's rotated telemetry)")
    p.add_argument("--candidate",
                   default=os.path.join(REPO, "runs", "bench", "current"),
                   help="candidate run dir for the compare gate")
    args = p.parse_args(argv)

    records = []
    if "bench" in args.legs:
        records.append(run_leg(
            "bench", [sys.executable, os.path.join(REPO, "bench.py")],
            args.bench_budget, check_stdout=check_bench_stdout))
    if "multichip" in args.legs:
        records.append(run_leg(
            "multichip",
            [sys.executable, os.path.join(REPO, "__graft_entry__.py")],
            args.multichip_budget))
    if "events" in args.legs:
        import glob
        candidates = ([os.path.join(REPO, "runs", "bench", "attempts.jsonl")]
                      + glob.glob(os.path.join(REPO, "runs", "*",
                                               "events.jsonl")))
        checked, errors = check_event_artifacts(candidates)
        records.append({"leg": "events", "ok": not errors,
                        "checked": checked, "error": "; ".join(errors[:5])
                        or None})
    if "compare" in args.legs:
        records.append(compare_leg(args.baseline, args.candidate))
    if "scangrad" in args.legs:
        records.append(run_leg(
            "scangrad",
            [sys.executable, "-m", "pytest", "tests/test_scan_grad.py",
             "-q", "-m", "not slow", "-p", "no:cacheprovider"],
            args.scangrad_budget, env={"JAX_PLATFORMS": "cpu"}))
    if "fusedcorr" in args.legs:
        records.append(run_leg(
            "fusedcorr",
            [sys.executable, "-m", "pytest", "tests/test_fused_corr.py",
             "-q", "-m", "not slow", "-p", "no:cacheprovider"],
            args.fusedcorr_budget, env={"JAX_PLATFORMS": "cpu"}))
    if "lint" in args.legs:
        records.append(run_leg(
            "lint", [sys.executable, "-m", "raft_stereo_tpu.cli", "lint"],
            args.lint_budget, env={"JAX_PLATFORMS": "cpu"}))
    if "fingerprint" in args.legs:
        records.append(run_leg(
            "fingerprint",
            [sys.executable, "-m", "raft_stereo_tpu.cli", "lint",
             "--fingerprint"],
            args.fingerprint_budget, env={"JAX_PLATFORMS": "cpu"}))
    if "fault" in args.legs:
        records.append(run_leg(
            "fault",
            [sys.executable, os.path.join(REPO, "scripts",
                                          "fault_drill.py")],
            args.fault_budget, env={"JAX_PLATFORMS": "cpu"}))
    if "serve" in args.legs:
        records.append(run_leg(
            "serve",
            [sys.executable, os.path.join(REPO, "scripts", "load_drill.py"),
             "--small", "--shapes", "48x96", "64x128",
             "--clients", "4", "--requests", "3",
             # witness: the drilled interleavings' actual lock-acquisition
             # orders are held against engine 4's static thread topology
             "--drills", "poison", "sigterm", "compare", "witness"],
            args.serve_budget, env={"JAX_PLATFORMS": "cpu"}))
    if "trace" in args.legs:
        records.append(run_leg(
            "trace",
            [sys.executable, os.path.join(REPO, "scripts",
                                          "trace_drill.py")],
            args.trace_budget, env={"JAX_PLATFORMS": "cpu"}))
    if "converge" in args.legs:
        records.append(run_leg(
            "converge",
            [sys.executable, os.path.join(REPO, "scripts",
                                          "converge_drill.py")],
            args.converge_budget, env={"JAX_PLATFORMS": "cpu"}))
    if "numerics" in args.legs:
        records.append(run_leg(
            "numerics",
            [sys.executable, os.path.join(REPO, "scripts",
                                          "numerics_drill.py")],
            args.numerics_budget, env={"JAX_PLATFORMS": "cpu"}))
    if "fleet" in args.legs:
        records.append(run_leg(
            "fleet",
            [sys.executable, os.path.join(REPO, "scripts",
                                          "fleet_drill.py")],
            args.fleet_budget, env={"JAX_PLATFORMS": "cpu"}))

    ok = True
    for rec in records:
        append_json_log(LOG, rec, stream=sys.stderr)
        ok = ok and rec["ok"]
    print(("rehearsal ok: " if ok else "REHEARSAL FAILED: ")
          + ", ".join(f"{r['leg']}={'ok' if r['ok'] else 'FAIL'}"
                      for r in records))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
