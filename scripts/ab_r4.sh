#!/bin/bash
# r4 A/B chain on the real chip: isolate the fused-lookup kernel's and the
# upsample-remat's contributions at the SceneFlow b8 recipe, then probe the
# schedules the AOT memory fix may have unlocked. Run on an OTHERWISE IDLE
# host (the lagged-fetch timing protocol is dispatch-sensitive on 1 core).
set -u
cd "$(dirname "$0")/.."
R='{"batch": 8, "h": 320, "w": 720, "train_iters": 22, "steps": 6, "fused_loss": true'
run() {
  echo "=== $1"
  timeout 1500 python bench.py --attempt "$2" 2>&1 | grep -E "BENCH_RESULT|Error|Exceeded|RESOURCE" | tail -2
}
run "banker blocks + fused_lookup OFF (r2 config + upsample remat)" "$R, \"remat_encoders\": \"blocks\", \"fused_lookup\": false}"
run "banker blocks + fused_lookup ON" "$R, \"remat_encoders\": \"blocks\"}"
run "norms monolith + fused ON (no conv re-runs)" "$R, \"remat_encoders\": \"norms\"}"
run "plain monolith (the primary)" "$R}"
run "b4 deferred-fused + ON" '{"batch": 4, "h": 320, "w": 720, "train_iters": 22, "steps": 6, "fused_loss": true}'
run "b4 deferred-fused + OFF" '{"batch": 4, "h": 320, "w": 720, "train_iters": 22, "steps": 6, "fused_loss": true, "fused_lookup": false}'
