#!/usr/bin/env python
"""Trained-scale EPE parity vs the PyTorch reference (no datasets needed).

``parity_check.py`` compares random-init models on random images; this script
closes the remaining acceptance gap ("EPE within 1% of the PyTorch baseline",
BASELINE.md) at *trained* scale without network access:

1. Builds synthetic stereo pairs with KNOWN ground truth: a smooth random
   disparity field warps a smooth random texture (img2(x) = img1(x - d(x))),
   so EPE against GT is well-defined for both models.
2. Trains the torch reference for a few hundred steps on such pairs — with
   BatchNorm running stats UPDATING (unlike the reference's freeze_bn
   training) so the converted checkpoint carries non-trivial BN statistics,
   where conversion bugs and bf16 drift actually bite.
3. Converts the trained state dict (utils/checkpoint_convert.py) and
   evaluates BOTH models at full SceneFlow eval scale (320x720 pad /32,
   32 iters, fp32): the acceptance criterion is relative EPE deviation
   |EPE_jax - EPE_torch| / EPE_torch <= --tolerance (default 1%).
4. Also reports (does not gate on) the mixed-precision bf16 deltas: compute
   dtype bf16, and bf16 correlation-volume storage (config
   corr_storage_dtype) — the measured numbers PERF/PARITY cite.

Run: python scripts/parity_trained.py [--train_steps 150] [--pairs 3]
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def smooth_field(rng, h, w, channels, octaves=4, base=8):
    """Sum of bilinearly-upsampled random grids — a cheap smooth texture."""
    out = np.zeros((h, w, channels), np.float32)
    try:
        import cv2
        resize = lambda g: cv2.resize(g, (w, h), interpolation=cv2.INTER_LINEAR)
    except ImportError:
        from PIL import Image
        def resize(g):
            return np.stack(
                [np.asarray(Image.fromarray(g[..., c]).resize(
                    (w, h), Image.BILINEAR)) for c in range(g.shape[-1])],
                axis=-1)
    for o in range(octaves):
        gh, gw = base * (2 ** o), base * (2 ** o)
        grid = rng.standard_normal((gh, gw, channels)).astype(np.float32)
        r = resize(grid)
        if r.ndim == 2:
            r = r[..., None]
        out += r / (2 ** o)
    return out


def make_pair(rng, h, w, max_disp=48.0):
    """(img1, img2, disparity) with img2 the GT-warped img1."""
    tex = smooth_field(rng, h, w, 3)
    tex = (tex - tex.min()) / (np.ptp(tex) + 1e-6) * 255.0
    d = smooth_field(rng, h, w, 1, octaves=3)
    d = (d - d.min()) / (np.ptp(d) + 1e-6) * rng.uniform(0.3, 1.0) * max_disp
    # Disparity convention: left pixel x matches right pixel x - d. We
    # synthesize the RIGHT image by sampling the left texture at x + d,
    # using the LEFT-frame field d as an approximate inverse warp — the
    # exact left-frame disparity at x' = x + d(x) is d(x), not d(x'), so
    # the GT is approximate and absolute EPE is only indicative. The parity
    # verdict is unaffected: both models are scored against the same field,
    # and only the torch-vs-jax relative deviation gates.
    xs = np.arange(w, dtype=np.float32)[None, :, None] + d
    x0 = np.clip(np.floor(xs), 0, w - 1).astype(np.int64)
    x1 = np.clip(x0 + 1, 0, w - 1)
    frac = np.clip(xs - x0, 0.0, 1.0)
    rows = np.arange(h)[:, None]
    img2 = (tex[rows, x0[..., 0], :] * (1 - frac) +
            tex[rows, x1[..., 0], :] * frac)
    return tex.astype(np.float32), img2.astype(np.float32), d[..., 0]


def epe(disp_pred, disp_gt):
    return float(np.mean(np.abs(disp_pred - disp_gt)))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--reference_dir", default="/root/reference")
    p.add_argument("--train_steps", type=int, default=150)
    p.add_argument("--train_size", type=int, nargs=2, default=[128, 256])
    p.add_argument("--train_iters", type=int, default=7)
    p.add_argument("--eval_size", type=int, nargs=2, default=[320, 720])
    p.add_argument("--eval_iters", type=int, default=32)
    p.add_argument("--pairs", type=int, default=3)
    p.add_argument("--seed", type=int, default=11)
    p.add_argument("--tolerance", type=float, default=0.01,
                   help="max relative EPE deviation vs torch (1%% default)")
    p.add_argument("--realtime_steps", type=int, default=120,
                   help="torch training steps for the realtime-preset "
                        "parity phase (0 skips it)")
    args = p.parse_args()

    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import torch

    sys.path.insert(0, args.reference_dir)
    from core.raft_stereo import RAFTStereo as TorchRAFTStereo

    from raft_stereo_tpu.config import RAFTStereoConfig
    from raft_stereo_tpu.models import create_model, init_model
    from raft_stereo_tpu.utils.checkpoint_convert import (
        convert_state_dict, validate_against_variables)

    torch.manual_seed(args.seed)
    targs = argparse.Namespace(
        hidden_dims=[128, 128, 128], corr_implementation="reg",
        shared_backbone=False, corr_levels=4, corr_radius=4, n_downsample=2,
        context_norm="batch", slow_fast_gru=False, n_gru_layers=3,
        mixed_precision=False)
    tmodel = TorchRAFTStereo(targs)

    # --- short torch training on synthetic pairs (BN stats updating) -------
    rng = np.random.default_rng(args.seed)
    th, tw = args.train_size
    tmodel.train()  # NO freeze_bn: running stats must move
    opt = torch.optim.AdamW(tmodel.parameters(), lr=2e-4, weight_decay=1e-5)
    t0 = time.time()
    for step in range(args.train_steps):
        i1, i2, d = make_pair(rng, th, tw)
        im1 = torch.from_numpy(i1.transpose(2, 0, 1))[None]
        im2 = torch.from_numpy(i2.transpose(2, 0, 1))[None]
        flow_gt = torch.from_numpy(-d)[None, None]  # flow-x = -disparity
        preds = tmodel(im1, im2, iters=args.train_iters)
        gamma = 0.9 ** (15.0 / max(args.train_iters - 1, 1))
        loss = sum((gamma ** (len(preds) - 1 - i)) *
                   (pred[:, :1] - flow_gt).abs().mean()
                   for i, pred in enumerate(preds))
        opt.zero_grad()
        loss.backward()
        torch.nn.utils.clip_grad_norm_(tmodel.parameters(), 1.0)
        opt.step()
        if step % 25 == 0 or step == args.train_steps - 1:
            print(f"torch train step {step:4d} loss {float(loss):.3f} "
                  f"({time.time()-t0:.0f}s)", flush=True)
    tmodel.eval()
    sd = tmodel.state_dict()
    rm = sd["cnet.norm1.running_mean"]
    print(f"BN running stats moved: |mean| {float(rm.abs().mean()):.4f} "
          f"(zero at init)", flush=True)
    assert float(rm.abs().mean()) > 1e-3, "BN stats did not update"

    # --- convert & evaluate both at full scale -----------------------------
    cfg = RAFTStereoConfig()  # fp32 eval default (fp32 volume)
    model, variables = init_model(jax.random.PRNGKey(0), cfg, (1, 64, 128, 3))
    converted = validate_against_variables(convert_state_dict(sd), variables)

    # Gated variants (fp32): the default XLA path AND the Pallas kernels the
    # TPU presets actually select (reg_pallas: windowed lookup kernel;
    # alt_pallas: fused build+lookup — reference semantics core/corr.py:31-61
    # and :64-107). On CPU the Pallas kernels execute in interpreter mode —
    # the same kernel code path the TPU compiles. bf16 variants reported,
    # not gated.
    gated = {
        "fp32": create_model(cfg),
        "fp32+reg_pallas": create_model(RAFTStereoConfig(
            corr_implementation="reg_pallas",
            corr_storage_dtype="float32")),
        "fp32+alt_pallas": create_model(RAFTStereoConfig(
            corr_implementation="alt_pallas",
            corr_storage_dtype="float32")),
        # r4 fused kernel: 4-level pyramid lookup + convc1 in one Pallas
        # kernel (fused_lookup) — opt-in (measured slower than XLA's
        # unfused path, PERF.md r4 A/B; parity still pinned here).
        "fp32+fused_r4": create_model(RAFTStereoConfig(
            fused_lookup=True)),
    }
    variants = {
        **gated,
        "bf16": create_model(RAFTStereoConfig(mixed_precision=True)),
        "bf16+bf16vol": create_model(RAFTStereoConfig(
            mixed_precision=True, corr_storage_dtype="bfloat16")),
    }

    eh, ew = args.eval_size
    results = {k: [] for k in ["torch", *variants]}
    for i in range(args.pairs):
        i1, i2, d = make_pair(rng, eh, ew)
        with torch.no_grad():
            _, t_up = tmodel(
                torch.from_numpy(i1.transpose(2, 0, 1))[None],
                torch.from_numpy(i2.transpose(2, 0, 1))[None],
                iters=args.eval_iters, test_mode=True)
        results["torch"].append(epe(-t_up.numpy()[0, 0], d))
        for name, m in variants.items():
            _, j_up = m.apply(converted, jnp.asarray(i1)[None],
                              jnp.asarray(i2)[None],
                              iters=args.eval_iters, test_mode=True)
            results[name].append(epe(-np.asarray(j_up)[0, ..., 0], d))
        print(f"pair {i}: torch EPE {results['torch'][-1]:.4f}  " +
              "  ".join(f"{k} {results[k][-1]:.4f}" for k in variants),
              flush=True)

    t_epe = float(np.mean(results["torch"]))
    print(f"\nmean EPE over {args.pairs} pairs at {eh}x{ew}/"
          f"{args.eval_iters} iters:")
    rel = {}
    for k in variants:
        j_epe = float(np.mean(results[k]))
        rel[k] = abs(j_epe - t_epe) / max(t_epe, 1e-9)
        print(f"  torch {t_epe:.4f} vs {k:13s} {j_epe:.4f}  "
              f"rel-dev {100*rel[k]:.3f}%")

    failed = [k for k in gated if rel[k] > args.tolerance]
    if failed:
        for k in failed:
            print(f"FAIL: {k} relative EPE deviation {100*rel[k]:.3f}% "
                  f"> {100*args.tolerance:.1f}%")
        return 1
    print(f"PASS: {', '.join(gated)} within {100*args.tolerance:.1f}% of "
          f"the torch baseline (bf16 deltas reported above are "
          f"informational)")

    if args.realtime_steps > 0:
        rc = realtime_parity(args, make_pair, epe)
        if rc:
            return rc
    return 0


def realtime_parity(args, make_pair, epe):
    """Trained-scale parity for the shared-backbone realtime preset
    (README.md:105: shared_backbone, n_downsample 3, n_gru_layers 2,
    slow_fast_gru, 7 iters). Trains a separate torch model with the realtime
    architecture (corr 'reg' — the CPU-runnable oracle for reg_cuda), then
    gates the converted jax model in fp32 with both the XLA 'reg' path and
    the 'reg_pallas' kernel the TPU preset defaults to; the preset's own
    bf16 numbers are reported, not gated."""
    import argparse as _ap
    import jax
    import jax.numpy as jnp
    import torch

    from raft_stereo_tpu.config import RAFTStereoConfig
    from raft_stereo_tpu.models import create_model, init_model
    from raft_stereo_tpu.utils.checkpoint_convert import (
        convert_state_dict, validate_against_variables)
    from core.raft_stereo import RAFTStereo as TorchRAFTStereo

    print("\n--- realtime preset (shared backbone) parity ---", flush=True)
    torch.manual_seed(args.seed + 1)
    targs = _ap.Namespace(
        hidden_dims=[128, 128, 128], corr_implementation="reg",
        shared_backbone=True, corr_levels=4, corr_radius=4, n_downsample=3,
        context_norm="batch", slow_fast_gru=True, n_gru_layers=2,
        mixed_precision=False)
    tmodel = TorchRAFTStereo(targs)

    rng = np.random.default_rng(args.seed + 1)
    th, tw = args.train_size
    tmodel.train()
    opt = torch.optim.AdamW(tmodel.parameters(), lr=2e-4, weight_decay=1e-5)
    t0 = time.time()
    for step in range(args.realtime_steps):
        i1, i2, d = make_pair(rng, th, tw)
        im1 = torch.from_numpy(i1.transpose(2, 0, 1))[None]
        im2 = torch.from_numpy(i2.transpose(2, 0, 1))[None]
        flow_gt = torch.from_numpy(-d)[None, None]
        preds = tmodel(im1, im2, iters=args.train_iters)
        gamma = 0.9 ** (15.0 / max(args.train_iters - 1, 1))
        loss = sum((gamma ** (len(preds) - 1 - i)) *
                   (pred[:, :1] - flow_gt).abs().mean()
                   for i, pred in enumerate(preds))
        opt.zero_grad()
        loss.backward()
        torch.nn.utils.clip_grad_norm_(tmodel.parameters(), 1.0)
        opt.step()
        if step % 25 == 0 or step == args.realtime_steps - 1:
            print(f"torch realtime train step {step:4d} loss "
                  f"{float(loss):.3f} ({time.time()-t0:.0f}s)", flush=True)
    tmodel.eval()
    sd = tmodel.state_dict()

    base = dict(shared_backbone=True, n_downsample=3, n_gru_layers=2,
                slow_fast_gru=True)
    cfg = RAFTStereoConfig(**base)
    _, variables = init_model(jax.random.PRNGKey(0), cfg, (1, 64, 128, 3))
    converted = validate_against_variables(convert_state_dict(sd), variables)

    gated = {
        "rt-fp32": create_model(cfg),
        "rt-fp32+reg_pallas": create_model(RAFTStereoConfig(
            **base, corr_implementation="reg_pallas",
            corr_storage_dtype="float32")),
        "rt-fp32+fused_r4": create_model(RAFTStereoConfig(
            **base, fused_lookup=True)),
    }
    variants = {
        **gated,
        "rt-preset(bf16+reg_pallas)": create_model(RAFTStereoConfig(
            **base, corr_implementation="reg_pallas",
            mixed_precision=True)),
    }

    # realtime runs 7 iterations at 1/8 res; eval size must divide the
    # n_downsample=3 pyramid (x32 with the 2-level GRU's /16... use /32)
    eh, ew = args.eval_size
    eh, ew = (eh // 32) * 32, (ew // 32) * 32
    iters = 7
    results = {k: [] for k in ["torch", *variants]}
    for i in range(args.pairs):
        i1, i2, d = make_pair(rng, eh, ew)
        with torch.no_grad():
            _, t_up = tmodel(
                torch.from_numpy(i1.transpose(2, 0, 1))[None],
                torch.from_numpy(i2.transpose(2, 0, 1))[None],
                iters=iters, test_mode=True)
        results["torch"].append(epe(-t_up.numpy()[0, 0], d))
        for name, m in variants.items():
            _, j_up = m.apply(converted, jnp.asarray(i1)[None],
                              jnp.asarray(i2)[None],
                              iters=iters, test_mode=True)
            results[name].append(epe(-np.asarray(j_up)[0, ..., 0], d))
        print(f"pair {i}: torch EPE {results['torch'][-1]:.4f}  " +
              "  ".join(f"{k} {results[k][-1]:.4f}" for k in variants),
              flush=True)

    t_epe = float(np.mean(results["torch"]))
    print(f"\nrealtime mean EPE over {args.pairs} pairs at {eh}x{ew}/"
          f"{iters} iters:")
    rel = {}
    for k in variants:
        j_epe = float(np.mean(results[k]))
        rel[k] = abs(j_epe - t_epe) / max(t_epe, 1e-9)
        print(f"  torch {t_epe:.4f} vs {k:26s} {j_epe:.4f}  "
              f"rel-dev {100*rel[k]:.3f}%")
    failed = [k for k in gated if rel[k] > args.tolerance]
    if failed:
        for k in failed:
            print(f"FAIL: {k} relative EPE deviation {100*rel[k]:.3f}% "
                  f"> {100*args.tolerance:.1f}%")
        return 1
    print(f"PASS: {', '.join(gated)} within {100*args.tolerance:.1f}% of "
          f"the torch realtime baseline")
    return 0


if __name__ == "__main__":
    sys.exit(main())
