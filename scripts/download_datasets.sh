#!/bin/bash
# Middlebury MiddEval3 (Q/H/F + GT) and ETH3D two-view sets, laid out as
# raft_stereo_tpu/data/datasets.py expects under datasets/.
set -e
mkdir -p datasets/Middlebury datasets/ETH3D
cd datasets/Middlebury
mkdir -p MiddEval3
wget -nc https://www.dropbox.com/s/fn8siy5muak3of3/official_train.txt -P MiddEval3/
for split in Q H F; do
  wget -nc https://vision.middlebury.edu/stereo/submit3/zip/MiddEval3-data-$split.zip
  unzip -on MiddEval3-data-$split.zip
  wget -nc https://vision.middlebury.edu/stereo/submit3/zip/MiddEval3-GT0-$split.zip
  unzip -on MiddEval3-GT0-$split.zip
done
cd ../ETH3D
wget -nc https://www.eth3d.net/data/two_view_training.7z
7z x -y two_view_training.7z -otwo_view_training
wget -nc https://www.eth3d.net/data/two_view_training_gt.7z
7z x -y two_view_training_gt.7z -otwo_view_training_gt
wget -nc https://www.eth3d.net/data/two_view_test.7z
7z x -y two_view_test.7z -otwo_view_test
