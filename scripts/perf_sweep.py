"""Perf sweep on real TPU: time train-step variants to find throughput headroom.

Times the SceneFlow-recipe training step (batch 8, 22 iters, 320x720) across
corr implementations, volume-storage precisions, remat on/off and the
fused-loss path, plus forward-only and iteration-count scaling to split
per-iteration cost from fixed cost. Prints one line per variant:
pairs/sec/chip and ms/step.
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

import os
import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from raft_stereo_tpu.config import RAFTStereoConfig, TrainConfig
from raft_stereo_tpu.models import init_model
from raft_stereo_tpu.training.optim import fetch_optimizer
from raft_stereo_tpu.training.state import TrainState, make_train_step


def make_batch(rng, batch, h, w):
    k1, k2, k3 = jax.random.split(rng, 3)
    return {
        "image1": jax.random.uniform(k1, (batch, h, w, 3), jnp.float32) * 255,
        "image2": jax.random.uniform(k2, (batch, h, w, 3), jnp.float32) * 255,
        "flow": -jax.random.uniform(k3, (batch, h, w, 1), jnp.float32) * 50,
        "valid": jnp.ones((batch, h, w), jnp.float32),
    }


# NOTE: on tunneled TPU devices (axon), block_until_ready has been observed
# to return before queued executions finish (see bench.py); a host transfer
# of an executable output is the only reliable synchronization point. The
# warmup + lagged-fetch protocol here mirrors bench.py:run_bench — change
# them together.

def time_step(fn, state, batch, steps=4):
    state, m = fn(state, batch)  # compile + warmup
    float(m["loss"])
    state, m = fn(state, batch)
    float(m["loss"])
    t0 = time.perf_counter()
    prev = None
    for _ in range(steps):
        state, m = fn(state, batch)
        if prev is not None:
            float(prev["loss"])
        prev = m
    float(prev["loss"])
    return (time.perf_counter() - t0) / steps


def time_fwd(model, variables, batch, iters, steps=4):
    @jax.jit
    def fwd(v, b):
        preds = model.apply(v, b["image1"], b["image2"], iters=iters)
        return jnp.sum(preds[-1])

    float(fwd(variables, batch))
    float(fwd(variables, batch))
    t0 = time.perf_counter()
    outs = [fwd(variables, batch) for _ in range(steps)]
    for o in outs:
        float(o)
    return (time.perf_counter() - t0) / steps


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--size", type=int, nargs=2, default=(320, 720))
    p.add_argument("--iters", type=int, default=22)
    p.add_argument("--variants", nargs="*", default=None)
    args = p.parse_args()

    batch, (h, w), iters = args.batch, args.size, args.iters
    data = make_batch(jax.random.PRNGKey(1), batch, h, w)
    tcfg = TrainConfig(batch_size=batch, train_iters=iters, num_steps=200000,
                       image_size=(h, w))

    variants = {
        "reg/full-remat": dict(corr_implementation="reg"),
        "reg/no-remat": dict(corr_implementation="reg",
                             remat_refinement=False),
        "reg/fp32-volume": dict(corr_implementation="reg",
                                corr_storage_dtype="float32"),
        "reg/in-scan-upsample": dict(corr_implementation="reg",
                                     deferred_upsample=False),
        "reg_pallas/full-remat": dict(corr_implementation="reg_pallas"),
        "alt/full-remat": dict(corr_implementation="alt"),
        "alt_pallas/full-remat": dict(corr_implementation="alt_pallas"),
        "reg/fused-loss": dict(corr_implementation="reg", _fused=True),
        "reg/remat-enc": dict(corr_implementation="reg", remat_encoders=True),
    }
    if args.variants:
        variants = {k: v for k, v in variants.items()
                    if any(s in k for s in args.variants)}

    results = {}
    for name, overrides in variants.items():
        overrides = dict(overrides)
        fused = overrides.pop("_fused", False)
        overrides.setdefault("corr_storage_dtype", "bfloat16")
        cfg = RAFTStereoConfig(mixed_precision=True, **overrides)
        model, variables = init_model(jax.random.PRNGKey(0), cfg, (1, h, w, 3))
        tx = fetch_optimizer(tcfg)
        state = TrainState.create(variables, tx)
        step = jax.jit(make_train_step(model, tx, iters, fused_loss=fused))
        try:
            dt = time_step(step, state, data)
        except Exception as e:  # OOM etc.
            print(f"{name:28s} FAILED: {type(e).__name__}: {str(e)[:120]}")
            continue
        results[name] = dt
        print(f"{name:28s} {dt*1e3:8.1f} ms/step  "
              f"{batch/dt:6.2f} pairs/sec/chip", flush=True)

    # iteration scaling + forward-only on the best variant
    if not results:
        print("all variants failed; skipping scaling runs")
        return
    best = min(results, key=results.get)
    best_overrides = dict(variants[best])
    best_overrides.pop("_fused", None)
    best_overrides.setdefault("corr_storage_dtype", "bfloat16")
    cfg = RAFTStereoConfig(mixed_precision=True, **best_overrides)
    model, variables = init_model(jax.random.PRNGKey(0), cfg, (1, h, w, 3))
    for n in (2, iters):
        dt = time_fwd(model, variables, data, n)
        print(f"fwd-only iters={n:2d} ({best})   {dt*1e3:8.1f} ms", flush=True)
    tx = fetch_optimizer(tcfg)
    state = TrainState.create(variables, tx)
    best_fused = variants[best].get("_fused", False)
    for n in (2,):
        step = jax.jit(make_train_step(model, tx, n, fused_loss=best_fused))
        dt = time_step(step, state, data)
        print(f"train iters={n:2d} ({best})      {dt*1e3:8.1f} ms", flush=True)


if __name__ == "__main__":
    main()
