"""Compile-retry harness: bank the plain-b8 monolith into the persistent cache.

The monolithic (no encoder remat) batch-8 train step is the fastest projected
recipe (~10.3 pairs/s, PERF.md) but the tunneled remote-compile helper has
rejected it in every session since round 1. This harness retries an AOT
compile-only attempt of EXACTLY the bench primary's graph (bench.py
``--attempt`` with ``compile_only``) on a timer, in fresh subprocesses, until
one window lands the executable in the shared persistent ``.jax_cache`` —
after which ``bench.py``'s primary attempt hits the cache forever and the
projected number becomes measurable.

r5 update: this harness's captured stderr root-caused the rejection — the
terminal shunts big graphs to a ``tpu_compile_helper`` subprocess whose
``TPU_WORKER_HOSTNAMES`` env var holds a shell warning string, so the
failure is DETERMINISTIC for over-threshold graphs, not helper weather
(PERF.md "r5: the monolith rejection root-caused"). The probe stays useful
as a canary for the terminal image getting fixed; its dated failure log is
the round's record either way. (The split-compilation step this harness
also probed in early r5 windows was deleted the same round: its b8 pieces
hit the same deterministic bug, falsifying its premise that pieces compile
where the monolith does not.)

Every attempt is appended as a dated JSON line to ``runs/monolith_probe.log``
so the round records either the bank or N dated windows that all failed.

Run: python scripts/bank_monolith.py [--interval 1200] [--max-hours 10]
     [--once]
"""

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from bench import (  # noqa: E402  (no jax at module level)
    append_json_log, primary_attempt_kwargs,
    run_attempt_subprocess_detailed)

LOG_PATH = os.path.join(REPO, "runs", "monolith_probe.log")

# The bench primary's exact kwargs (single source: bench.py) plus
# compile_only — identical config => identical HLO => identical cache key.
MONOLITH = dict(compile_only=True, **primary_attempt_kwargs())


def _log(entry):
    append_json_log(LOG_PATH, entry)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--interval", type=float, default=1200.0,
                   help="seconds between probe windows")
    p.add_argument("--max-hours", type=float, default=10.0)
    p.add_argument("--timeout", type=float, default=1200.0,
                   help="per-attempt subprocess timeout")
    p.add_argument("--once", action="store_true")
    args = p.parse_args()

    deadline = time.time() + args.max_hours * 3600
    banked = False
    window = 0
    while time.time() < deadline and not banked:
        window += 1
        result, err, dt = run_attempt_subprocess_detailed(
            MONOLITH, args.timeout)
        _log({"window": window, "target": "monolith",
              "ok": result is not None,
              "compile_s": None if result is None else result["value"],
              # a CPU-host probe proves the harness, not the TPU helper —
              # the platform on record keeps the two kinds of window apart
              "platform": None if result is None else result.get("platform"),
              "error": None if err is None else err[:400],
              "wall_s": round(dt, 1)})
        banked = result is not None
        if args.once or banked:
            break
        time.sleep(args.interval)
    _log({"done": True, "windows": window, "monolith_banked": banked})
    return 0 if banked else 1


if __name__ == "__main__":
    sys.exit(main())
