#!/usr/bin/env python
"""Serving load drill: prove the continuous-batching claims with traffic,
not a docstring.

The serving subsystem (raft_stereo_tpu/serve) claims that a mixed-shape
many-client load — including a poisoned request and a mid-load SIGTERM —
is served with zero lost admitted requests, per-request fault isolation,
and sustained batched throughput no worse than a sequential ``predict()``
loop over the same trace. This drill makes those claims a gate. Every leg
drives the REAL CLI surface (``python -m raft_stereo_tpu.cli loadtest``)
as a subprocess, on CPU, in-sandbox:

* **poison** — the full drill trace (>=3 shape buckets, >=8 concurrent
  client streams, >=1 video stream riding flow_init warm starts) with one
  NaN-poisoned request: exactly that request must retire as an error
  (device-side finiteness flag), its batchmates untouched, zero lost; the
  phase also leaves the seq/serve telemetry run dirs for the compare leg.
* **sigterm** — the same trace, SIGTERM'd mid-load once enough progress
  lines landed: the server must drain (exit 0), every admitted request
  retired (zero lost), later submits rejected-not-lost.
* **compare** — the existing run-regression gate (``cli compare --json``)
  arbitrates served-vs-sequential throughput from the poison phase's two
  run dirs — served sustained pairs/s must not drop more than the gate's
  threshold below the sequential baseline — and the serve events must
  carry the v6 ``slo`` rollups (p50/p99, in_flight) plus per-entry
  ``xla_memory`` introspection.
* **witness** — the same trace under ``RAFT_LOCK_WITNESS``
  (obs/lockwitness.py): the actual lock-acquisition orders the load
  exercised are held against graftlint engine 4's static thread
  topology; a contradiction or a dynamically-closed lock-order cycle
  fails the leg.

Each leg appends a JSON record to ``runs/load_drill/drills.jsonl``
through the shared obs/ sink; exit status is non-zero if any leg failed,
so scripts/rehearse_round.py's ``serve`` leg can gate a round on it.

Run: python scripts/load_drill.py [--drills poison sigterm compare
     witness]
     [--shapes 48x96 64x128 96x64] [--clients 8] [--requests 4]
     [--max-batch 2] [--iters 2] [--keep-work]
"""

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from raft_stereo_tpu.obs.events import append_json_log  # noqa: E402

OUT = os.path.join(REPO, "runs", "load_drill")
LOG = os.path.join(OUT, "drills.jsonl")

CHILD_TIMEOUT_S = 1800.0


def loadtest_cmd(args, run_dir, poison_at=None, requests=None):
    cmd = [sys.executable, "-m", "raft_stereo_tpu.cli", "loadtest",
           "--run_dir", run_dir, "--shapes", *args.shapes,
           "--clients", str(args.clients),
           "--requests_per_client", str(requests or args.requests),
           "--video_streams", "1", "--iters", str(args.iters),
           "--max_batch", str(args.max_batch), "--window", "2",
           "--slo_every", "4", "--seed", str(args.seed)]
    if poison_at is not None:
        cmd += ["--poison_at", str(poison_at)]
    return cmd


def parse_summary(stdout):
    for line in reversed(stdout.splitlines()):
        if line.startswith("LOADTEST summary "):
            return json.loads(line[len("LOADTEST summary "):])
    return None


def drill_poison(args, work):
    """Full trace + one poisoned request; leaves seq/serve run dirs."""
    run_dir = os.path.join(work, "poison")
    # poison a mid-trace ordinal on a non-video client so the video
    # session's warm-start chain stays a clean-path proof
    poison_at = args.requests * 2 + 1
    t0 = time.monotonic()
    proc = subprocess.run(loadtest_cmd(args, run_dir, poison_at=poison_at),
                          cwd=REPO, stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT, text=True,
                          timeout=CHILD_TIMEOUT_S)
    wall = time.monotonic() - t0
    summary = parse_summary(proc.stdout or "")
    errors = []
    if proc.returncode != 0:
        errors.append(f"loadtest rc={proc.returncode}")
    if summary is None:
        errors.append("no LOADTEST summary line")
        served = {}
    else:
        served = summary["served"]
        total = args.clients * args.requests
        if served.get("lost") != 0:
            errors.append(f"lost={served.get('lost')} admitted requests")
        if served.get("failed") != 1 or served.get("poisoned_failed") != 1:
            errors.append(
                f"expected exactly the poisoned request to fail, got "
                f"failed={served.get('failed')} "
                f"poisoned_failed={served.get('poisoned_failed')}")
        if served.get("ok") != total - 1:
            errors.append(f"ok={served.get('ok')}, expected {total - 1}")
        if served.get("rejected") != 0:
            errors.append(f"rejected={served.get('rejected')} without drain")
        if not served.get("drained"):
            errors.append("server did not drain cleanly")
    return {
        "drill": "poison", "ok": not errors, "wall_s": round(wall, 1),
        "poison_at": poison_at, "summary": summary,
        "error": "; ".join(errors) or None,
        "tail": "\n".join((proc.stdout or "").splitlines()[-5:]),
    }, run_dir


def drill_sigterm(args, work):
    """SIGTERM mid-load: drain must finish every admitted request."""
    run_dir = os.path.join(work, "sigterm")
    # longer trace so the signal lands with work still queued
    requests = args.requests * 2
    total = args.clients * requests
    threshold = max(2, total // 6)
    t0 = time.monotonic()
    proc = subprocess.Popen(
        loadtest_cmd(args, run_dir, requests=requests), cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        bufsize=1)
    lines, sent_at = [], None
    try:
        for line in proc.stdout:
            lines.append(line.rstrip("\n"))
            if sent_at is None and line.startswith("LOADTEST progress"):
                done = int(line.split("done=")[1].split()[0])
                if done >= threshold:
                    proc.send_signal(signal.SIGTERM)
                    sent_at = done
        proc.wait(timeout=CHILD_TIMEOUT_S)
    except Exception:
        proc.kill()
        raise
    wall = time.monotonic() - t0
    stdout = "\n".join(lines)
    summary = parse_summary(stdout)
    errors = []
    if sent_at is None:
        errors.append(f"never reached {threshold} completions to signal")
    if proc.returncode != 0:
        errors.append(f"loadtest rc={proc.returncode} (drain must exit 0)")
    if summary is None:
        errors.append("no LOADTEST summary line")
    else:
        served = summary["served"]
        if served.get("lost") != 0:
            errors.append(f"lost={served.get('lost')} admitted requests")
        if not served.get("drained"):
            errors.append("server did not drain")
        if served.get("signal") != "SIGTERM":
            errors.append(f"signal={served.get('signal')}")
        accounted = (served.get("ok", 0) + served.get("failed", 0)
                     + served.get("rejected", 0))
        if accounted != served.get("submitted"):
            errors.append(f"accounting leak: ok+failed+rejected="
                          f"{accounted} != submitted="
                          f"{served.get('submitted')}")
        if served.get("rejected", 0) == 0:
            errors.append("no rejects — signal landed after the trace "
                          "finished (raise --requests)")
    return {
        "drill": "sigterm", "ok": not errors, "wall_s": round(wall, 1),
        "signal_after": sent_at, "summary": summary,
        "error": "; ".join(errors) or None,
        "tail": "\n".join(stdout.splitlines()[-5:]),
    }


def drill_compare(args, poison_run_dir):
    """Served-vs-sequential gate + v6/introspection event checks."""
    seq = os.path.join(poison_run_dir, "seq")
    serve = os.path.join(poison_run_dir, "serve")
    report_path = os.path.join(poison_run_dir, "compare.json")
    t0 = time.monotonic()
    # The binding gate is throughput: sustained batched serving must beat
    # (or match) the sequential baseline, so a 0.0 drop is tolerated. The
    # other knobs are waived — the serve run deliberately AOT-compiles
    # more programs (one per bucket x batch x warm flavor) and its
    # per-request device time rides a bigger batch, so compile_total_s
    # and the phase percentiles are not like-for-like against a
    # one-request-at-a-time loop.
    proc = subprocess.run(
        [sys.executable, "-m", "raft_stereo_tpu.cli", "compare", seq, serve,
         "--max-throughput-drop", "0.0",
         "--max-phase-increase", "1e9",
         "--max-compile-growth", "1e9",
         "--max-memory-growth", "1e9",
         "--json", report_path], cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, timeout=600.0)
    wall = time.monotonic() - t0
    errors = []
    report = {}
    try:
        with open(report_path) as f:
            report = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        errors.append(f"no readable compare report: {e}")
    if proc.returncode != 0:
        errors.append("compare gate failed: "
                      + ", ".join(report.get("regressions", ["rc!=0"])))
    # the serve run must carry the v6 SLO rollups and per-executable
    # introspection the subsystem promises
    from raft_stereo_tpu.obs import read_events
    events = read_events(os.path.join(serve, "events.jsonl"))
    kinds = {}
    for e in events:
        kinds[e.get("event")] = kinds.get(e.get("event"), 0) + 1
    slo = [e for e in events if e.get("event") == "slo"]
    if not slo:
        errors.append("no slo events on the serve run")
    elif not all(k in slo[-1] for k in
                 ("p50_ms", "p99_ms", "pairs_per_sec", "in_flight")):
        errors.append(f"slo rollup incomplete: {slo[-1]}")
    if kinds.get("request", 0) == 0:
        errors.append("no request events on the serve run")
    if kinds.get("xla_memory", 0) == 0:
        errors.append("no xla_memory introspection from the executable "
                      "cache")
    from raft_stereo_tpu.obs.validate import check_path
    schema_errors = check_path(os.path.join(serve, "events.jsonl"))
    if schema_errors:
        errors.append(f"schema lint: {schema_errors[:3]}")
    metrics = {
        name: {"baseline": m["baseline"], "candidate": m["candidate"]}
        for name, m in report.get("metrics", {}).items()}
    return {
        "drill": "compare", "ok": not errors, "wall_s": round(wall, 1),
        "metrics": metrics, "event_counts": kinds,
        "slo_last": slo[-1] if slo else None,
        "error": "; ".join(errors) or None,
    }


def drill_witness(args, work):
    """Dynamic lock-order witness leg (graftlint engine 4's runtime
    half): run the load under ``RAFT_LOCK_WITNESS`` so every package
    lock acquisition is recorded, then hold the witnessed order graph
    against the static thread topology. A witnessed edge that
    contradicts the static acquisition order — or that closes a cycle
    the static pass missed — fails the drill; the evidence banks into
    drills.jsonl like every other gate."""
    run_dir = os.path.join(work, "witness")
    dump = os.path.join(run_dir, "lock_witness.json")
    os.makedirs(run_dir, exist_ok=True)
    env = dict(os.environ, RAFT_LOCK_WITNESS=dump)
    t0 = time.monotonic()
    proc = subprocess.run(loadtest_cmd(args, run_dir), cwd=REPO, env=env,
                          stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT, text=True,
                          timeout=CHILD_TIMEOUT_S)
    errors = []
    if proc.returncode != 0:
        errors.append(f"witnessed loadtest rc={proc.returncode}: "
                      f"{proc.stdout[-300:]}")
    findings, locks, edges = [], 0, 0
    if os.path.exists(dump):
        from raft_stereo_tpu.analysis.concurrency_rules import (
            build_topology, check_witness, load_witness)
        wit = load_witness(dump)
        locks, edges = len(wit.get("locks", {})), len(wit.get("edges", []))
        topo = build_topology(os.path.join(REPO, "raft_stereo_tpu"))
        findings = check_witness(topo, wit)
        errors.extend(f"{f.rule} {f.location}: {f.message}"
                      for f in findings if f.severity == "error")
    else:
        errors.append("loadtest left no witness dump (the "
                      "RAFT_LOCK_WITNESS hook did not engage)")
    wall = time.monotonic() - t0
    return {
        "drill": "witness", "ok": not errors, "wall_s": round(wall, 1),
        "witness_locks": locks, "witnessed_edges": edges,
        "checks": [f"{f.severity}:{f.location}" for f in findings],
        "error": "; ".join(errors) or None,
    }


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Serving load drill (see module doc)")
    p.add_argument("--drills", nargs="+",
                   default=["poison", "sigterm", "compare", "witness"],
                   choices=["poison", "sigterm", "compare", "witness"])
    p.add_argument("--shapes", nargs="+",
                   default=["48x96", "64x128", "96x64"])
    p.add_argument("--clients", type=int, default=8)
    p.add_argument("--requests", type=int, default=4)
    p.add_argument("--max-batch", dest="max_batch", type=int, default=2)
    p.add_argument("--iters", type=int, default=2)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--keep-work", action="store_true")
    p.add_argument("--small", action="store_true",
                   help="waive the >=3-bucket / >=8-client minima (the "
                        "rehearsal's budgeted smoke variant; the banked "
                        "acceptance record must come from a full run)")
    args = p.parse_args(argv)

    if not args.small:
        if len(set(args.shapes)) < 3:
            p.error("the drill needs >= 3 distinct shape buckets")
        if args.clients < 8:
            p.error("the drill needs >= 8 concurrent client streams")

    os.makedirs(OUT, exist_ok=True)
    work = os.path.join(OUT, "work")
    shutil.rmtree(work, ignore_errors=True)
    os.makedirs(work, exist_ok=True)

    records, poison_run_dir = [], os.path.join(work, "poison")
    if "poison" in args.drills:
        rec, poison_run_dir = drill_poison(args, work)
        records.append(rec)
    if "sigterm" in args.drills:
        records.append(drill_sigterm(args, work))
    if "compare" in args.drills:
        if os.path.exists(os.path.join(poison_run_dir, "serve",
                                       "events.jsonl")):
            records.append(drill_compare(args, poison_run_dir))
        else:
            records.append({"drill": "compare", "ok": False,
                            "error": "poison phase left no serve run dir"})
    if "witness" in args.drills:
        records.append(drill_witness(args, work))

    ok = True
    for rec in records:
        rec["platform"] = os.environ.get("JAX_PLATFORMS", "default")
        rec["small"] = args.small
        append_json_log(LOG, rec, stream=sys.stderr)
        ok = ok and rec["ok"]
    if not args.keep_work and ok:
        # keep the banked drills.jsonl, drop the bulky run dirs
        shutil.rmtree(work, ignore_errors=True)
    print(("load drill ok: " if ok else "LOAD DRILL FAILED: ")
          + ", ".join(f"{r['drill']}={'ok' if r['ok'] else 'FAIL'}"
                      for r in records))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
