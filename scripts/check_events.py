#!/usr/bin/env python
"""Schema lint for events.jsonl artifacts — thin CLI over obs/validate.py.

Validates every record of one or more ``events.jsonl`` files (or run
directories containing one) against the supported schema versions and each
event type's required fields (obs/events.py), and exits non-zero on any
violation. The validation logic lives in
``raft_stereo_tpu.obs.validate`` — shared with scripts/rehearse_round.py's
``events`` leg and the graftlint test fixtures — so the CLI and the
library can never drift apart.

Back-compat: v1 -> ... -> v7 were additive (obs/events.py
``SUPPORTED_SCHEMA_VERSIONS``), so pre-existing artifacts lint clean; the
v4 addition is the ``lint`` static-analysis report event
(raft_stereo_tpu/analysis), the v5 additions are the fault-tolerance
events — preempt/resume/ckpt_integrity/anomaly
(raft_stereo_tpu/training/resilience.py), v6 the serving events, and v7
the tracing events — ``span`` (obs/trace.py) and ``flightrec`` (the
telemetry flight recorder). For v7 files the lint additionally checks
span referential integrity (obs/validate.py ``check_span_integrity``):
unique span_ids, parent_ids resolving within the file, non-empty
trace_ids (``remote_parent: true`` spans are exempt from the in-file
parent resolution — their parent lives in another host's log).

v10 adds the fleet-observatory events — ``heartbeat`` liveness beats and
the ``clock_anchor`` monotonic-to-wall mapping (obs/fleet.py) — plus host
identity (``host_id``/``pid``/``coords``) riding every record as optional
extras. For files carrying them the lint additionally checks fleet
referential integrity (obs/validate.py ``check_fleet_integrity``):
non-empty host_ids consistent within a run segment, at most one
clock_anchor per host per segment, heartbeat ``seq`` strictly increasing
per (host, role) with a non-rewinding clock. All of v8 -> v10 stayed
additive, so banked v1 -> v9 artifacts still lint clean.

``iter_policy.json`` artifacts (``cli converge --emit-policy``) are also
accepted: any ``*.json`` path whose top-level ``kind`` is ``iter_policy``
is held against the policy schema instead (obs/validate.py
``check_iter_policy``): bucket coverage, tau > 0, budget within the
recorded valid_iters, provenance fields present.

Usage: python scripts/check_events.py <events.jsonl | run_dir | iter_policy.json> [...]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from raft_stereo_tpu.obs.validate import check_path, main as _main  # noqa: E402

# Back-compat alias: scripts/rehearse_round.py (and older callers) import
# ``check_events.check``.
check = check_path


def main(argv=None) -> int:
    return _main(argv, doc=__doc__)


if __name__ == "__main__":
    sys.exit(main())
