#!/usr/bin/env python
"""Schema lint for events.jsonl artifacts (obs/events.py).

Validates every record of one or more ``events.jsonl`` files (or run
directories containing one) against the supported schema versions and each
event type's required fields — the streaming-eval ``pipeline`` gauge
(``in_flight`` required), the v2 compiled-artifact introspection records
``xla_memory`` (``source``/``peak_bytes``) and ``xla_cost``
(``source``/``flops``), and the v3 jaxpr conv-placement profile
``op_counts`` (``source``/``conv_total``, the batched-weight-grad scan's
structural evidence) — newer events additionally may not claim a schema
older than their introduction — and exits non-zero on any violation; wired
into the tier-1 run via tests/test_telemetry.py, tests/test_eval_stream.py,
tests/test_obs_xla.py and tests/test_scan_grad.py so schema drift fails
tests instead of silently corrupting downstream summarizers.

Back-compat: v1 -> v2 -> v3 were additive (obs/events.py
``SUPPORTED_SCHEMA_VERSIONS``), so pre-existing artifacts lint clean.

Usage: python scripts/check_events.py <events.jsonl | run_dir> [...]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from raft_stereo_tpu.obs.events import read_events, validate_events  # noqa: E402


def check(path: str) -> list:
    """Return ["<path>: <violation>", ...] for one file or run dir."""
    if os.path.isdir(path):
        path = os.path.join(path, "events.jsonl")
    if not os.path.exists(path):
        return [f"{path}: missing"]
    try:
        records = read_events(path)
    except ValueError as e:
        return [str(e)]
    if not records:
        return [f"{path}: empty event log"]
    return [f"{path}: {e}" for e in validate_events(records)]


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print(__doc__.strip(), file=sys.stderr)
        return 2
    errors = []
    for path in argv:
        errors.extend(check(path))
    for e in errors:
        print(e, file=sys.stderr)
    if not errors:
        print(f"ok: {len(argv)} artifact(s) conform to the event schema")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
