#!/usr/bin/env python
"""Fault-injection drill: prove the exact-resume claim with a kill, not a
docstring.

The fault-tolerance layer (training/resilience.py) claims that a killed
training run, resumed with ``--restore_ckpt auto``, is INDISTINGUISHABLE
from one that never stopped — bitwise-equal final params, same per-step
loss trajectory on the event stream. This drill makes that claim a gate.
Every leg drives the REAL CLI surface (``python -m raft_stereo_tpu.cli
train``) as subprocesses over a tiny synthetic SceneFlow tree, on CPU,
in-sandbox:

* **sigterm** — run, SIGTERM at a randomized step (the preemption handler
  saves a ``reason="preempt"`` checkpoint and exits 0), resume with
  ``--restore_ckpt auto``, assert final params bitwise-equal to the
  uninterrupted oracle and the assembled per-step loss stream identical.
* **sigkill** — same, but SIGKILL (no chance to save): resume rolls back
  to the last periodic checkpoint (``--checkpoint_frequency``), replays
  the lost steps from the Philox-exact stream, and must still end
  bitwise-equal to the oracle.
* **corrupt** — SIGKILL a run, then truncate a file inside its newest
  checkpoint: auto-resume must record ``ckpt_integrity ok=false`` for it,
  fall back to the previous valid checkpoint, and still match the oracle.
* **nan** — inject an all-NaN batch at a known step
  (``RAFT_FAULT_NAN_STEP``): the device-side anomaly guard must skip that
  optimizer update (``skipped_updates>0`` on the events), the run must
  complete, and the final params must be finite.

Each leg appends a JSON record to ``runs/fault_drill/drills.jsonl``
through the shared obs/ sink; exit status is non-zero if any leg failed,
so scripts/rehearse_round.py's ``fault`` leg can gate a round on it.

Run: python scripts/fault_drill.py [--drills sigterm sigkill corrupt nan]
     [--steps 6] [--ckpt-every 2] [--seed N] [--keep-work]
"""

import argparse
import glob
import json
import os
import random
import shutil
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from raft_stereo_tpu.obs.events import append_json_log  # noqa: E402

OUT = os.path.join(REPO, "runs", "fault_drill")
LOG = os.path.join(OUT, "drills.jsonl")

H, W = 48, 64  # synthetic frame size (the trainer-test shape)
CHILD_TIMEOUT_S = 900.0


# --- synthetic data ----------------------------------------------------------

def make_sceneflow_tree(root, n=4):
    """Tiny FlyingThings-layout tree (the tests' fixture, kept in sync by
    tests/test_resilience.py::test_drill_tree_matches_loader)."""
    import numpy as np
    from PIL import Image

    from raft_stereo_tpu.data import frame_utils

    rng = np.random.default_rng(0)
    for dstype in ("frames_cleanpass", "frames_finalpass"):
        for side in ("left", "right"):
            os.makedirs(os.path.join(root, "FlyingThings3D", dstype, "TRAIN",
                                     "A", "0000", side), exist_ok=True)
        os.makedirs(os.path.join(root, "FlyingThings3D", "disparity",
                                 "TRAIN", "A", "0000", "left"), exist_ok=True)
        for i in range(n):
            for side in ("left", "right"):
                img = rng.integers(0, 255, (H, W, 3), dtype=np.uint8)
                Image.fromarray(img).save(os.path.join(
                    root, "FlyingThings3D", dstype, "TRAIN", "A", "0000",
                    side, f"{i:04d}.png"))
            frame_utils.write_pfm(
                os.path.join(root, "FlyingThings3D", "disparity", "TRAIN",
                             "A", "0000", "left", f"{i:04d}.pfm"),
                rng.uniform(0.5, 8, (H, W)).astype(np.float32))


# --- child runs --------------------------------------------------------------

def child_cmd(name, work, steps, ckpt_every, restore=None):
    # ``name`` is "<base>@<leg>": the checkpoint run name (shared between a
    # drill's kill and resume legs, so auto-resume finds the kill leg's
    # checkpoints) vs the per-leg run_dir root (separate event streams)
    base, leg = name.split("@")[0], name.split("@")[-1]
    cmd = [sys.executable, "-m", "raft_stereo_tpu.cli", "train",
           "--name", base,
           "--data_root", os.path.join(work, "data"),
           "--ckpt_dir", os.path.join(work, "ckpts", base),
           "--run_dir", os.path.join(work, "runs", leg),
           "--batch_size", "2", "--num_steps", str(steps),
           "--image_size", str(H), str(W),
           "--train_iters", "1", "--valid_iters", "1",
           "--hidden_dims", "32", "32", "32",
           "--validation_frequency", "1000000",
           "--checkpoint_frequency", str(ckpt_every),
           "--ckpt_keep_last", "0",
           "--num_workers", "2", "--lr", "1e-4",
           "--data_parallel", "1", "--stall_deadline_s", "0"]
    if restore:
        cmd += ["--restore_ckpt", restore]
    return cmd


def run_child(name, work, steps, ckpt_every, restore=None, env_extra=None,
              kill=None, kill_step=None, require_checkpoints=0):
    """Run one training child; optionally signal it once the event stream
    shows ``kill_step``. Returns (returncode, run_dir, log_path)."""
    # the ckpt_dir is shared between a drill's legs (keyed by the part
    # before '@'), the run_dir is per leg (the part after '@')
    base = name.split("@")[0]
    leg = name.split("@")[-1]
    run_dir = os.path.join(work, "runs", leg, base)
    log_path = os.path.join(work, f"{leg}.log")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1")
    # drill children run a 1-device mesh; drop any test-harness forcing of
    # a virtual multi-device platform (pure speed, not correctness)
    env.pop("XLA_FLAGS", None)
    env.update(env_extra or {})
    cmd = child_cmd(name, work, steps, ckpt_every, restore=restore)
    with open(log_path, "w") as log:
        proc = subprocess.Popen(cmd, cwd=REPO, stdout=log,
                                stderr=subprocess.STDOUT, env=env)
        try:
            if kill is not None:
                # the step event for step s lands while s+1 runs (lagged
                # metrics fetch, trainer.py) — waiting for s-1 signals the
                # child while it is executing ~step s, with the remaining
                # steps as margin against the signal landing after the run
                # already completed
                seen = wait_for_step(
                    os.path.join(run_dir, "events.jsonl"),
                    max(kill_step - 1, 1), proc,
                    require_checkpoints=require_checkpoints)
                if seen is None:
                    proc.kill()
                    proc.wait(timeout=30)
                    raise RuntimeError(
                        f"{leg}: child exited (rc={proc.returncode}) before "
                        f"reaching kill step {kill_step}")
                proc.send_signal(kill)
            rc = proc.wait(timeout=CHILD_TIMEOUT_S)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=30)
            raise RuntimeError(f"{leg}: child timed out after "
                               f"{CHILD_TIMEOUT_S:.0f}s (see {log_path})")
    return rc, run_dir, log_path


def wait_for_step(events_path, step, proc, timeout_s=CHILD_TIMEOUT_S,
                  require_checkpoints=0):
    """Poll a (possibly mid-write) events.jsonl until a step event with
    ``step >= step`` appears; None when the child exits first.

    ``require_checkpoints`` additionally waits for that many ``checkpoint``
    events — the SIGKILL/corrupt drills must not fire before the
    checkpoints they roll back to are durable on disk (the checkpoint
    event is emitted only after the atomic rename published it)."""

    def ready(events):
        stepped = any(e.get("event") == "step" and e.get("step", 0) >= step
                      for e in events)
        ckpts = sum(e.get("event") == "checkpoint" for e in events)
        return stepped and ckpts >= require_checkpoints

    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        if ready(read_events_lenient(events_path)):
            return step
        if proc.poll() is not None:
            # one final read: the event may have landed as it exited
            return step if ready(read_events_lenient(events_path)) else None
        time.sleep(0.2)
    raise RuntimeError(f"no step >= {step} within {timeout_s:.0f}s "
                       f"in {events_path}")


def read_events_lenient(path):
    """Parse an events.jsonl, skipping unparseable lines — a SIGKILL can
    truncate the final record mid-write, which is exactly the artifact
    state this drill exists to exercise."""
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    except OSError:
        pass
    return out


# --- assertions --------------------------------------------------------------

def load_ckpt_tree(path):
    """Raw orbax restore of a checkpoint dir (manifest layout aware)."""
    import orbax.checkpoint as ocp

    from raft_stereo_tpu.training.resilience import checkpoint_state_dir
    return ocp.PyTreeCheckpointer().restore(checkpoint_state_dir(path))


def params_bitwise_equal(path_a, path_b):
    import jax
    import numpy as np

    ta, tb = load_ckpt_tree(path_a), load_ckpt_tree(path_b)
    la, sa = jax.tree.flatten(ta["params"])
    lb, sb = jax.tree.flatten(tb["params"])
    if sa != sb:
        return False, "param tree structures differ"
    for i, (a, b) in enumerate(zip(la, lb)):
        if not np.array_equal(np.asarray(a), np.asarray(b)):
            return False, f"param leaf {i} differs"
    return True, None


def params_all_finite(path):
    import jax
    import numpy as np

    tree = load_ckpt_tree(path)
    return all(np.all(np.isfinite(np.asarray(l)))
               for l in jax.tree.leaves(tree["params"]))


def step_loss_map(events):
    return {rec["step"]: rec["loss"] for rec in events
            if rec.get("event") == "step" and "loss" in rec}


def assert_stream_matches_oracle(oracle_events, run_events_list, steps):
    """The assembled per-step loss stream of the interrupted run(s) must be
    IDENTICAL to the oracle's — later runs override the replayed overlap
    (which must itself match, or the final params could not be bitwise
    equal)."""
    oracle = step_loss_map(oracle_events)
    assembled = {}
    for events in run_events_list:
        assembled.update(step_loss_map(events))
    missing = [s for s in range(1, steps + 1) if s not in assembled]
    if missing:
        return False, f"steps missing from assembled event stream: {missing}"
    diff = [s for s in range(1, steps + 1)
            if assembled[s] != oracle.get(s)]
    if diff:
        return False, (f"loss differs from oracle at steps {diff}: "
                       f"{[(assembled[s], oracle.get(s)) for s in diff[:3]]}")
    return True, None


def newest_step_ckpt(ckpt_dir, name):
    import re
    pat = re.compile(rf"^(\d+)_{re.escape(name)}$")
    best, best_step = None, -1
    for entry in os.listdir(ckpt_dir):
        m = pat.match(entry)
        if m and int(m.group(1)) > best_step:
            best, best_step = os.path.join(ckpt_dir, entry), int(m.group(1))
    return best, best_step


# --- drills ------------------------------------------------------------------

def drill_kill(work, oracle, steps, ckpt_every, kill_step, sig, leg):
    """Shared body of the sigterm/sigkill drills."""
    name = f"{leg}@{leg}-run1"
    rc1, run_dir1, log1 = run_child(
        name, work, steps, ckpt_every, kill=sig, kill_step=kill_step,
        require_checkpoints=1 if sig == signal.SIGKILL else 0)
    events1 = read_events_lenient(os.path.join(run_dir1, "events.jsonl"))
    detail = {"kill_step": kill_step, "signal": sig.name, "rc1": rc1}
    if sig == signal.SIGTERM:
        if rc1 != 0:
            return False, dict(detail, error=f"SIGTERM child rc={rc1} "
                               f"(expected graceful 0); see {log1}")
        if not any(e.get("event") == "preempt" for e in events1):
            return False, dict(detail, error="no preempt event on record")
        if not any(e.get("event") == "checkpoint"
                   and e.get("reason") == "preempt" for e in events1):
            return False, dict(detail,
                               error="no reason=preempt checkpoint event")
    else:
        if rc1 == 0:
            return False, dict(detail, error="SIGKILL child exited 0?!")

    rc2, run_dir2, log2 = run_child(f"{leg}@{leg}-run2", work, steps,
                                    ckpt_every, restore="auto")
    detail["rc2"] = rc2
    if rc2 != 0:
        return False, dict(detail, error=f"resume rc={rc2}; see {log2}")
    events2 = read_events_lenient(os.path.join(run_dir2, "events.jsonl"))
    resume = [e for e in events2 if e.get("event") == "resume"]
    if not resume:
        return False, dict(detail, error="resumed run has no resume event")
    detail["resumed_step"] = resume[0]["step"]
    detail["resumed_from"] = resume[0]["path"]

    ok, why = params_bitwise_equal(
        os.path.join(work, "ckpts", "oracle", "oracle"),
        os.path.join(work, "ckpts", leg, leg))
    if not ok:
        return False, dict(detail, error=f"final params: {why}")
    oracle_events = read_events_lenient(
        os.path.join(work, "runs", "oracle", "oracle", "events.jsonl"))
    ok, why = assert_stream_matches_oracle(oracle_events,
                                           [events1, events2], steps)
    if not ok:
        return False, dict(detail, error=why)
    skipped = sum(e.get("skipped_updates", 0) for e in events1 + events2
                  if e.get("event") == "step")
    if skipped:
        return False, dict(detail, error=f"unexpected skipped updates "
                                         f"({skipped}) in a clean drill")
    return True, detail


def drill_corrupt(work, oracle, steps, ckpt_every):
    """SIGKILL a run, truncate its newest checkpoint, resume: auto must
    skip the corrupt one (ckpt_integrity ok=false), roll back to the
    previous valid checkpoint and still match the oracle bitwise."""
    # kill late enough that at least two periodic checkpoints exist (and
    # wait for both checkpoint events: durable-on-disk, not just stepped)
    kill_step = 2 * ckpt_every + 1
    rc1, run_dir1, _log1 = run_child("corrupt@corrupt-run1", work, steps,
                                     ckpt_every, kill=signal.SIGKILL,
                                     kill_step=kill_step,
                                     require_checkpoints=2)
    ckpt_dir = os.path.join(work, "ckpts", "corrupt")
    newest, newest_step = newest_step_ckpt(ckpt_dir, "corrupt")
    detail = {"rc1": rc1, "corrupted": newest, "corrupted_step": newest_step}
    if newest is None or newest_step < 2 * ckpt_every:
        return False, dict(detail, error="fewer than two periodic "
                                         "checkpoints before the kill")
    # truncate the largest file in the newest checkpoint's state tree
    files = [p for p in glob.glob(os.path.join(newest, "state", "**", "*"),
                                  recursive=True) if os.path.isfile(p)]
    victim = max(files, key=os.path.getsize)
    with open(victim, "r+b") as f:
        f.truncate(max(os.path.getsize(victim) // 2, 1))
    # if the kill raced past the final save, drop the stepless final so the
    # corrupted step checkpoint is genuinely the newest candidate
    final_ckpt = os.path.join(ckpt_dir, "corrupt")
    if os.path.isdir(final_ckpt):
        shutil.rmtree(final_ckpt)

    rc2, run_dir2, log2 = run_child("corrupt@corrupt-run2", work, steps,
                                    ckpt_every, restore="auto")
    detail["rc2"] = rc2
    if rc2 != 0:
        return False, dict(detail, error=f"resume rc={rc2}; see {log2}")
    events2 = read_events_lenient(os.path.join(run_dir2, "events.jsonl"))
    bad = [e for e in events2 if e.get("event") == "ckpt_integrity"
           and not e.get("ok")]
    if not any(e.get("path") == newest for e in bad):
        return False, dict(detail, error="no ckpt_integrity ok=false for "
                                         "the corrupted checkpoint")
    resume = [e for e in events2 if e.get("event") == "resume"]
    if not resume or resume[0]["step"] != newest_step - ckpt_every:
        return False, dict(detail, error=f"expected rollback to step "
                           f"{newest_step - ckpt_every}, resume events: "
                           f"{resume}")
    detail["rolled_back_to"] = resume[0]["step"]
    ok, why = params_bitwise_equal(
        os.path.join(work, "ckpts", "oracle", "oracle"),
        os.path.join(work, "ckpts", "corrupt", "corrupt"))
    if not ok:
        return False, dict(detail, error=f"final params: {why}")
    return True, detail


def drill_nan(work, steps, ckpt_every, nan_step=3):
    """Inject an all-NaN batch: the device guard must skip that update
    (skipped_updates>0), the run must finish, params must stay finite."""
    rc, run_dir, log = run_child(
        "nan@nan-run", work, steps, ckpt_every,
        env_extra={"RAFT_FAULT_NAN_STEP": str(nan_step)})
    detail = {"rc": rc, "nan_step": nan_step}
    if rc != 0:
        return False, dict(detail, error=f"NaN run rc={rc} (the guard "
                           f"should have survived it); see {log}")
    events = read_events_lenient(os.path.join(run_dir, "events.jsonl"))
    skipped = sum(e.get("skipped_updates", 0) for e in events
                  if e.get("event") == "step")
    detail["skipped_updates"] = skipped
    if skipped <= 0:
        return False, dict(detail, error="no skipped updates on record")
    anomalies = [e for e in events if e.get("event") == "anomaly"
                 and e.get("kind") == "nonfinite_grad"]
    if not any(a.get("step") == nan_step for a in anomalies):
        return False, dict(detail, error=f"no nonfinite_grad anomaly at "
                           f"step {nan_step}: {anomalies}")
    if not params_all_finite(os.path.join(work, "ckpts", "nan", "nan")):
        return False, dict(detail, error="final params are not finite")
    return True, detail


# --- main --------------------------------------------------------------------

def main(argv=None):
    p = argparse.ArgumentParser(
        description="Kill/corrupt/NaN fault drills over the real train CLI "
                    "(see module doc)")
    p.add_argument("--drills", nargs="+",
                   default=["sigterm", "sigkill", "corrupt", "nan"],
                   choices=["sigterm", "sigkill", "corrupt", "nan"])
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--ckpt-every", type=int, default=2)
    p.add_argument("--seed", type=int, default=None,
                   help="kill-step randomization seed (default: random, "
                        "recorded in the drill log)")
    p.add_argument("--keep-work", action="store_true",
                   help="keep the work dir (child run artifacts) on success")
    args = p.parse_args(argv)

    seed = args.seed if args.seed is not None \
        else random.SystemRandom().randrange(1 << 20)
    rng = random.Random(seed)
    os.makedirs(OUT, exist_ok=True)
    work = os.path.join(OUT, "work")
    if os.path.exists(work):
        shutil.rmtree(work)
    os.makedirs(work)
    make_sceneflow_tree(os.path.join(work, "data"))

    needs_oracle = {"sigterm", "sigkill", "corrupt"} & set(args.drills)
    t0 = time.monotonic()
    records = []
    try:
        if needs_oracle:
            rc, _run_dir, log = run_child("oracle@oracle", work, args.steps,
                                          args.ckpt_every)
            if rc != 0:
                raise RuntimeError(f"oracle run rc={rc}; see {log}")
        for drill in args.drills:
            t1 = time.monotonic()
            try:
                if drill in ("sigterm", "sigkill"):
                    # randomized, but never past the last step (there must
                    # be work left to lose); SIGKILL additionally never
                    # before the first periodic checkpoint can exist —
                    # an uncheckpointed SIGKILL legitimately restarts from
                    # scratch, which proves nothing about rollback
                    sig = (signal.SIGTERM if drill == "sigterm"
                           else signal.SIGKILL)
                    lo = 2 if sig == signal.SIGTERM else args.ckpt_every + 1
                    kill_step = rng.randint(lo, max(args.steps - 3, lo))
                    ok, detail = drill_kill(work, "oracle", args.steps,
                                            args.ckpt_every, kill_step,
                                            sig, drill)
                elif drill == "corrupt":
                    ok, detail = drill_corrupt(work, "oracle", args.steps,
                                               args.ckpt_every)
                else:
                    ok, detail = drill_nan(work, args.steps,
                                           args.ckpt_every)
            except Exception as e:
                ok, detail = False, {"error": f"{type(e).__name__}: {e}"}
            records.append({"drill": drill, "ok": ok, "seed": seed,
                            "steps": args.steps,
                            "ckpt_every": args.ckpt_every,
                            "wall_s": round(time.monotonic() - t1, 1),
                            "detail": detail})
            append_json_log(LOG, records[-1], stream=sys.stderr)
    finally:
        if all(r["ok"] for r in records) and records \
                and not args.keep_work:
            shutil.rmtree(work, ignore_errors=True)

    ok = bool(records) and all(r["ok"] for r in records)
    summary = {"drill": "summary", "ok": ok, "seed": seed,
               "wall_s": round(time.monotonic() - t0, 1),
               "legs": {r["drill"]: r["ok"] for r in records}}
    append_json_log(LOG, summary, stream=sys.stderr)
    print(("fault drill ok: " if ok else "FAULT DRILL FAILED: ")
          + ", ".join(f"{r['drill']}={'ok' if r['ok'] else 'FAIL'}"
                      for r in records))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
