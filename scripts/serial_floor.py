#!/usr/bin/env python
"""Decompose the refinement scan's serial floor per GRU iteration.

PERF.md's ceiling argument rests on a ~450 ms batch-independent serial floor
(the `lax.scan` over GRU refinement iterations — RAFT's iterative loop,
arXiv:2003.12039 — forward and backward); VERDICT r5 #6 notes it has never
been decomposed per-iteration. This script splits it with the chunked/
unrolled timing mode (utils/profiling.py):

* time the SAME graph at several iteration counts — the fit's slope is the
  cost of one more GRU iteration, the intercept the per-call fixed work
  (encoders + volume build + upsample/loss tail + host dispatch);
* time the sweep again fully UNROLLED (``scan_unroll = iters``: XLA fuses
  across iteration boundaries, no loop carry) — the rolled-minus-unrolled
  slope isolates the loop/layout overhead each iteration pays for living
  inside the ``while`` from its actual GRU/lookup compute;
* record the per-iteration mean |delta disparity| (the model's in-graph
  ``iter_metrics`` aux output) — how much each iteration still MOVES the
  field, i.e. whether the serial floor is buying convergence.

Every configuration is AOT-compiled (``lower().compile()``) and its
xla_memory/xla_cost introspection (obs/xla.py) lands on the run's
events.jsonl next to the timing JSON.

Run: python scripts/serial_floor.py --run_dir runs/serial_floor \\
         [--mode train|infer] [--iters 2 4 8 12] [--unroll-iters 2 4 8]
     (defaults are CPU-sized; on the TPU host use --batch 8 --h 320 --w 720
      --iters 2 6 12 22 for the flagship recipe's floor)
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from raft_stereo_tpu.config import RAFTStereoConfig, TrainConfig  # noqa: E402
from raft_stereo_tpu.models import init_model  # noqa: E402
from raft_stereo_tpu.obs import Telemetry  # noqa: E402
from raft_stereo_tpu.obs.xla import introspect_compiled  # noqa: E402
from raft_stereo_tpu.utils.profiling import (  # noqa: E402
    decompose_serial_floor, time_compiled)


def build_fn(args, model, variables, state_and_tx, iters, mode):
    """A jitted callable of no per-call setup: (args) -> outputs, plus the
    (state/batch) operands it closes over, ready for lower/compile."""
    b, h, w = args.batch, args.h, args.w
    rng = np.random.default_rng(0)
    if mode == "train":
        from raft_stereo_tpu.training.state import make_train_step
        state, tx = state_and_tx
        batch = {
            "image1": jnp.asarray(rng.uniform(0, 255, (b, h, w, 3)),
                                  jnp.float32),
            "image2": jnp.asarray(rng.uniform(0, 255, (b, h, w, 3)),
                                  jnp.float32),
            "flow": jnp.asarray(rng.uniform(-16, 0, (b, h, w, 1)),
                                jnp.float32),
            "valid": jnp.ones((b, h, w), jnp.float32),
        }
        step = jax.jit(make_train_step(model, tx, iters, fused_loss=True))
        return step, (state, batch)
    im1 = jnp.asarray(rng.uniform(0, 255, (b, h, w, 3)), jnp.float32)
    im2 = jnp.asarray(rng.uniform(0, 255, (b, h, w, 3)), jnp.float32)
    fn = jax.jit(lambda a, c: model.apply(variables, a, c, iters=iters,
                                          test_mode=True))
    return fn, (im1, im2)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--mode", choices=["train", "infer"], default="infer",
                   help="decompose the training step's scans (fwd+bwd) or "
                        "the inference scan")
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--h", type=int, default=96)
    p.add_argument("--w", type=int, default=160)
    p.add_argument("--iters", type=int, nargs="+", default=[2, 4, 8, 12],
                   help="rolled-scan iteration counts to sweep")
    p.add_argument("--unroll-iters", type=int, nargs="+", default=None,
                   help="iteration counts for the fully-unrolled contrast "
                        "sweep (default: same as --iters; pass 0 to skip)")
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--mixed_precision", action="store_true")
    p.add_argument("--run_dir", default="runs/serial_floor")
    args = p.parse_args()

    platform = jax.devices()[0].platform
    tel = Telemetry(args.run_dir, stall_deadline_s=None)
    tel.run_start(config={**vars(args), "platform": platform})

    def setup(unroll):
        cfg = RAFTStereoConfig(mixed_precision=args.mixed_precision,
                               scan_unroll=unroll)
        model, variables = init_model(jax.random.PRNGKey(0), cfg,
                                      (1, args.h, args.w, 3))
        state_and_tx = None
        if args.mode == "train":
            from raft_stereo_tpu.training.optim import fetch_optimizer
            from raft_stereo_tpu.training.state import TrainState
            tx = fetch_optimizer(TrainConfig(
                batch_size=args.batch, image_size=(args.h, args.w)))
            state_and_tx = (TrainState.create(variables, tx), tx)
        return cfg, model, variables, state_and_tx

    def sweep(iters_list, unrolled):
        times = {}
        cfg_cache = {}
        for it in iters_list:
            unroll = it if unrolled else 1
            if unroll not in cfg_cache:
                cfg_cache[unroll] = setup(unroll)
            cfg, model, variables, st = cfg_cache[unroll]
            fn, operands = build_fn(args, model, variables, st, it,
                                    args.mode)
            t0 = time.perf_counter()
            compiled = fn.lower(*operands).compile()
            compile_s = time.perf_counter() - t0
            tag = (f"serial_floor_{args.mode}_it{it}"
                   + ("_unrolled" if unrolled else ""))
            tel.emit("compile", duration_s=round(compile_s, 3), source=tag)
            introspect_compiled(compiled, tel, source=tag,
                                extra={"iters": it,
                                       "unrolled": bool(unrolled)})
            times[it] = time_compiled(compiled, operands,
                                      repeats=args.repeats)
            print(f"{tag}: {times[it] * 1e3:.1f} ms "
                  f"(compile {compile_s:.1f} s)", flush=True)
        return times

    rolled = sweep(args.iters, unrolled=False)
    unroll_iters = (args.iters if args.unroll_iters is None
                    else [i for i in args.unroll_iters if i > 0])
    unrolled = sweep(unroll_iters, unrolled=True) if unroll_iters else None

    decomp = decompose_serial_floor(rolled, unrolled)

    # convergence axis: what each iteration still moves the disparity field
    # (in-graph aux, iter_metrics) — inference scan only
    delta_norms = None
    if args.mode == "infer":
        cfg, model, variables, _ = setup(1)
        it = max(args.iters)
        rng = np.random.default_rng(0)
        im1 = jnp.asarray(rng.uniform(0, 255, (args.batch, args.h, args.w, 3)),
                          jnp.float32)
        im2 = jnp.asarray(rng.uniform(0, 255, (args.batch, args.h, args.w, 3)),
                          jnp.float32)
        _, _, norms = jax.jit(
            lambda a, c: model.apply(variables, a, c, iters=it,
                                     test_mode=True, iter_metrics=True)
        )(im1, im2)
        delta_norms = [round(float(x), 5) for x in np.asarray(norms)]

    summary = {
        "mode": args.mode, "platform": platform,
        "batch": args.batch, "image_size": [args.h, args.w],
        "decomposition": decomp,
        "delta_disparity_norms": delta_norms,
    }
    out_path = os.path.join(args.run_dir, "serial_floor.json")
    with open(out_path, "w") as f:
        json.dump(summary, f, indent=1)
    tel.emit("run_end", steps=len(rolled), ok=True)
    tel.close()

    ms = lambda s: f"{s * 1e3:.2f} ms"  # noqa: E731
    print(f"\nserial-floor decomposition ({args.mode}, {platform}, "
          f"b{args.batch} {args.h}x{args.w}):")
    print(f"  fixed per call:        {ms(decomp['fixed_s'])}")
    print(f"  per iteration (total): {ms(decomp['per_iter_s'])}")
    if "per_iter_compute_s" in decomp:
        print(f"  per iteration compute: {ms(decomp['per_iter_compute_s'])}")
        print(f"  per iteration loop/layout overhead: "
              f"{ms(decomp['per_iter_loop_overhead_s'])}")
    if delta_norms:
        print(f"  delta-disparity norms: {delta_norms}")
    print(f"artifact: {out_path} (+ events.jsonl)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
