#!/usr/bin/env python
"""Tracing rehearsal: prove `cli timeline` + `cli doctor` on real runs.

The span-tracing acceptance bar (r13) is not "the unit tests pass" — it
is that the artifacts a real run leaves behind support the workflow:

1. **train leg** — a tiny CPU training run (synthetic FlyingThings tree,
   the fault_drill fixture) with tracing on (the default). Its run dir
   must yield: `cli timeline` exit 0 with >= 90% of each step's wall
   time covered by named child spans, and `cli doctor` exit 0 with a
   non-UNKNOWN train verdict.
2. **serve leg** — a tiny `cli loadtest` (no baseline phase). The serve
   run dir must yield the same: timeline exit 0 with >= 90% request
   child coverage, doctor exit 0 with a non-UNKNOWN serve verdict.

Each leg appends a dated JSON record to ``runs/trace_drill/drills.jsonl``;
exit non-zero if any check failed. Driven by scripts/rehearse_round.py's
``trace`` leg.

Run: JAX_PLATFORMS=cpu python scripts/trace_drill.py [--keep-work]
"""

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

OUT = os.path.join(REPO, "runs", "trace_drill")
LOG = os.path.join(OUT, "drills.jsonl")

COVERAGE_MIN = 0.9
CHILD_TIMEOUT_S = 900.0


def _run(cmd, env_extra=None, timeout=CHILD_TIMEOUT_S):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1")
    # 1-device is plenty for the drill; drop any test-harness device forcing
    env.pop("XLA_FLAGS", None)
    env.update(env_extra or {})
    proc = subprocess.run(cmd, cwd=REPO, stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT, text=True,
                          timeout=timeout, env=env)
    return proc.returncode, proc.stdout or ""


def _coverage(run_dir):
    """Min child-coverage fraction over the run's root spans (None when
    the run produced no roots)."""
    from raft_stereo_tpu.obs.events import read_events
    from raft_stereo_tpu.obs.timeline import span_coverage
    records = read_events(os.path.join(run_dir, "events.jsonl"))
    spans = [r for r in records if r.get("event") == "span"]
    cov = span_coverage(spans)
    return cov.get("min") if cov.get("roots") else None


def _check_run(leg, run_dir, expect_phase):
    """timeline + doctor over one run dir; returns the drill record."""
    errors = []
    rc, out = _run([sys.executable, "-m", "raft_stereo_tpu.cli",
                    "timeline", run_dir])
    if rc != 0:
        errors.append(f"timeline rc={rc}: {out.splitlines()[-1:]}")
    cov = _coverage(run_dir)
    if cov is None:
        errors.append("no root spans in the event stream")
    elif cov < COVERAGE_MIN:
        errors.append(f"span child coverage {cov:.0%} < "
                      f"{COVERAGE_MIN:.0%}")
    rc, out = _run([sys.executable, "-m", "raft_stereo_tpu.cli",
                    "doctor", run_dir, "--json"])
    verdicts = {}
    if rc != 0:
        errors.append(f"doctor rc={rc}")
    else:
        try:
            report = json.loads(out[out.index("{"):])
            verdicts = {v["phase"]: v["verdict"]
                        for v in report["verdicts"]}
        except (ValueError, KeyError) as e:
            errors.append(f"unparseable doctor report: {e}")
    if verdicts and verdicts.get(expect_phase, "UNKNOWN") == "UNKNOWN":
        errors.append(f"doctor verdict for {expect_phase!r} is UNKNOWN: "
                      f"{verdicts}")
    return {"drill": leg, "ok": not errors, "run_dir": run_dir,
            "coverage_min": cov, "verdicts": verdicts,
            "error": "; ".join(errors) or None}


def drill_train(work):
    from fault_drill import make_sceneflow_tree
    make_sceneflow_tree(os.path.join(work, "data"))
    rc, out = _run([
        sys.executable, "-m", "raft_stereo_tpu.cli", "train",
        "--name", "trace", "--data_root", os.path.join(work, "data"),
        "--ckpt_dir", os.path.join(work, "ckpts"),
        "--run_dir", os.path.join(work, "runs"),
        "--batch_size", "2", "--num_steps", "3",
        "--image_size", "48", "64",
        "--train_iters", "1", "--valid_iters", "1",
        "--hidden_dims", "32", "32", "32",
        "--validation_frequency", "1000000",
        "--num_workers", "2", "--lr", "1e-4",
        "--data_parallel", "1", "--stall_deadline_s", "0"])
    if rc != 0:
        return {"drill": "train", "ok": False,
                "error": f"train rc={rc}",
                "tail": "\n".join(out.splitlines()[-6:])}
    return _check_run("train", os.path.join(work, "runs", "trace"),
                      "train")


def drill_serve(work):
    run_dir = os.path.join(work, "loadtest")
    rc, out = _run([
        sys.executable, "-m", "raft_stereo_tpu.cli", "loadtest",
        "--run_dir", run_dir, "--no_baseline", "--no_progress",
        "--shapes", "48x96", "64x128",
        "--clients", "3", "--requests_per_client", "2",
        "--video_streams", "0", "--max_batch", "2", "--window", "2",
        "--iters", "1", "--hidden_dims", "32", "32", "32"])
    if rc != 0:
        return {"drill": "serve", "ok": False,
                "error": f"loadtest rc={rc}",
                "tail": "\n".join(out.splitlines()[-6:])}
    return _check_run("serve", os.path.join(run_dir, "serve"), "serve")


def main(argv=None):
    p = argparse.ArgumentParser(
        description="timeline/doctor rehearsal over real tiny runs "
                    "(see module doc)")
    p.add_argument("--keep-work", action="store_true",
                   help="keep the scratch tree (default: delete on exit)")
    args = p.parse_args(argv)

    from raft_stereo_tpu.obs.events import append_json_log

    os.makedirs(OUT, exist_ok=True)
    work = tempfile.mkdtemp(prefix="trace_drill_")
    t0 = time.monotonic()
    try:
        records = [drill_train(work), drill_serve(work)]
    finally:
        if args.keep_work:
            print(f"work tree kept: {work}")
        else:
            shutil.rmtree(work, ignore_errors=True)
    ok = True
    for rec in records:
        rec["wall_s"] = round(time.monotonic() - t0, 1)
        append_json_log(LOG, rec, stream=sys.stderr)
        ok = ok and rec["ok"]
    print(("TRACE DRILL ok: " if ok else "TRACE DRILL FAILED: ")
          + ", ".join(f"{r['drill']}={'ok' if r['ok'] else 'FAIL'}"
                      for r in records))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
