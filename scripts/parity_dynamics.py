#!/usr/bin/env python
"""Training-dynamics parity: torch reference vs this framework, side by side.

``parity_trained.py`` proves the FORWARD path at trained scale (train torch,
convert, compare inference). This script closes the remaining proxy for the
"EPE within 1%" acceptance criterion (BASELINE.md) that is closable without
the unreachable released weights: COMPOUNDING drift over optimization steps.
Optimizer and loss are unit-parity-tested in isolation; here the whole
training loop runs in both frameworks and the trajectories are compared:

1. Build ONE torch reference model; convert its *initial* state so both
   frameworks start from bit-identical weights (frozen BN, as the reference
   trains — train_stereo.py:151 ``freeze_bn``; our ``make_train_step`` holds
   ``batch_stats`` fixed by construction).
2. Pre-generate an identical synthetic data stream (known-GT warped pairs,
   scripts/parity_trained.py's generator) and run N AdamW+OneCycle steps in
   each framework with the reference recipe (train_stereo.py:35-79: adjusted
   gamma 0.9 sequence loss, lr 2e-4, wdecay 1e-5, eps 1e-8, OneCycle linear
   pct_start 0.01 over N+100, global-norm clip 1.0), fp32 on CPU.
3. Compare per-step loss trajectories (windowed means) and the final models'
   EPE on held-out pairs, each framework evaluating its OWN trained weights
   natively. GATE (the null-floor rule, VERDICT r5 weak #3): pass ⇔ the
   cross-framework deviation is within the measured SAME-framework floor —
   the ``--mode null`` run's JSON (torch trained twice from a
   1e-6-perturbed init) is taken as input (``--null``, default
   ``runs/parity_dynamics_null.json``) and both axes are gated against it:
   last-window loss deviation ≤ the null run's, final-EPE deviation ≤ the
   null run's. Two trainings of the same framework cannot be expected to
   land closer than that floor, so a cross-framework drift under it IS
   parity — machine-checked now, not narrated. Without a null JSON the
   gate falls back to the fixed ``--tolerance`` on the loss axis alone
   (the pre-r6 rule).

Run: python scripts/parity_dynamics.py --mode null   # chaos-floor yardstick
     python scripts/parity_dynamics.py [--steps 400] [--null runs/parity_dynamics_null.json]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from parity_trained import make_pair  # noqa: E402  (same synthetic generator)


def floor_gate(loss_rel, epe_rel, null_summary=None, tolerance=0.02):
    """The null-floor rule: pass ⇔ cross-framework deviation ≤ the measured
    same-framework floor, on BOTH axes the null run measured.

    ``null_summary`` is ``--mode null``'s JSON (``last_window_loss_rel`` +
    ``final_epe.rel_dev``). Returns ``{"pass", "rule", "checks"}`` where
    each check records the deviation, its floor, and the verdict; with no
    null summary the gate is the fixed loss tolerance (the pre-r6 rule).
    """
    if null_summary:
        checks = {}
        floor_loss = null_summary.get("last_window_loss_rel")
        if floor_loss is not None:
            checks["loss"] = {"deviation": loss_rel, "floor": floor_loss,
                              "ok": bool(loss_rel <= floor_loss)}
        floor_epe = (null_summary.get("final_epe") or {}).get("rel_dev")
        if floor_epe is not None and epe_rel is not None:
            checks["epe"] = {"deviation": epe_rel, "floor": floor_epe,
                             "ok": bool(epe_rel <= floor_epe)}
        if checks:
            return {"pass": all(c["ok"] for c in checks.values()),
                    "rule": "null_floor", "checks": checks}
    return {"pass": bool(loss_rel <= tolerance), "rule": "tolerance",
            "checks": {"loss": {"deviation": loss_rel, "floor": tolerance,
                                "ok": bool(loss_rel <= tolerance)}}}


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--reference_dir", default="/root/reference")
    p.add_argument("--steps", type=int, default=400)
    p.add_argument("--batch", type=int, default=2)
    p.add_argument("--train_size", type=int, nargs=2, default=[96, 192])
    p.add_argument("--train_iters", type=int, default=7)
    p.add_argument("--eval_size", type=int, nargs=2, default=[192, 384])
    p.add_argument("--eval_iters", type=int, default=16)
    p.add_argument("--eval_pairs", type=int, default=4)
    p.add_argument("--seed", type=int, default=23)
    p.add_argument("--window", type=int, default=50)
    p.add_argument("--tolerance", type=float, default=0.02)
    p.add_argument("--out", default="runs/parity_dynamics.json")
    p.add_argument("--mode", choices=["both", "null"], default="both",
                   help="'both' trains torch and jax side by side; 'null' "
                        "trains torch TWICE (the second from an init "
                        "perturbed by --perturb) on the same stream — the "
                        "measured chaos floor that bounds how close two "
                        "trainings of THE SAME framework can be expected "
                        "to land, the yardstick for the 'both' deviations")
    p.add_argument("--perturb", type=float, default=1e-6)
    p.add_argument("--null", default="runs/parity_dynamics_null.json",
                   help="the --mode null run's JSON (the measured "
                        "same-framework floor the gate compares against; "
                        "missing -> fixed --tolerance fallback)")
    args = p.parse_args()
    if args.mode == "null" and args.out == p.get_default("out"):
        # never clobber the cross-framework artifact with the null summary
        args.out = "runs/parity_dynamics_null.json"

    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import torch

    sys.path.insert(0, args.reference_dir)
    from core.raft_stereo import RAFTStereo as TorchRAFTStereo

    from raft_stereo_tpu.config import RAFTStereoConfig, TrainConfig
    from raft_stereo_tpu.models import create_model, init_model
    from raft_stereo_tpu.training.optim import fetch_optimizer
    from raft_stereo_tpu.training.state import TrainState, make_train_step
    from raft_stereo_tpu.utils.checkpoint_convert import (
        convert_state_dict, validate_against_variables)

    th, tw = args.train_size
    b, iters = args.batch, args.train_iters

    # --- identical init ----------------------------------------------------
    torch.manual_seed(args.seed)
    targs = argparse.Namespace(
        hidden_dims=[128, 128, 128], corr_implementation="reg",
        shared_backbone=False, corr_levels=4, corr_radius=4, n_downsample=2,
        context_norm="batch", slow_fast_gru=False, n_gru_layers=3,
        mixed_precision=False)
    tmodel = TorchRAFTStereo(targs)
    # fp32; refinement remat OFF: it is pure scheduling (gradients identical,
    # pinned by test_training.py's save-policy equivalence tests) and on the
    # XLA-CPU host this comparison runs on, paying the scan recompute makes
    # each step ~2x slower for zero numerical difference.
    cfg = RAFTStereoConfig(remat_refinement=False)
    model, variables = init_model(jax.random.PRNGKey(0), cfg, (1, th, tw, 3))
    converted = validate_against_variables(
        convert_state_dict(tmodel.state_dict()), variables)

    # --- identical data stream --------------------------------------------
    rng = np.random.default_rng(args.seed)
    print(f"pre-generating {args.steps} b{b} {th}x{tw} batches", flush=True)
    stream = []
    for _ in range(args.steps):
        pairs = [make_pair(rng, th, tw) for _ in range(b)]
        stream.append((
            np.stack([p[0] for p in pairs]),            # (B,H,W,3)
            np.stack([p[1] for p in pairs]),
            np.stack([-p[2] for p in pairs])[..., None],  # flow-x = -disp
        ))

    # --- torch training loop (reference recipe, train_stereo.py:150-196) ---
    def torch_train(model_, tag):
        model_.train()
        model_.freeze_bn()
        opt = torch.optim.AdamW(model_.parameters(), lr=2e-4,
                                weight_decay=1e-5, eps=1e-8)
        sched = torch.optim.lr_scheduler.OneCycleLR(
            opt, 2e-4, args.steps + 100, pct_start=0.01,
            cycle_momentum=False, anneal_strategy="linear")
        gamma_adj = 0.9 ** (15.0 / max(iters - 1, 1))
        losses = []
        t0 = time.time()
        for step, (i1, i2, f) in enumerate(stream):
            im1 = torch.from_numpy(i1.transpose(0, 3, 1, 2))
            im2 = torch.from_numpy(i2.transpose(0, 3, 1, 2))
            flow_gt = torch.from_numpy(f.transpose(0, 3, 1, 2))
            opt.zero_grad()
            preds = model_(im1, im2, iters=iters)
            # The reference sequence_loss masking (train_stereo.py:43-46):
            # valid pixels with |gt flow| < max_flow=700, per-iteration mean
            # over MASKED pixels only — the same normalization our jax
            # sequence_loss applies, so the trajectories being compared run
            # the same loss even if a synthetic pair ever exceeds max_flow.
            # (The generator has no invalid pixels, so valid is all-ones.)
            mask = (flow_gt.abs() < 700.0).float()
            denom = mask.sum().clamp(min=1.0)
            loss = sum((gamma_adj ** (len(preds) - 1 - i)) *
                       ((pr[:, :1] - flow_gt).abs() * mask).sum() / denom
                       for i, pr in enumerate(preds))
            loss.backward()
            torch.nn.utils.clip_grad_norm_(model_.parameters(), 1.0)
            opt.step()
            sched.step()
            losses.append(float(loss))
            if step % 25 == 0:
                print(f"{tag} step {step:4d} loss {losses[-1]:.4f} "
                      f"({time.time()-t0:.0f}s)", flush=True)
        model_.eval()
        return losses

    def torch_eval(model_, pairs):
        epes = []
        for i1, i2, d in pairs:
            with torch.no_grad():
                _, up = model_(torch.from_numpy(i1.transpose(2, 0, 1))[None],
                               torch.from_numpy(i2.transpose(2, 0, 1))[None],
                               iters=args.eval_iters, test_mode=True)
            epes.append(float(np.mean(np.abs(-up.numpy()[0, 0] - d))))
        return epes

    if args.mode == "null":
        # Chaos-floor measurement: the SAME framework trained twice from
        # inits differing by --perturb * N(0,1). Whatever deviation this
        # produces after the same stream is the noise floor against which
        # the torch-vs-jax numbers must be read — two fp32 trainings are
        # chaotic amplifiers, not reproducible functions.
        eh, ew = args.eval_size
        torch.manual_seed(args.seed)
        tmodel_b = TorchRAFTStereo(targs)  # bit-identical init
        g = torch.Generator().manual_seed(12345)
        with torch.no_grad():
            for p_ in tmodel_b.parameters():
                p_.add_(args.perturb *
                        torch.randn(p_.shape, generator=g))
        a_losses = torch_train(tmodel, "torch/a")
        b_losses = torch_train(tmodel_b, "torch/b")
        pairs = [make_pair(rng, eh, ew) for _ in range(args.eval_pairs)]
        a_epes, b_epes = torch_eval(tmodel, pairs), torch_eval(tmodel_b, pairs)
        a_arr, b_arr = np.asarray(a_losses), np.asarray(b_losses)
        last = slice(args.steps - args.window, args.steps)
        loss_rel = abs(b_arr[last].mean() - a_arr[last].mean()) / \
            max(a_arr[last].mean(), 1e-9)
        a_epe, b_epe = float(np.mean(a_epes)), float(np.mean(b_epes))
        epe_rel = abs(b_epe - a_epe) / max(a_epe, 1e-9)
        summary = {
            "mode": "null", "perturb": args.perturb, "steps": args.steps,
            "last_window_loss_rel": round(float(loss_rel), 5),
            "final_epe": {"a": round(a_epe, 5), "b": round(b_epe, 5),
                          "rel_dev": round(epe_rel, 5)},
            "a_epes": [round(x, 5) for x in a_epes],
            "b_epes": [round(x, 5) for x in b_epes],
        }
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as fh:
            json.dump(summary, fh, indent=1)
        print(f"\nCHAOS FLOOR (torch vs torch, perturb {args.perturb:g}): "
              f"final EPE a {a_epe:.4f} b {b_epe:.4f} "
              f"rel {100*epe_rel:.2f}%  last-window loss rel "
              f"{100*float(loss_rel):.2f}%", flush=True)
        return 0

    t_losses = torch_train(tmodel, "torch")

    # --- jax training loop (this framework's stack) -------------------------
    tcfg = TrainConfig(batch_size=b, train_iters=iters, lr=2e-4,
                       wdecay=1e-5, num_steps=args.steps,
                       image_size=(th, tw))
    tx = fetch_optimizer(tcfg)
    state = TrainState.create(converted, tx)
    step_fn = jax.jit(make_train_step(model, tx, iters))
    j_losses = []
    t0 = time.time()
    for step, (i1, i2, f) in enumerate(stream):
        batch = {"image1": jnp.asarray(i1), "image2": jnp.asarray(i2),
                 "flow": jnp.asarray(f),
                 "valid": jnp.ones((b, th, tw), jnp.float32)}
        state, metrics = step_fn(state, batch)
        j_losses.append(float(metrics["loss"]))
        if step % 25 == 0:
            print(f"jax   step {step:4d} loss {j_losses[-1]:.4f} "
                  f"({time.time()-t0:.0f}s)", flush=True)

    # --- compare trajectories ----------------------------------------------
    t_arr, j_arr = np.asarray(t_losses), np.asarray(j_losses)
    windows = []
    for s in range(0, args.steps, args.window):
        tm = float(t_arr[s:s + args.window].mean())
        jm = float(j_arr[s:s + args.window].mean())
        windows.append({"steps": [s, min(s + args.window, args.steps)],
                        "torch": round(tm, 5), "jax": round(jm, 5),
                        "rel_dev": round(abs(jm - tm) / max(tm, 1e-9), 5)})
        print(f"window {windows[-1]['steps']}: torch {tm:.4f} "
              f"jax {jm:.4f} rel {100*windows[-1]['rel_dev']:.2f}%",
              flush=True)

    # --- held-out EPE, each framework natively ------------------------------
    eh, ew = args.eval_size
    pairs = [make_pair(rng, eh, ew) for _ in range(args.eval_pairs)]
    t_epes = torch_eval(tmodel, pairs)
    j_epes = []
    for i, (i1, i2, d) in enumerate(pairs):
        _, j_up = model.apply(
            {"params": state.params, "batch_stats": state.batch_stats},
            jnp.asarray(i1)[None], jnp.asarray(i2)[None],
            iters=args.eval_iters, test_mode=True)
        j_epes.append(float(np.mean(np.abs(-np.asarray(j_up)[0, ..., 0] - d))))
        print(f"eval pair {i}: torch EPE {t_epes[i]:.4f} "
              f"jax EPE {j_epes[-1]:.4f}", flush=True)

    t_epe, j_epe = float(np.mean(t_epes)), float(np.mean(j_epes))
    epe_rel = abs(j_epe - t_epe) / max(t_epe, 1e-9)
    last_rel = windows[-1]["rel_dev"]
    # The GATE is the null-floor rule (floor_gate): cross-framework
    # deviation passes iff it is within what the SAME framework deviates
    # from a 1e-6-perturbed init on the same stream (--mode null's JSON) —
    # both the last-window loss axis and the chaos-dominated final-EPE
    # axis. The fixed --tolerance is only the fallback when no null run
    # has been measured.
    null_summary = None
    if args.null and os.path.exists(args.null):
        with open(args.null) as fh:
            null_summary = json.load(fh)
    gate = floor_gate(last_rel, epe_rel, null_summary, args.tolerance)
    summary = {
        "steps": args.steps, "batch": b, "train_size": [th, tw],
        "train_iters": iters, "windows": windows,
        "final_epe": {"torch": round(t_epe, 5), "jax": round(j_epe, 5),
                      "rel_dev": round(epe_rel, 5)},
        "eval": {"size": [eh, ew], "iters": args.eval_iters,
                 "pairs": args.eval_pairs},
        "torch_losses": [round(x, 5) for x in t_losses],
        "jax_losses": [round(x, 5) for x in j_losses],
        "gate": gate,
        "null_input": args.null if null_summary else None,
        "pass": gate["pass"],
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as fh:
        json.dump(summary, fh, indent=1)
    floors = "; ".join(
        f"{ax} {100 * c['deviation']:.2f}% vs floor {100 * c['floor']:.2f}%"
        for ax, c in gate["checks"].items())
    print(f"\nfinal EPE: torch {t_epe:.4f} jax {j_epe:.4f} "
          f"rel {100*epe_rel:.2f}%  last-window loss rel "
          f"{100*last_rel:.2f}%  -> "
          f"{'PASS' if summary['pass'] else 'FAIL'} "
          f"({gate['rule']}: {floors})", flush=True)
    return 0 if summary["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
