#!/usr/bin/env python
"""End-to-end numerical parity harness vs the PyTorch reference.

Builds the reference RAFTStereo (from --reference_dir, default
/root/reference) and this framework's model with IDENTICAL weights — either a
released ``.pth`` checkpoint or a seeded random torch init — runs both on the
same image pairs (random, or a left/right pair from disk), and reports the
deviation of the predicted disparities. This automates the "EPE within 1% of
the PyTorch/CUDA baseline" acceptance check (BASELINE.json) without needing
benchmark datasets on disk.

Usage:
  python scripts/parity_check.py                       # random weights+images
  python scripts/parity_check.py --restore_ckpt m.pth --iters 32
  python scripts/parity_check.py -l left.png -r right.png --restore_ckpt m.pth
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--reference_dir", default="/root/reference")
    parser.add_argument("--restore_ckpt", default=None, help=".pth weights")
    parser.add_argument("-l", "--left", default=None)
    parser.add_argument("-r", "--right", default=None)
    parser.add_argument("--iters", type=int, default=12)
    parser.add_argument("--size", type=int, nargs=2, default=[96, 160],
                        help="random-image H W (ignored with -l/-r)")
    parser.add_argument("--pairs", type=int, default=2)
    parser.add_argument("--seed", type=int, default=7)
    parser.add_argument("--tolerance", type=float, default=0.05,
                        help="max allowed mean |disparity| deviation (px)")
    from raft_stereo_tpu import cli
    cli.add_model_args(parser)
    args = parser.parse_args()

    import jax
    jax.config.update("jax_platforms", "cpu")  # bit-stable comparison target
    import torch

    sys.path.insert(0, args.reference_dir)
    from core.raft_stereo import RAFTStereo as TorchRAFTStereo

    from raft_stereo_tpu.models import init_model
    from raft_stereo_tpu.utils.checkpoint_convert import (
        convert_state_dict, load_reference_checkpoint,
        validate_against_variables)

    cfg = cli.model_config(args)
    targs = argparse.Namespace(
        hidden_dims=list(cfg.hidden_dims), corr_implementation="reg",
        shared_backbone=cfg.shared_backbone, corr_levels=cfg.corr_levels,
        corr_radius=cfg.corr_radius, n_downsample=cfg.n_downsample,
        context_norm=cfg.context_norm, slow_fast_gru=cfg.slow_fast_gru,
        n_gru_layers=cfg.n_gru_layers, mixed_precision=False)
    torch.manual_seed(args.seed)
    tmodel = TorchRAFTStereo(targs)
    if args.restore_ckpt:
        sd = torch.load(args.restore_ckpt, map_location="cpu")
        tmodel.load_state_dict(
            {k.replace("module.", ""): v for k, v in sd.items()})
    tmodel.eval()

    if args.restore_ckpt:
        converted = load_reference_checkpoint(args.restore_ckpt)
    else:
        converted = convert_state_dict(tmodel.state_dict())
    model, variables = init_model(jax.random.PRNGKey(0), cfg,
                                  (1, 64, 128, 3))
    converted = validate_against_variables(converted, variables)

    if args.left:
        from raft_stereo_tpu.data.frame_utils import read_image
        imgs = [(read_image(args.left)[None].astype(np.float32),
                 read_image(args.right)[None].astype(np.float32))]
    else:
        rng = np.random.default_rng(args.seed)
        h, w = args.size
        imgs = [(rng.uniform(0, 255, (1, h, w, 3)).astype(np.float32),
                 rng.uniform(0, 255, (1, h, w, 3)).astype(np.float32))
                for _ in range(args.pairs)]

    worst = 0.0
    for i, (im1, im2) in enumerate(imgs):
        with torch.no_grad():
            _, t_up = tmodel(torch.from_numpy(im1.transpose(0, 3, 1, 2)),
                             torch.from_numpy(im2.transpose(0, 3, 1, 2)),
                             iters=args.iters, test_mode=True)
        t_disp = -t_up.numpy()[:, 0]
        _, j_up = model.apply(converted, im1, im2, iters=args.iters,
                              test_mode=True)
        j_disp = -np.asarray(j_up)[..., 0]
        dev = np.abs(j_disp - t_disp)
        print(f"pair {i}: mean|Δdisp| {dev.mean():.5f}px  "
              f"max|Δdisp| {dev.max():.5f}px  "
              f"(torch range [{t_disp.min():.2f}, {t_disp.max():.2f}])")
        worst = max(worst, float(dev.mean()))

    if worst > args.tolerance:
        print(f"FAIL: mean deviation {worst:.5f} > {args.tolerance}")
        return 1
    print(f"PASS: all pairs within {args.tolerance}px mean deviation")
    return 0


if __name__ == "__main__":
    sys.exit(main())
