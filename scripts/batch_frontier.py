"""Measure the batch-scaling frontier past b8 (VERDICT r4 item 2, r5 #3).

PERF.md's ceiling argument rests on a ~450 ms batch-independent serial floor
fitted from b2/b4/b8 (r1); the floor amortizes with per-chip batch, and the
same linear model predicts ~12 pairs/s at b16 — but batch > 8 was never
measured. This walks b10/b12/b16 at the SceneFlow recipe shape on the real
chip, per batch trying the banker schedule first (hires-blocks remat + r4
best schedule), then the hires_frugal rung (blocks_hires remat with the
memory-frugal tail/budget defaults — the r8 addition probing whether bf16
volumes + a lighter graph reopen b12-b16 under the compile-shunt
threshold, VERDICT r5 weak #5), and finally the memory-frugal schedule
(remat_encoders=True + rematerialized loss tail + default chunk-on-pressure
upsample budget) when neither hires graph fits/compiles.

Correlation-volume storage dtype (VERDICT r5 #3): ``run_bench`` has pinned
``corr_storage_dtype="bfloat16"`` since r4 (commit 8aa95de), so every ladder
row — including the r5 b9-b16 ladder — already ran with the halved-residency
bf16 volume; the hypothesis that bf16 might reopen the >b8 lane was tested
the day the ladder ran, just not visibly. The dtype is now an explicit,
LOGGED kwarg on every row (``--dtypes``, default bfloat16), and passing
``--dtypes bfloat16 float32`` adds the fp32 contrast rows that bound what
the bf16 volume is actually buying at each batch.

Correlation implementation (r18): the ``fused`` rung reruns each batch's
ladder with the memoryless W2-blocked lookup (``--impls reg fused``,
default) — the b10-b16 rungs the materialized volume closed are exactly
what deleting its allocation class should reopen, so every row now logs
``corr_implementation`` and the fused rows ladder the same three schedules.
``--impls reg`` restores the pre-r18 ladder byte-for-byte.

Results append to runs/batch_frontier.log as dated JSON lines; attempts run
through bench.py's locked subprocess runner so they serialize with the
monolith prober and any driver bench run.

Run: python scripts/batch_frontier.py [--batches 10 12 16]
     [--dtypes bfloat16 float32] [--impls reg fused]
"""

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from bench import (  # noqa: E402
    FLAGSHIP_RECIPE, append_json_log, run_attempt_subprocess_detailed)
from raft_stereo_tpu.config import R4_BEST_SCHEDULE  # noqa: E402

LOG = os.path.join(REPO, "runs", "batch_frontier.log")
RECIPE = dict(fused_loss=True, **FLAGSHIP_RECIPE)


def _log(entry):
    append_json_log(LOG, entry)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batches", type=int, nargs="+", default=[10, 12, 16])
    p.add_argument("--dtypes", nargs="+", default=["bfloat16"],
                   choices=["bfloat16", "float32"],
                   help="corr-volume storage dtypes to ladder (bf16 is the "
                        "bench default; float32 adds the contrast row)")
    p.add_argument("--impls", nargs="+", default=["reg", "fused"],
                   choices=["reg", "fused", "alt", "reg_pallas",
                            "alt_pallas"],
                   help="correlation implementations to ladder: 'fused' is "
                        "the r18 memoryless rung (no B*H*W^2 volume class) "
                        "probing whether b10-b16 reopen; 'reg' alone "
                        "restores the pre-r18 ladder")
    p.add_argument("--timeout", type=float, default=1500.0)
    args = p.parse_args()

    banker = dict(remat_encoders="blocks_hires", **R4_BEST_SCHEDULE)
    # The VERDICT r5 weak-#5 rung: blocks_hires remat WITHOUT the r4 best
    # schedule's saved-tail/one-shot additions (rematerialized loss tail +
    # chunk-on-pressure budget stay at their memory-frugal defaults). The
    # r5 ladder only ran banker (shunted at b>=9 — its graph is over the
    # terminal's broken big-graph compile threshold) and full-encoder-remat
    # frugal; this middle point is the lightest graph that keeps the
    # hires-blocks encoder policy, the candidate for reopening b12-b16
    # with bf16 volumes under the shunt line.
    hires_frugal = dict(remat_encoders="blocks_hires")
    frugal = dict(remat_encoders=True)  # remat_loss_tail defaults True,
    # upsample_tile_budget defaults to chunk-on-pressure
    best = None
    for b in args.batches:
        for dtype in args.dtypes:
            for impl in args.impls:
                for name, sched in (("banker", banker),
                                    ("hires_frugal", hires_frugal),
                                    ("frugal", frugal)):
                    kw = dict(batch=b, corr_storage_dtype=dtype,
                              corr_implementation=impl, **sched, **RECIPE)
                    result, err, wall = run_attempt_subprocess_detailed(
                        kw, args.timeout)
                    # the attempt's compiled-artifact introspection
                    # (bench.py AOT path, obs/xla.py) rides every row:
                    # peak/temp bytes say WHY a batch stops fitting,
                    # flops/byte whether the ladder left the compute-bound
                    # regime
                    xla = (result or {}).get("xla") or {}
                    _log({"batch": b, "schedule": name,
                          "corr_storage_dtype": dtype,
                          "corr_implementation": impl,
                          "ok": result is not None,
                          "pairs_per_sec":
                              None if result is None else result["value"],
                          "xla_peak_bytes": xla.get("peak_bytes"),
                          "xla_temp_bytes": xla.get("temp_bytes"),
                          "xla_flops_per_byte": xla.get("flops_per_byte"),
                          "error": None if err is None else err[:300],
                          "wall_s": round(wall, 1)})
                    if result is not None:
                        if best is None or result["value"] > best[4]:
                            best = (b, name, dtype, impl, result["value"])
                        break  # heaviest fitting schedule wins per impl
    _log({"done": True,
          "best": None if best is None else
          {"batch": best[0], "schedule": best[1],
           "corr_storage_dtype": best[2], "corr_implementation": best[3],
           "pairs_per_sec": best[4]}})
    return 0


if __name__ == "__main__":
    sys.exit(main())
