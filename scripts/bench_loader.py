#!/usr/bin/env python
"""Host data-pipeline throughput: can the loader feed the device?

Synthesizes a FlyingThings3D-layout tree (SceneFlow-native 540x960 PNG pairs
+ PFM disparity), then times the REAL pipeline end-to-end — decode (PNG+PFM),
full FlowAugmentor with the SceneFlow recipe's augmentation params, crop to
320x720, threaded prefetch, fused uint8->f32 collate — exactly what
``fetch_dataloader`` builds for training (reference analog:
stereo_datasets.py:283-321 + DataLoader with SLURM_CPUS_PER_TASK-2 workers).

Prints pairs/sec overall plus a per-stage breakdown (decode vs augment vs
collate), and the key capacity figure: pairs/sec *per worker thread*, since
the loader scales ~linearly with cores until decode saturates memory
bandwidth. The acceptance question (VERDICT r1 #6) is whether the host
pipeline sustains >= 2x the device training rate.

Run: python scripts/bench_loader.py [--samples 64] [--batches 8] [--workers N]
"""

import argparse
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def synthesize_tree(root: str, n: int, h: int = 540, w: int = 960,
                    seed: int = 0) -> None:
    """FlyingThings3D TRAIN layout: <root>/FlyingThings3D/frames_cleanpass/
    TRAIN/A/0000/left|right/*.png + disparity PFMs."""
    from raft_stereo_tpu.data.frame_utils import write_pfm

    rng = np.random.default_rng(seed)
    try:
        import cv2

        def write_png(path, arr):
            cv2.imwrite(path, arr[..., ::-1])
    except ImportError:
        from PIL import Image

        def write_png(path, arr):
            Image.fromarray(arr).save(path)

    base = os.path.join(root, "FlyingThings3D")
    for i in range(n):
        scene = os.path.join("TRAIN", "A", f"{i:04d}")
        for sub in ("left", "right"):
            os.makedirs(os.path.join(base, "frames_cleanpass", scene, sub),
                        exist_ok=True)
        os.makedirs(os.path.join(base, "disparity", scene, "left"),
                    exist_ok=True)
        # low-frequency noise upsampled: realistic PNG compression load
        small = rng.integers(0, 255, (h // 8, w // 8, 3), dtype=np.uint8)
        img = np.kron(small, np.ones((8, 8, 1), np.uint8)).astype(np.int16)
        img = np.minimum(img + rng.integers(0, 17, img.shape, dtype=np.int16),
                         255).astype(np.uint8)
        for sub in ("left", "right"):
            write_png(os.path.join(base, "frames_cleanpass", scene, sub,
                                   "0006.png"), img)
        disp = rng.uniform(1.0, 64.0, (h, w)).astype(np.float32)
        write_pfm(os.path.join(base, "disparity", scene, "left", "0006.pfm"),
                  disp)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--samples", type=int, default=64)
    p.add_argument("--batches", type=int, default=8)
    p.add_argument("--batch_size", type=int, default=8)
    p.add_argument("--workers", type=int, default=os.cpu_count() or 1)
    p.add_argument("--keep_tree", default=None,
                   help="existing synthetic root to reuse (skips synthesis)")
    args = p.parse_args()

    from raft_stereo_tpu.config import sceneflow_config
    from raft_stereo_tpu.data.datasets import SceneFlow
    from raft_stereo_tpu.data.loader import Loader
    from raft_stereo_tpu.data import native

    _, tcfg = sceneflow_config()

    root = args.keep_tree or tempfile.mkdtemp(prefix="sf_synth_")
    try:
        if not args.keep_tree:
            t0 = time.time()
            synthesize_tree(root, args.samples)
            print(f"synthesized {args.samples} triples in "
                  f"{time.time()-t0:.1f}s at {root}")

        aug_params = {
            "crop_size": tuple(tcfg.image_size),
            "min_scale": tcfg.spatial_scale[0],
            "max_scale": tcfg.spatial_scale[1],
            "do_flip": tcfg.do_flip,
            "yjitter": not tcfg.noyjitter,
            "saturation_range": tuple(tcfg.saturation_range),
        }
        ds = SceneFlow(aug_params, root=root, dstype="frames_cleanpass")
        assert len(ds) == args.samples, (len(ds), args.samples)
        print(f"native collate available: {native.available()}")

        # per-stage: decode vs augment (single-thread, amortized)
        n_probe = min(8, len(ds))
        t0 = time.perf_counter()
        raws = [ds.read_raw(i) for i in range(n_probe)]
        t_decode = (time.perf_counter() - t0) / n_probe
        rng = np.random.default_rng(0)
        t0 = time.perf_counter()
        for i in range(n_probe):
            img1, img2, flow, valid = raws[i]
            ds.augmentor(img1, img2, flow, rng)
        t_aug = (time.perf_counter() - t0) / n_probe
        print(f"per-sample single-thread: decode {1e3*t_decode:.1f} ms, "
              f"augment {1e3*t_aug:.1f} ms "
              f"-> {1.0/(t_decode+t_aug):.2f} pairs/s/thread")

        loader = Loader(ds, batch_size=args.batch_size, seed=1234,
                        num_workers=args.workers, shuffle=True,
                        drop_last=True)
        # one warm epoch pass for page cache, then timed batches
        it = iter(loader)
        next(it)
        t0 = time.perf_counter()
        n = 0
        for _ in range(args.batches - 1):
            batch = next(it, None)
            if batch is None:
                it = iter(loader)
                batch = next(it)
            assert batch["image1"].shape == (
                args.batch_size, *tcfg.image_size, 3)
            assert batch["image1"].dtype == np.float32
            n += args.batch_size
        dt = time.perf_counter() - t0
        rate = n / dt
        print(f"loader end-to-end: {rate:.2f} pairs/s with "
              f"{args.workers} worker thread(s) "
              f"({rate/args.workers:.2f} pairs/s/worker)")
        print(f"capacity check: device rate R needs host >= 2R; at "
              f"{rate/args.workers:.2f}/worker this host config sustains "
              f"2x a {rate/2:.1f} pairs/s device")
    finally:
        if not args.keep_tree:
            shutil.rmtree(root, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
