#!/usr/bin/env python
"""Host data-pipeline throughput: can the loader feed the device?

Synthesizes a FlyingThings3D-layout tree (SceneFlow-native 540x960 PNG pairs
+ PFM disparity), then times the REAL pipeline end-to-end — decode (PNG+PFM),
full FlowAugmentor with the SceneFlow recipe's augmentation params, crop to
320x720, threaded prefetch, fused uint8->f32 collate — exactly what
``fetch_dataloader`` builds for training (reference analog:
stereo_datasets.py:283-321 + DataLoader with SLURM_CPUS_PER_TASK-2 workers).

Prints pairs/sec overall plus a per-stage breakdown (decode vs augment vs
collate), and the key capacity figure: pairs/sec *per worker thread*, since
the loader scales ~linearly with cores until decode saturates memory
bandwidth. The acceptance question (VERDICT r1 #6) is whether the host
pipeline sustains >= 2x the device training rate.

Run: python scripts/bench_loader.py [--samples 64] [--batches 8] [--workers N]
"""

import argparse
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def synthesize_tree(root: str, n: int, h: int = 540, w: int = 960,
                    seed: int = 0) -> None:
    """FlyingThings3D TRAIN layout: <root>/FlyingThings3D/frames_cleanpass/
    TRAIN/A/0000/left|right/*.png + disparity PFMs."""
    from raft_stereo_tpu.data.frame_utils import write_pfm

    rng = np.random.default_rng(seed)
    try:
        import cv2

        def write_png(path, arr):
            cv2.imwrite(path, arr[..., ::-1])
    except ImportError:
        from PIL import Image

        def write_png(path, arr):
            Image.fromarray(arr).save(path)

    base = os.path.join(root, "FlyingThings3D")
    for i in range(n):
        scene = os.path.join("TRAIN", "A", f"{i:04d}")
        for sub in ("left", "right"):
            os.makedirs(os.path.join(base, "frames_cleanpass", scene, sub),
                        exist_ok=True)
        os.makedirs(os.path.join(base, "disparity", scene, "left"),
                    exist_ok=True)
        # low-frequency noise upsampled: realistic PNG compression load
        small = rng.integers(0, 255, (h // 8, w // 8, 3), dtype=np.uint8)
        img = np.kron(small, np.ones((8, 8, 1), np.uint8)).astype(np.int16)
        img = np.minimum(img + rng.integers(0, 17, img.shape, dtype=np.int16),
                         255).astype(np.uint8)
        for sub in ("left", "right"):
            write_png(os.path.join(base, "frames_cleanpass", scene, sub,
                                   "0006.png"), img)
        disp = rng.uniform(1.0, 64.0, (h, w)).astype(np.float32)
        write_pfm(os.path.join(base, "disparity", scene, "left", "0006.pfm"),
                  disp)


def measure_gil_availability(work_fn, duration: float = 2.0) -> float:
    """Fraction of GIL time available to OTHER threads while ``work_fn`` loops
    in a worker thread.

    A prober thread counts trivial GIL-requiring ticks; the ratio of its rate
    with the worker active to its rate alone is ~0.5 on a single core when the
    worker's hot C kernels release the GIL (fair core split) and collapses
    toward 0 when the worker sits in LONG non-releasing C calls (the switch
    interval cannot preempt C code) — exactly the failure mode that would
    break multi-thread loader scaling. This is the measurable proxy for
    thread scaling on a 1-core sandbox, where N-thread aggregate throughput
    of CPU-bound work is flat regardless of the GIL.
    """
    import threading

    def tick_rate(stop_evt):
        n = 0
        t0 = time.perf_counter()
        while not stop_evt.is_set():
            for _ in range(1000):
                n += 1
        return n / (time.perf_counter() - t0)

    # baseline: prober alone
    stop = threading.Event()
    timer = threading.Timer(duration, stop.set)
    timer.start()
    alone = tick_rate(stop)

    # with the worker looping work_fn
    stop = threading.Event()

    def worker():
        while not stop.is_set():
            work_fn()

    w = threading.Thread(target=worker, daemon=True)
    w.start()
    timer = threading.Timer(duration, stop.set)
    timer.start()
    with_worker = tick_rate(stop)
    w.join(timeout=30)
    return with_worker / alone


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--samples", type=int, default=64)
    p.add_argument("--batches", type=int, default=8)
    p.add_argument("--batch_size", type=int, default=8)
    p.add_argument("--workers", type=int, default=os.cpu_count() or 1)
    p.add_argument("--sweep", default=None,
                   help="comma-separated worker counts to sweep, e.g. 1,2,4")
    p.add_argument("--gil_probe", action="store_true",
                   help="measure GIL availability during decode/augment")
    p.add_argument("--keep_tree", default=None,
                   help="existing synthetic root to reuse (skips synthesis)")
    args = p.parse_args()

    from raft_stereo_tpu.config import sceneflow_config
    from raft_stereo_tpu.data.datasets import SceneFlow
    from raft_stereo_tpu.data.loader import Loader
    from raft_stereo_tpu.data import native

    _, tcfg = sceneflow_config()

    root = args.keep_tree or tempfile.mkdtemp(prefix="sf_synth_")
    try:
        if not args.keep_tree:
            t0 = time.time()
            synthesize_tree(root, args.samples)
            print(f"synthesized {args.samples} triples in "
                  f"{time.time()-t0:.1f}s at {root}")

        aug_params = {
            "crop_size": tuple(tcfg.image_size),
            "min_scale": tcfg.spatial_scale[0],
            "max_scale": tcfg.spatial_scale[1],
            "do_flip": tcfg.do_flip,
            "yjitter": not tcfg.noyjitter,
            "saturation_range": tuple(tcfg.saturation_range),
        }
        ds = SceneFlow(aug_params, root=root, dstype="frames_cleanpass")
        assert len(ds) == args.samples, (len(ds), args.samples)
        print(f"native collate available: {native.available()}")

        # per-stage: decode vs augment (single-thread, amortized)
        n_probe = min(8, len(ds))
        t0 = time.perf_counter()
        raws = [ds.read_raw(i) for i in range(n_probe)]
        t_decode = (time.perf_counter() - t0) / n_probe
        rng = np.random.default_rng(0)
        t0 = time.perf_counter()
        for i in range(n_probe):
            img1, img2, flow, valid = raws[i]
            ds.augmentor(img1, img2, flow, rng)
        t_aug = (time.perf_counter() - t0) / n_probe
        print(f"per-sample single-thread: decode {1e3*t_decode:.1f} ms, "
              f"augment {1e3*t_aug:.1f} ms "
              f"-> {1.0/(t_decode+t_aug):.2f} pairs/s/thread")

        if args.gil_probe:
            # Direct evidence for the thread-scaling mechanism: do the hot
            # loops release the GIL during their C kernels?
            idx = [0]

            def decode_once():
                ds.read_raw(idx[0] % n_probe)
                idx[0] += 1

            aug_rng = np.random.default_rng(0)

            def augment_once():
                img1, img2, flow, valid = raws[idx[0] % n_probe]
                ds.augmentor(img1, img2, flow, aug_rng)
                idx[0] += 1

            for name, fn in (("decode", decode_once),
                             ("augment", augment_once)):
                avail = measure_gil_availability(fn)
                print(f"GIL availability during {name}: {avail:.2f} "
                      f"(~0.5 = hot C kernels release the GIL on this "
                      f"1-core box; ~0 = long non-releasing calls)")

        def run_loader(workers: int) -> float:
            loader = Loader(ds, batch_size=args.batch_size, seed=1234,
                            num_workers=workers, shuffle=True,
                            drop_last=True)
            # one warm batch for page cache / thread spin-up, then timed
            it = iter(loader)
            next(it)
            t0 = time.perf_counter()
            n = 0
            for _ in range(args.batches - 1):
                batch = next(it, None)
                if batch is None:
                    it = iter(loader)
                    batch = next(it)
                assert batch["image1"].shape == (
                    args.batch_size, *tcfg.image_size, 3)
                assert batch["image1"].dtype == np.float32
                n += args.batch_size
            return n / (time.perf_counter() - t0)

        counts = ([int(c) for c in args.sweep.split(",")] if args.sweep
                  else [args.workers])
        for workers in counts:
            rate = run_loader(workers)
            print(f"loader end-to-end: {rate:.2f} pairs/s with "
                  f"{workers} worker thread(s) "
                  f"({rate/workers:.2f} pairs/s/worker)")
            print(f"capacity check: device rate R needs host >= 2R; at "
                  f"{rate/workers:.2f}/worker this host config sustains "
                  f"2x a {rate/2:.1f} pairs/s device")
    finally:
        if not args.keep_tree:
            shutil.rmtree(root, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
