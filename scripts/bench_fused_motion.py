"""Standalone TPU benchmark of the fused lookup+motion kernel vs the XLA path.

Compares forward and forward+backward times at the SceneFlow train shape
(level-0 grid 80x180), kernel vs the unfused composition, and prints ms per
call. Also the quickest way to see whether Mosaic accepts the kernel's VMEM
footprint at a given row-block choice.
"""

import argparse
import time
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.nn.gru import BasicMotionEncoder
from raft_stereo_tpu.ops.corr import CorrState, _lookup_reg
from raft_stereo_tpu.ops.pallas.motion_kernels import (
    fused_corr_motion,
    fused_motion_applicable,
)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--h", type=int, default=80)
    p.add_argument("--w", type=int, default=180)
    p.add_argument("--vol_dtype", default="bfloat16")
    p.add_argument("--dt", default="bfloat16")
    p.add_argument("--steps", type=int, default=20)
    args = p.parse_args()

    vdt = jnp.dtype(args.vol_dtype)
    dt = jnp.dtype(args.dt)
    b, h, w = args.batch, args.h, args.w
    w2s = [w, w // 2, w // 4, w // 8]
    rng = np.random.default_rng(0)
    levels = tuple(jnp.asarray(rng.standard_normal((b, h, w, x)), vdt)
                   for x in w2s)
    coords = jnp.asarray(rng.uniform(0, w, (b, h, w)), jnp.float32)
    print("applicable:", fused_motion_applicable(levels, 4))

    kp = {
        "c1_k": jnp.asarray(rng.standard_normal((36, 64)) * .1, jnp.float32),
        "c1_b": jnp.zeros((64,), jnp.float32),
        "c2_k": jnp.asarray(rng.standard_normal((3, 3, 64, 64)) * .1,
                            jnp.float32),
        "c2_b": jnp.zeros((64,), jnp.float32),
        "f1_k": jnp.asarray(rng.standard_normal((49, 64)) * .1, jnp.float32),
        "f1_b": jnp.zeros((64,), jnp.float32),
        "f2_k": jnp.asarray(rng.standard_normal((3, 3, 64, 64)) * .1,
                            jnp.float32),
        "f2_b": jnp.zeros((64,), jnp.float32),
        "o_k": jnp.asarray(rng.standard_normal((3, 3, 128, 126)) * .1,
                           jnp.float32),
        "o_b": jnp.zeros((126,), jnp.float32),
    }
    flax_params = {
        "convc1": {"kernel": kp["c1_k"].reshape(1, 1, 36, 64),
                   "bias": kp["c1_b"]},
        "convc2": {"kernel": kp["c2_k"], "bias": kp["c2_b"]},
        "convf1": {"kernel": jnp.stack(
            [kp["f1_k"].reshape(7, 7, 64),
             jnp.zeros((7, 7, 64), jnp.float32)], axis=2),
            "bias": kp["f1_b"]},
        "convf2": {"kernel": kp["f2_k"], "bias": kp["f2_b"]},
        "conv": {"kernel": kp["o_k"], "bias": kp["o_b"]},
    }

    col = jnp.arange(w, dtype=jnp.float32)[None, None, :]
    enc = BasicMotionEncoder(RAFTStereoConfig(), dtype=dt)

    def xla_path(levels, coords, fp):
        state = CorrState(levels=levels, fmap1=None, impl="reg", radius=4)
        corr = _lookup_reg(state, coords).astype(dt)
        flow = jnp.stack([coords - col, jnp.zeros_like(coords)],
                         axis=-1).astype(dt)
        return enc.apply({"params": fp}, flow, corr)

    def kernel_path(levels, coords, kp):
        return fused_corr_motion(levels, coords, kp, 4, dt)

    probe = jnp.asarray(rng.standard_normal((b, h, w, 128)), jnp.float32)

    def timed(fn, *a):
        out = fn(*a)
        jax.block_until_ready(out)
        # sync via scalar fetch (tunneled-device quirk)
        float(jnp.sum(out if isinstance(out, jax.Array)
                      else jax.tree.leaves(out)[0]))
        t0 = time.perf_counter()
        for _ in range(args.steps):
            out = fn(*a)
        float(jnp.sum(out if isinstance(out, jax.Array)
                      else jax.tree.leaves(out)[0]))
        return (time.perf_counter() - t0) / args.steps * 1e3

    for name, fn, pp in (("xla", xla_path, flax_params),
                         ("kernel", kernel_path, kp)):
        fwd = jax.jit(fn)
        try:
            t = timed(fwd, levels, coords, pp)
            print(f"{name} fwd:      {t:8.3f} ms")
        except Exception as e:
            print(f"{name} fwd FAILED: {type(e).__name__} {str(e)[:200]}")
            continue

        def loss(levels, pp):
            return jnp.sum(fn(levels, coords, pp) * probe)

        bwd = jax.jit(jax.grad(loss, argnums=(0, 1)))
        try:
            t = timed(lambda l, p_: bwd(l, p_), levels, pp)
            print(f"{name} fwd+bwd:  {t:8.3f} ms")
        except Exception as e:
            print(f"{name} bwd FAILED: {type(e).__name__} {str(e)[:200]}")


if __name__ == "__main__":
    main()
