#!/bin/bash
# Fetch the released RAFT-Stereo checkpoints (raftstereo-{sceneflow,middlebury,
# eth3d,realtime}.pth, iraftstereo_rvc.pth). These are the reference's weights;
# the framework loads .pth directly via utils/checkpoint_convert.py.
set -e
mkdir -p models && cd models
wget https://www.dropbox.com/s/ftveifyqcomiwaq/models.zip
unzip -o models.zip && rm models.zip
