#!/usr/bin/env python
"""Structural evidence for the batched-weight-grad scan (PERF.md r8).

The custom-VJP refinement scan (ops/scan_grad.py) claims to replace the
autodiff backward's per-iteration weight-grad convolutions with post-scan
batched contractions, and to shrink the refinement save-stack allocation
class. This script produces the machine-readable artifacts for both claims,
on any backend (the jaxpr profile needs no compile at all):

* **op placement** — ``obs.xla.conv_op_profile`` over the jaxpr of the
  train-step gradient, custom VJP off vs on: convs per scan body (executed
  once per refinement iteration) vs outside any scan (executed once per
  step). The autodiff backward scan carries every gate-conv wgrad per
  iteration; the custom path's reverse scan must show FEWER convs per step
  while the outside count GROWS by the batched contractions.
* **memory** — ``memory_analysis()`` of the compiled step (off vs on, same
  shape), quantifying the residual-stack trade the custom path makes and
  what ``--residual_dtype bfloat16`` buys back.

Artifacts: dated ``op_counts``/``xla_memory`` events into
``<out>/events.jsonl`` (schema v3, linted by scripts/check_events.py) plus
one human-readable JSON summary on stdout and at ``<out>/summary.json``.

Run (CPU is fine): python scripts/scan_wgrad_evidence.py
     [--batch 1 --h 64 --w 96 --iters 8] [--no-compile]
     [--residual_dtype bfloat16] [--out runs/scan_grad_evidence]
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def build_grad_fn(cfg_kwargs, batch, h, w, iters):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from raft_stereo_tpu.config import RAFTStereoConfig
    from raft_stereo_tpu.models import init_model
    from raft_stereo_tpu.training.loss import loss_mask, sequence_loss_fused

    cfg = RAFTStereoConfig(**cfg_kwargs)
    model, variables = init_model(jax.random.PRNGKey(0), cfg, (1, h, w, 3))
    rng = np.random.default_rng(0)
    img1 = jnp.asarray(rng.uniform(0, 255, (batch, h, w, 3)), jnp.float32)
    img2 = jnp.asarray(rng.uniform(0, 255, (batch, h, w, 3)), jnp.float32)
    gt = jnp.asarray(rng.uniform(-8, 0, (batch, h, w, 1)), jnp.float32)
    mask = loss_mask(gt, jnp.ones((batch, h, w), jnp.float32))
    rest = {k: v for k, v in variables.items() if k != "params"}

    def loss(p):
        err, final = model.apply({"params": p, **rest}, img1, img2,
                                 iters=iters, flow_gt=gt, loss_mask=mask)
        return sequence_loss_fused(err, final, gt, mask)[0]

    return jax.grad(loss), variables["params"]


def profile_variant(name, cfg_kwargs, args, tel):
    import jax

    from raft_stereo_tpu.obs.xla import (conv_op_profile, emit_op_counts,
                                         introspect_compiled)

    grad_fn, params = build_grad_fn(cfg_kwargs, args.batch, args.h, args.w,
                                    args.iters)
    jaxpr = jax.make_jaxpr(grad_fn)(params)
    profile = conv_op_profile(jaxpr)
    rec = emit_op_counts(profile, tel, source=f"scan_wgrad_{name}",
                         extra={"variant": name, "iters": args.iters})
    out = {"variant": name, "op_profile": profile, **rec}
    if not args.no_compile:
        compiled = jax.jit(grad_fn).lower(params).compile()
        xla = introspect_compiled(compiled, tel,
                                  source=f"scan_wgrad_{name}",
                                  extra={"variant": name})
        out["memory"] = xla["memory"]
    return out


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--h", type=int, default=64)
    p.add_argument("--w", type=int, default=96)
    p.add_argument("--iters", type=int, default=8)
    p.add_argument("--residual_dtype", default=None,
                   choices=[None, "bfloat16", "float32"])
    p.add_argument("--save_policy", default=None,
                   help="refinement_save_policy override (true/false/corr)")
    p.add_argument("--no-compile", action="store_true",
                   help="jaxpr profile only (skip the memory_analysis "
                        "compile — the op-placement claim needs no XLA)")
    p.add_argument("--out", default=os.path.join(REPO, "runs",
                                                 "scan_grad_evidence"))
    args = p.parse_args(argv)

    from raft_stereo_tpu.obs import Telemetry
    tel = Telemetry(args.out, stall_deadline_s=None)
    tel.run_start(config=vars(args))

    policy = {"true": True, "false": False, "corr": "corr"}.get(
        str(args.save_policy).lower())
    base = dict(refinement_save_policy=policy,
                residual_dtype=args.residual_dtype)
    results = [
        profile_variant("autodiff", dict(base, batched_scan_wgrad=False),
                        args, tel),
        profile_variant("batched_wgrad", dict(base, batched_scan_wgrad=True),
                        args, tel),
    ]
    tel.emit("run_end", steps=0, ok=True)
    tel.close()

    # The headline comparison: per-step convs of the LAST scan (the
    # backward/reverse scan in both variants) and the outside-scan count.
    def last_scan(r):
        scans = r["op_profile"]["scans"]
        return scans[-1]["convs_per_step"] if scans else 0

    summary = {
        "shape": [args.batch, args.h, args.w], "iters": args.iters,
        "residual_dtype": args.residual_dtype,
        "save_policy": args.save_policy,
        "bwd_scan_convs_per_step": {r["variant"]: last_scan(r)
                                    for r in results},
        "convs_outside_scans": {r["variant"]:
                                r["op_profile"]["outside_scans"]
                                for r in results},
        "peak_bytes": {r["variant"]:
                       (r.get("memory") or {}).get("peak_bytes")
                       for r in results},
        "events": os.path.join(args.out, "events.jsonl"),
    }
    path = os.path.join(args.out, "summary.json")
    with open(path, "w") as f:
        json.dump({"summary": summary, "variants": results}, f, indent=1)
    print(json.dumps(summary, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
