"""Profile one train-step configuration and decompose scan vs fixed buckets.

Captures a ``jax.profiler`` trace of the SceneFlow-recipe training step and
splits device time into the refinement scans (the ``while`` ops: forward and
backward) and the fixed bucket (everything else: encoders fwd+bwd, volume
build, post-scan upsample/loss, optimizer), with per-op tops for each — the
measurement that drives PERF.md's "path to 20 pairs/s" plan.

Usage:
    python scripts/profile_step.py --batch 4 --steps 3
    python scripts/profile_step.py --batch 8 --remat_encoders blocks
"""

import argparse
import collections
import glob
import gzip
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from raft_stereo_tpu.config import RAFTStereoConfig, TrainConfig
from raft_stereo_tpu.models import init_model
from raft_stereo_tpu.training.optim import fetch_optimizer
from raft_stereo_tpu.training.state import TrainState, make_train_step
from raft_stereo_tpu.utils.profiling import trace


def load_events(log_dir):
    paths = sorted(glob.glob(
        os.path.join(log_dir, "plugins", "profile", "*", "*.trace.json.gz")))
    data = json.load(gzip.open(paths[-1], "rt"))
    events = data.get("traceEvents", [])
    device_pids, op_tids = set(), set()
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            if "/device:" in e.get("args", {}).get("name", ""):
                device_pids.add(e["pid"])
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            if e.get("args", {}).get("name") == "XLA Ops":
                op_tids.add((e["pid"], e["tid"]))
    out = []
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in device_pids:
            continue
        if op_tids and (e["pid"], e.get("tid")) not in op_tids:
            continue
        out.append(e)
    return out


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--h", type=int, default=320)
    p.add_argument("--w", type=int, default=720)
    p.add_argument("--iters", type=int, default=22)
    p.add_argument("--steps", type=int, default=3)
    p.add_argument("--stacked", action="store_true",
                   help="stacked-loss step instead of deferred-fused")
    p.add_argument("--remat_encoders", default=False,
                   help="False | True | blocks | blocks_hires | norms")
    p.add_argument("--corr", default="reg")
    p.add_argument("--top", type=int, default=14)
    p.add_argument("--logdir", default="/tmp/profile_step")
    p.add_argument("--best_schedule", action="store_true",
                   help="the r4-measured best schedule: one-shot post-scan "
                        "upsample + saved loss tail + unfolded saves "
                        "(bench.py banker)")
    p.add_argument("--run_dir", default=None,
                   help="also emit xla_memory/xla_cost introspection "
                        "events to <run_dir>/events.jsonl")
    args = p.parse_args()

    remat_enc = {"False": False, "True": True}.get(
        str(args.remat_encoders), args.remat_encoders)
    from raft_stereo_tpu.config import R4_BEST_SCHEDULE
    sched = dict(R4_BEST_SCHEDULE) if args.best_schedule else {}
    cfg = RAFTStereoConfig(mixed_precision=True,
                           corr_storage_dtype="bfloat16",
                           corr_implementation=args.corr,
                           remat_encoders=remat_enc, **sched)
    tcfg = TrainConfig(batch_size=args.batch, train_iters=args.iters,
                       num_steps=200000, image_size=(args.h, args.w))
    model, variables = init_model(jax.random.PRNGKey(0), cfg,
                                  (1, args.h, args.w, 3))
    tx = fetch_optimizer(tcfg)
    state = TrainState.create(variables, tx)
    rng = jax.random.PRNGKey(1)
    k1, k2, k3 = jax.random.split(rng, 3)
    batch = {
        "image1": jax.random.uniform(k1, (args.batch, args.h, args.w, 3),
                                     jnp.float32) * 255,
        "image2": jax.random.uniform(k2, (args.batch, args.h, args.w, 3),
                                     jnp.float32) * 255,
        "flow": -jax.random.uniform(k3, (args.batch, args.h, args.w, 1),
                                    jnp.float32) * 50,
        "valid": jnp.ones((args.batch, args.h, args.w), jnp.float32),
    }
    step_jit = jax.jit(make_train_step(model, tx, args.iters,
                                       fused_loss=not args.stacked),
                       donate_argnums=(0,))
    # AOT compile (same executable + cache key as the first jitted call) so
    # the profile carries the executable's memory/cost analyses alongside
    # the trace — what the step NEEDS, next to where its time GOES.
    from raft_stereo_tpu.obs.xla import introspect_compiled
    step = step_jit.lower(state, batch).compile()
    tel = None
    if args.run_dir:
        from raft_stereo_tpu.obs import Telemetry
        tel = Telemetry(args.run_dir, stall_deadline_s=None)
        tel.run_start(config=vars(args))
    analysis = introspect_compiled(step, tel, source="profile_step",
                                   extra={"batch": args.batch})
    mem, cost = analysis["memory"], analysis["cost"]
    if mem:
        gib = 1024 ** 3
        head = (f" (headroom {mem['headroom_bytes'] / gib:.2f} of "
                f"{mem['capacity_bytes'] / gib:.1f} GiB)"
                if "headroom_bytes" in mem else "")
        print(f"xla memory: peak {mem['peak_bytes'] / gib:.2f} GiB{head} — "
              f"args {mem.get('argument_bytes', 0) / gib:.2f}, "
              f"temps {mem.get('temp_bytes', 0) / gib:.2f}, "
              f"outputs {mem.get('output_bytes', 0) / gib:.2f} GiB")
    if cost:
        print(f"xla cost: {cost['flops']:.3g} flops, "
              f"{cost.get('bytes_accessed', 0):.3g} bytes accessed"
              + (f", {cost['flops_per_byte']} flops/byte"
                 if "flops_per_byte" in cost else ""))
    state, m = step(state, batch)
    float(m["loss"])
    state, m = step(state, batch)
    float(m["loss"])
    t0 = time.perf_counter()
    prev = None
    for _ in range(args.steps):
        state, m = step(state, batch)
        if prev is not None:
            float(prev["loss"])
        prev = m
    float(prev["loss"])
    wall = (time.perf_counter() - t0) / args.steps

    with trace(args.logdir):
        prev = None
        for _ in range(args.steps):
            state, m = step(state, batch)
            if prev is not None:
                float(prev["loss"])
            prev = m
        float(prev["loss"])

    events = load_events(args.logdir)
    whiles = [e for e in events
              if e.get("args", {}).get("hlo_category") == "while"]
    leaves = [e for e in events
              if e.get("args", {}).get("hlo_category") != "while"]
    n = args.steps

    spans = collections.defaultdict(float)
    for e in whiles:
        spans[e["name"]] += e["dur"]

    def containing_while(e):
        t = e["ts"]
        for w in whiles:
            if w["ts"] <= t and t + e.get("dur", 0) <= w["ts"] + w["dur"]:
                return w["name"]
        return None

    buckets = collections.defaultdict(
        lambda: (collections.Counter(), collections.Counter()))
    meta = {}
    for e in leaves:
        key = containing_while(e) or "fixed (outside scans)"
        t, c = buckets[key]
        t[e["name"]] += e["dur"]
        c[e["name"]] += 1
        if e["name"] not in meta:
            meta[e["name"]] = e.get("args", {}).get("long_name", "")[:110]

    total_leaf = sum(e["dur"] for e in leaves) / 1e3 / n
    print(f"wall/step: {wall * 1e3:.1f} ms   device-op total: "
          f"{total_leaf:.1f} ms/step   (batch {args.batch}, "
          f"{args.h}x{args.w}, iters {args.iters}, "
          f"{'stacked' if args.stacked else 'fused'}, "
          f"remat_enc={remat_enc})")
    print("\nwhile spans (scan fwd/bwd):")
    for name, dur in sorted(spans.items(), key=lambda kv: -kv[1]):
        print(f"  {dur / 1e3 / n:9.2f} ms/step  {name}")
    for key, (t, c) in sorted(buckets.items(),
                              key=lambda kv: -sum(kv[1][0].values())):
        print(f"\n{key}: {sum(t.values()) / 1e3 / n:.1f} ms/step")
        for name, dur in t.most_common(args.top):
            print(f"  {dur / 1e3 / n:9.2f} ms x{c[name] // n:<4d} "
                  f"{name[:40]:40s} {meta[name][:70]}")
    if tel is not None:
        tel.emit("run_end", steps=args.steps, ok=True)
        tel.close()


if __name__ == "__main__":
    main()
