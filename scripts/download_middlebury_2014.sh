#!/bin/bash
# Middlebury 2014 scenes (perfect + imperfect rectification), for the
# middlebury_2014 training mixture (datasets.py Middlebury split="2014").
set -e
mkdir -p datasets/Middlebury/2014
cd datasets/Middlebury/2014
scenes="Adirondack Backpack Bicycle1 Cable Classroom1 Couch Flowers Jadeplant
Mask Motorcycle Piano Pipes Playroom Playtable Recycle Shelves Shopvac Sticks
Storage Sword1 Sword2 Umbrella Vintage"
for s in $scenes; do
  for kind in perfect imperfect; do
    wget -nc https://vision.middlebury.edu/stereo/data/scenes2014/zip/$s-$kind.zip
    unzip -on $s-$kind.zip
  done
done
