"""Probe the remote-compile helper's failure boundary at the flagship shape.

The tunneled TPU's compile service has rejected every plain batch-8 train-step
graph since round 1 (HTTP 500, helper subprocess exit 1) while smaller or
remat-heavier graphs compile. This script compiles ISOLATED pieces of the
step at batch 8 to locate the boundary:

  1. encoders fwd+bwd only (full residuals, no remat),
  2. refinement scan + loss + grads only (encoder outputs as graph INPUTS),
  3. the full plain step (known-failing control).

If 1 and 2 compile while 3 fails, a split-compilation train step (encoder
piece + scan piece stitched through explicit residuals) can recover the
plain-b8 schedule the monolithic graph is denied.

Run: python scripts/probe_compile.py [--batch 8] [--pieces enc,scan,full]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from raft_stereo_tpu.config import RAFTStereoConfig, TrainConfig
from raft_stereo_tpu.models import init_model
from raft_stereo_tpu.training.loss import loss_mask, sequence_loss_fused
from raft_stereo_tpu.training.optim import fetch_optimizer
from raft_stereo_tpu.training.state import TrainState, make_train_step


def report(name, fn, *args):
    t0 = time.time()
    try:
        out = fn(*args)
        jax.block_until_ready(out)
        # fetch one scalar: tunneled devices can ack before execution ends
        leaf = jax.tree_util.tree_leaves(out)[0]
        np.asarray(jax.device_get(jax.tree.map(jnp.sum, leaf)))
        print(f"[probe] {name}: OK in {time.time()-t0:.1f}s")
        return True
    except Exception as e:
        print(f"[probe] {name}: FAIL in {time.time()-t0:.1f}s: "
              f"{type(e).__name__}: {str(e)[:200]}")
        return False


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--h", type=int, default=320)
    p.add_argument("--w", type=int, default=720)
    p.add_argument("--iters", type=int, default=22)
    p.add_argument("--pieces", default="enc,scan,full")
    args = p.parse_args()
    pieces = args.pieces.split(",")

    b, h, w = args.batch, args.h, args.w
    cfg = RAFTStereoConfig(mixed_precision=True,
                           corr_storage_dtype="bfloat16")
    tcfg = TrainConfig(batch_size=b, train_iters=args.iters,
                       num_steps=200000, image_size=(h, w))
    model, variables = init_model(jax.random.PRNGKey(0), cfg, (1, h, w, 3))
    tx = fetch_optimizer(tcfg)

    rng = np.random.default_rng(0)
    img1 = jnp.asarray(rng.uniform(0, 255, (b, h, w, 3)), jnp.float32)
    img2 = jnp.asarray(rng.uniform(0, 255, (b, h, w, 3)), jnp.float32)
    flow = jnp.asarray(rng.uniform(-64, 0, (b, h, w, 1)), jnp.float32)
    valid = jnp.ones((b, h, w), jnp.float32)

    if "enc" in pieces:
        # encoders fwd+bwd as one graph, full residuals (the piece plain-b8
        # saves that the remat fallbacks recompute)
        from raft_stereo_tpu.nn.encoder import BasicEncoder, MultiBasicEncoder

        cnet = MultiBasicEncoder(output_dim=(cfg.hidden_dims, cfg.hidden_dims),
                                 norm_fn=cfg.context_norm,
                                 downsample=cfg.n_downsample,
                                 dtype=jnp.bfloat16)
        fnet = BasicEncoder(output_dim=256, norm_fn="instance",
                            downsample=cfg.n_downsample, dtype=jnp.bfloat16)
        kc = jax.random.PRNGKey(1)
        cvars = cnet.init(kc, jnp.zeros((2, h, w, 3)), num_layers=3)
        fvars = fnet.init(kc, jnp.zeros((2, h, w, 3)))

        def enc_loss(cp, fp):
            outs = cnet.apply(cp, jnp.concatenate([img1, img1], 0) / 255.0,
                              num_layers=3)
            fmaps = fnet.apply(fp, jnp.concatenate([img1, img2], 0) / 255.0)
            s = sum(jnp.sum(jnp.abs(t.astype(jnp.float32)))
                    for lvl in outs for t in lvl)
            return s + jnp.sum(jnp.abs(fmaps.astype(jnp.float32)))

        report("encoders fwd+bwd b%d" % b,
               jax.jit(jax.grad(enc_loss, argnums=(0, 1))), cvars, fvars)

    if "scan" in pieces:
        # scan + loss + grads with the encoder outputs as INPUTS: the model
        # applied to precomputed fmaps/context is approximated by gradding
        # only the refinement/update params while encoders run under
        # stop_gradient — the backward graph then contains no encoder bwd.
        def scan_loss(refine_params, frozen_params):
            params = {**frozen_params, **refine_params}
            mask = loss_mask(flow, valid)
            err_sums, final = model.apply(
                {"params": params,
                 "batch_stats": variables.get("batch_stats", {})},
                img1, img2, iters=args.iters,
                flow_gt=flow, loss_mask=mask)
            return sequence_loss_fused(err_sums, final, flow, mask)[0]

        refine = {k: v for k, v in variables["params"].items()
                  if k in ("refinement",)}
        frozen = jax.lax.stop_gradient(
            {k: v for k, v in variables["params"].items()
             if k not in ("refinement",)})
        report("scan-only grads b%d" % b,
               jax.jit(jax.grad(scan_loss)), refine, frozen)

    if "full" in pieces:
        state = TrainState.create(variables, tx)
        step = jax.jit(make_train_step(model, tx, args.iters,
                                       fused_loss=True))
        batch = {"image1": img1, "image2": img2, "flow": flow, "valid": valid}
        report("full plain step b%d (control)" % b, step, state, batch)
    return 0


if __name__ == "__main__":
    sys.exit(main())
